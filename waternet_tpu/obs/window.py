"""Sliding-window metric primitives (docs/OBSERVABILITY.md "Windows & SLOs").

PR 13 gave the stack one trace and one metrics vocabulary, but every
quantile in serving/stats.py is computed over a since-process-start
reservoir: after warmup or an incident, the reported p99 is stale
history. These primitives answer "what is the p99 *right now*" with the
same design constraints as :mod:`waternet_tpu.obs.trace`:

* **Disabled means free.** Every ``record``/``add``/``set`` starts with
  one attribute load + bool check on the module switch and returns —
  no lock, no clock read. ``bench.py --config obs`` pins the armed cost.
* **Bounded memory.** A :class:`LogLinearHistogram` is a sparse dict of
  log-linear buckets (HDR-histogram style: linear sub-buckets inside
  each power-of-two octave, ≤ ~6% relative quantile error), O(1) per
  record. A :class:`WindowedHistogram` keeps a ring of per-shard
  histograms and forgets by overwriting stale shards — memory is
  O(shards × occupied buckets) forever, independent of load duration.
* **No threads of its own.** Shard rotation is lazy: whoever records or
  reads advances the ring against the injected ``clock``. Tests drive a
  fake clock, so window behavior is pinned without a single sleep.
* **Lock-light.** One plain ``threading.Lock`` per primitive; critical
  sections are a few arithmetic ops. Feeding code (ServingStats, the
  trainer loop) calls these OUTSIDE its own lock, so no new lock-order
  edges appear in the R102 graph.

One ring serves every window length: the ring spans the LONG window
(default 300 s in 10 s shards) and a read merges only the trailing
shards it needs, so the short (60 s) and long (300 s) views an SLO
burn-rate evaluation compares come from the same recorded data.
"""

from __future__ import annotations

import threading
import time
from math import frexp, inf
from typing import Dict, List, Optional, Tuple

#: Default short window: "current" latency/throughput, the /stats
#: ``latency_ms_window`` horizon and the fast SLO burn window.
DEFAULT_WINDOW_SEC = 60.0

#: Default long window = ring span: the sustained SLO burn window.
DEFAULT_LONG_WINDOW_SEC = 300.0

#: Default shard granularity: windows forget in steps of this.
DEFAULT_SHARD_SEC = 10.0

#: Linear sub-buckets per power-of-two octave. 16 bounds the quantile
#: upper-bound error at 1/16 of the octave width (~6% relative).
SUBBUCKETS = 16

#: frexp exponent clamp: 2**-21 .. 2**42 covers sub-microsecond
#: latencies in ms through HBM byte counts without index blowup.
_EMIN, _EMAX = -21, 42

#: Canonical Prometheus ``le`` ladder for latency histograms (ms).
DEFAULT_LE_MS = (
    1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0, 500.0, 1000.0, 2500.0,
)


class _Switch:
    """Module-wide arm/disarm for every window primitive.

    Mirrors trace.py's recorder flag: hot paths read ``_enabled``
    without the lock (a stale read merely drops or keeps one sample
    across the toggle edge); writes hold it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._enabled = True  # guarded-by: self._lock

    def enable(self) -> None:
        with self._lock:
            self._enabled = True

    def disable(self) -> None:
        with self._lock:
            self._enabled = False


#: Process-wide switch — windows are ON by default (unlike tracing, the
#: windowed quantiles are what /metrics reports, so they must be live on
#: an unconfigured server). bench.py's obs A/B disables them for its
#: "off" arm. Never reassigned.
_SWITCH = _Switch()


def enabled() -> bool:
    return _SWITCH._enabled


def enable() -> None:
    _SWITCH.enable()


def disable() -> None:
    _SWITCH.disable()


def bucket_index(value: float) -> int:
    """Log-linear bucket index of ``value`` — O(1), no search.

    ``frexp`` splits v = m * 2**e with m in [0.5, 1); the octave ``e``
    picks a run of :data:`SUBBUCKETS` linear buckets and the mantissa
    picks one. Values <= 0 land in bucket 0.
    """
    if value <= 0.0:
        return 0
    m, e = frexp(value)
    e = min(max(e, _EMIN), _EMAX)
    sub = int((2.0 * m - 1.0) * SUBBUCKETS)
    if sub >= SUBBUCKETS:  # m rounded up to 1.0 at float edge
        sub = SUBBUCKETS - 1
    return (e - _EMIN) * SUBBUCKETS + sub


def bucket_upper(idx: int) -> float:
    """Inclusive upper bound of bucket ``idx`` (its reported quantile)."""
    if idx <= 0:
        # Bucket 0 also absorbs <= 0 records; its honest upper bound is
        # the smallest representable bucket edge.
        idx = 0
    e = idx // SUBBUCKETS + _EMIN
    sub = idx % SUBBUCKETS
    return (0.5 + (sub + 1) / (2.0 * SUBBUCKETS)) * (2.0 ** e)


class LogLinearHistogram:
    """Sparse HDR-style histogram: O(1) record, mergeable, quantiles.

    NOT self-locked: instances live inside a locked owner (a
    :class:`WindowedHistogram` shard ring) or are short-lived merged
    snapshots owned by one reader thread.
    """

    __slots__ = ("counts", "count", "total", "vmin", "vmax")

    def __init__(self):
        self.counts: Dict[int, int] = {}
        self.count = 0
        self.total = 0.0
        self.vmin = inf
        self.vmax = -inf

    def record(self, value: float) -> None:
        idx = bucket_index(value)
        self.counts[idx] = self.counts.get(idx, 0) + 1
        self.count += 1
        self.total += value
        if value < self.vmin:
            self.vmin = value
        if value > self.vmax:
            self.vmax = value

    def merge(self, other: "LogLinearHistogram") -> None:
        for idx, n in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + n
        self.count += other.count
        self.total += other.total
        if other.vmin < self.vmin:
            self.vmin = other.vmin
        if other.vmax > self.vmax:
            self.vmax = other.vmax

    def clear(self) -> None:
        self.counts.clear()
        self.count = 0
        self.total = 0.0
        self.vmin = inf
        self.vmax = -inf

    def quantile(self, q: float) -> float:
        """Nearest-rank quantile as a bucket upper bound, clamped to the
        observed max (so single-bucket distributions report exactly)."""
        if self.count == 0:
            return 0.0
        rank = min(self.count - 1, max(0, int(round(q * (self.count - 1)))))
        seen = 0
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if seen > rank:
                return min(bucket_upper(idx), self.vmax)
        return self.vmax  # unreachable with count > 0

    def count_le(self, threshold: float) -> int:
        """Records known to be <= ``threshold``: full buckets whose upper
        bound fits (boundary-quantized, never over-counts a straddling
        bucket — an SLO "over threshold" count errs toward alarm)."""
        return sum(
            n for idx, n in self.counts.items()
            if bucket_upper(idx) <= threshold
        )

    def cumulative(self, bounds=DEFAULT_LE_MS) -> List[int]:
        """Cumulative counts at each of ``bounds`` — the Prometheus
        histogram ``le`` samples (the ``+Inf`` bucket is ``count``)."""
        out = []
        acc = 0
        items = sorted(self.counts.items())
        i = 0
        for le in bounds:
            while i < len(items) and bucket_upper(items[i][0]) <= le:
                acc += items[i][1]
                i += 1
            out.append(acc)
        return out


class WindowedHistogram:
    """A ring of per-shard histograms = a sliding-window histogram.

    The ring spans ``window_sec`` split into ``shards`` sub-windows;
    :meth:`merged` folds the trailing shards covering any window up to
    the ring span, so one instance serves both the short and the long
    SLO burn windows. Rotation is lazy against the injected ``clock`` —
    no threads, deterministic under a fake clock.
    """

    def __init__(
        self,
        window_sec: float = DEFAULT_LONG_WINDOW_SEC,
        shards: Optional[int] = None,
        clock=None,
    ):
        if shards is None:
            shards = max(1, int(round(window_sec / DEFAULT_SHARD_SEC)))
        if window_sec <= 0 or shards <= 0:
            raise ValueError("window_sec and shards must be positive")
        self.window_sec = float(window_sec)
        self.shards = int(shards)
        self.shard_sec = self.window_sec / self.shards
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        # Ring slot i holds [shard_epoch, histogram]; a slot whose epoch
        # is stale is cleared lazily on the next touch.
        self._ring: List[list] = [  # guarded-by: self._lock
            [-1, LogLinearHistogram()] for _ in range(self.shards)
        ]

    def _epoch(self, now: float) -> int:
        return int(now // self.shard_sec)

    # guarded-by: self._lock (callers hold it)
    def _shard(self, epoch: int) -> LogLinearHistogram:
        slot = self._ring[epoch % self.shards]
        if slot[0] != epoch:
            slot[0] = epoch
            slot[1].clear()
        return slot[1]

    def record(self, value: float) -> None:
        if not _SWITCH._enabled:
            return
        now = self._clock()
        with self._lock:
            self._shard(self._epoch(now)).record(float(value))

    def merged(self, window_sec: Optional[float] = None) -> LogLinearHistogram:
        """A fresh histogram folding the shards of the trailing window
        (default: the full ring span). Safe to read without further
        locking — the merge copies under the lock."""
        span = self.window_sec if window_sec is None else float(window_sec)
        k = max(1, min(self.shards, int(round(span / self.shard_sec))))
        out = LogLinearHistogram()
        now = self._clock()
        cur = self._epoch(now)
        with self._lock:
            for slot_epoch, hist in self._ring:
                if cur - k < slot_epoch <= cur:
                    out.merge(hist)
        return out

    def count(self, window_sec: Optional[float] = None) -> int:
        return self.merged(window_sec).count


class WindowedCounter:
    """Sliding-window event counter / rate (shed rate, error rate...).

    Same lazy shard ring as :class:`WindowedHistogram`, holding one
    float per shard.
    """

    def __init__(
        self,
        window_sec: float = DEFAULT_LONG_WINDOW_SEC,
        shards: Optional[int] = None,
        clock=None,
    ):
        if shards is None:
            shards = max(1, int(round(window_sec / DEFAULT_SHARD_SEC)))
        if window_sec <= 0 or shards <= 0:
            raise ValueError("window_sec and shards must be positive")
        self.window_sec = float(window_sec)
        self.shards = int(shards)
        self.shard_sec = self.window_sec / self.shards
        self._clock = clock if clock is not None else time.monotonic
        self._lock = threading.Lock()
        self._ring: List[list] = [  # guarded-by: self._lock
            [-1, 0.0] for _ in range(self.shards)
        ]

    def add(self, n: float = 1.0) -> None:
        if not _SWITCH._enabled:
            return
        now = self._clock()
        epoch = int(now // self.shard_sec)
        with self._lock:
            slot = self._ring[epoch % self.shards]
            if slot[0] != epoch:
                slot[0] = epoch
                slot[1] = 0.0
            slot[1] += n

    def total(self, window_sec: Optional[float] = None) -> float:
        span = self.window_sec if window_sec is None else float(window_sec)
        k = max(1, min(self.shards, int(round(span / self.shard_sec))))
        cur = int(self._clock() // self.shard_sec)
        with self._lock:
            return sum(
                v for epoch, v in self._ring if cur - k < epoch <= cur
            )

    def rate(self, window_sec: Optional[float] = None) -> float:
        """Events per second over the trailing window."""
        span = self.window_sec if window_sec is None else float(window_sec)
        span = min(span, self.window_sec)
        return self.total(span) / span if span > 0 else 0.0


class Gauge:
    """Last-value + peak gauge (HBM bytes, live MFU).

    ``set`` honors the module switch like every recorder; reads return
    ``None`` until the first set, so "never measured" (CPU hosts without
    ``memory_stats()``) stays distinguishable from 0.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._last: Optional[float] = None  # guarded-by: self._lock
        self._peak: Optional[float] = None  # guarded-by: self._lock

    def set(self, value: float) -> None:
        if not _SWITCH._enabled:
            return
        v = float(value)
        with self._lock:
            self._last = v
            if self._peak is None or v > self._peak:
                self._peak = v

    def last(self) -> Optional[float]:
        with self._lock:
            return self._last

    def peak(self) -> Optional[float]:
        with self._lock:
            return self._peak


def quantile_block(
    hist: LogLinearHistogram, quantiles=(0.50, 0.95, 0.99), digits: int = 3
) -> Dict[str, float]:
    """``{"p50": ..., "p95": ..., "p99": ..., "count": n}`` — the /stats
    windowed-quantile schema, shared by serving and the load generator."""
    out: Dict[str, float] = {
        f"p{int(q * 100)}": round(hist.quantile(q), digits)
        for q in quantiles
    }
    out["count"] = hist.count
    return out


def histogram_block(
    hist: LogLinearHistogram, bounds=DEFAULT_LE_MS
) -> Dict[str, object]:
    """The JSON form /metrics renders as a true Prometheus histogram:
    cumulative counts per ``le`` bound plus total count and sum."""
    return {
        "le": [float(b) for b in bounds],
        "cumulative": hist.cumulative(bounds),
        "count": hist.count,
        "sum": round(hist.total, 6),
    }
