"""SLO objectives, burn rates, and deterministic alert state machines.

The grammar (``--slo`` on the serving CLI, ``waternet-trace slo`` for
offline replay) is a comma-separated list of objectives:

    p99_ms<=250,error_rate<=0.01,availability>=0.999

Three objective kinds, each reduced to an **error budget** and a
**burn rate** over a window (docs/OBSERVABILITY.md "Windows & SLOs"):

``p<NN>_ms<=T``
    "At least NN% of requests complete within T ms." Budget is the
    allowed slow fraction ``1 - NN/100``; burn is (fraction of windowed
    requests over T) / budget. Burn 1.0 = slow requests arriving at
    exactly the rate the SLO tolerates.
``error_rate<=T``
    Budget is ``T`` itself; burn is windowed error fraction / T.
``availability>=Y``
    Budget is ``1 - Y``; burn is windowed unavailable fraction (errors
    plus sheds) / budget.

Burn is evaluated over TWO windows from the same shard ring (short
~60 s: "is it on fire now", long ~300 s: "is it sustained") and fed to
a per-objective state machine:

    ok --[long >= warn_burn, or short >= page_burn]--> warn
    warn --[short >= page_burn AND long >= warn_burn]--> page
    page/warn --[condition clear for hold_sec]--> one level down

Escalation is immediate; de-escalation requires the triggering
condition to stay false for ``hold_sec`` so a flapping signal cannot
ping-pong the grade. All time comes from the caller (``now``
arguments) — tests and the CLI replay drive a fake clock, no sleeps.

Pure stdlib; imported by ``waternet-trace`` so it must never pull jax.
"""

from __future__ import annotations

import re
import threading
from typing import Dict, List, Optional, Sequence, Tuple

from waternet_tpu.obs import window as obswin

#: Burn thresholds and de-escalation hold, shared defaults.
WARN_BURN = 1.0
PAGE_BURN = 2.0
HOLD_SEC = 60.0

_STATES = ("ok", "warn", "page")

_P_RE = re.compile(r"^p(\d{1,2})_ms<=([0-9.]+)$")
_ERR_RE = re.compile(r"^error_rate<=([0-9.]+)$")
_AVAIL_RE = re.compile(r"^availability>=([0-9.]+)$")


class SloObjective:
    """One parsed objective: a kind, a threshold, and an error budget."""

    __slots__ = ("name", "kind", "threshold", "budget", "quantile")

    def __init__(self, name: str, kind: str, threshold: float,
                 budget: float, quantile: Optional[float] = None):
        if budget <= 0.0:
            raise ValueError(
                f"SLO objective {name!r} has zero error budget — "
                "a 100% target cannot be burn-rated"
            )
        self.name = name
        self.kind = kind
        self.threshold = threshold
        self.budget = budget
        self.quantile = quantile

    def burn(self, hist: "obswin.LogLinearHistogram",
             ok: float, errors: float, shed: float) -> float:
        """Burn rate of this objective over one window's observations.

        Empty windows burn 0 — no traffic is not an outage (the
        liveness question belongs to /healthz replica probes).
        """
        if self.kind == "latency":
            n = hist.count
            if n == 0:
                return 0.0
            slow = n - hist.count_le(self.threshold)
            return (slow / n) / self.budget
        total = ok + errors + shed
        if total <= 0:
            return 0.0
        if self.kind == "error_rate":
            return (errors / total) / self.budget
        # availability: anything that did not complete counts against it
        return ((errors + shed) / total) / self.budget


def parse_slo(spec: str) -> List[SloObjective]:
    """Parse a ``--slo`` spec string into objectives. Raises ValueError
    with the offending clause on any syntax error."""
    objectives: List[SloObjective] = []
    for raw in spec.split(","):
        clause = raw.strip()
        if not clause:
            continue
        m = _P_RE.match(clause)
        if m:
            nn = int(m.group(1))
            if not 1 <= nn <= 99:
                raise ValueError(f"SLO quantile out of range in {clause!r}")
            objectives.append(SloObjective(
                clause, "latency", float(m.group(2)),
                budget=1.0 - nn / 100.0, quantile=nn / 100.0,
            ))
            continue
        m = _ERR_RE.match(clause)
        if m:
            objectives.append(SloObjective(
                clause, "error_rate", float(m.group(1)),
                budget=float(m.group(1)),
            ))
            continue
        m = _AVAIL_RE.match(clause)
        if m:
            y = float(m.group(1))
            if not 0.0 < y < 1.0:
                raise ValueError(
                    f"availability target must be in (0, 1) in {clause!r}")
            objectives.append(SloObjective(
                clause, "availability", y, budget=1.0 - y,
            ))
            continue
        raise ValueError(
            f"unrecognized SLO clause {clause!r} "
            "(expected pNN_ms<=T, error_rate<=T, or availability>=Y)"
        )
    if not objectives:
        raise ValueError(f"empty SLO spec: {spec!r}")
    return objectives


class _ObjectiveState:
    """Deterministic per-objective alert state machine.

    NOT self-locked — owned and driven under :class:`SloEngine`'s lock.
    """

    __slots__ = ("state", "since", "_clear_since")

    def __init__(self):
        self.state = "ok"
        self.since = None  # entered-current-state timestamp
        self._clear_since = None  # condition-false-since, for hold_sec

    def step(self, now: float, short_burn: float, long_burn: float,
             hold_sec: float) -> Optional[Tuple[str, str]]:
        """Advance one evaluation; returns (old, new) on transition."""
        page_cond = short_burn >= PAGE_BURN and long_burn >= WARN_BURN
        warn_cond = long_burn >= WARN_BURN or short_burn >= PAGE_BURN
        target = "page" if page_cond else ("warn" if warn_cond else "ok")
        old = self.state
        if self.since is None:
            self.since = now
        if _STATES.index(target) > _STATES.index(old):
            # escalate immediately (and restart any de-escalation hold)
            self.state = target
            self.since = now
            self._clear_since = None
            return (old, target)
        # current level's own trigger: does this level still justify itself?
        held = page_cond if old == "page" else warn_cond
        if old == "ok" or held:
            self._clear_since = None
            return None
        if self._clear_since is None:
            self._clear_since = now
        if now - self._clear_since >= hold_sec:
            # drop exactly one level; re-arm the hold for the next drop
            self.state = _STATES[_STATES.index(old) - 1]
            self.since = now
            self._clear_since = now
            return (old, self.state)
        return None


class WindowSample:
    """One window's worth of observations handed to the engine."""

    __slots__ = ("hist", "ok", "errors", "shed")

    def __init__(self, hist: "obswin.LogLinearHistogram",
                 ok: float = 0.0, errors: float = 0.0, shed: float = 0.0):
        self.hist = hist
        self.ok = ok
        self.errors = errors
        self.shed = shed


class SloEngine:
    """Evaluates objectives against short/long window samples and keeps
    the per-objective alert state machines."""

    def __init__(self, objectives: Sequence[SloObjective], *,
                 spec: Optional[str] = None,
                 short_sec: float = obswin.DEFAULT_WINDOW_SEC,
                 long_sec: float = obswin.DEFAULT_LONG_WINDOW_SEC,
                 hold_sec: float = HOLD_SEC):
        self.objectives = list(objectives)
        self.spec = spec if spec is not None else ",".join(
            o.name for o in self.objectives)
        self.short_sec = float(short_sec)
        self.long_sec = float(long_sec)
        self.hold_sec = float(hold_sec)
        self._lock = threading.Lock()
        self._states = {  # guarded-by: self._lock
            o.name: _ObjectiveState() for o in self.objectives
        }

    def evaluate(self, now: float, short: WindowSample,
                 long: WindowSample) -> Dict[str, object]:
        """Advance every state machine one tick and return the ``slo``
        block /stats publishes."""
        rows = []
        transitions = []
        with self._lock:
            for obj in self.objectives:
                sb = obj.burn(short.hist, short.ok, short.errors, short.shed)
                lb = obj.burn(long.hist, long.ok, long.errors, long.shed)
                st = self._states[obj.name]
                tr = st.step(now, sb, lb, self.hold_sec)
                if tr is not None:
                    transitions.append(
                        {"objective": obj.name, "from": tr[0], "to": tr[1],
                         "at": round(now, 3)})
                rows.append({
                    "objective": obj.name,
                    "kind": obj.kind,
                    "threshold": obj.threshold,
                    "budget": round(obj.budget, 6),
                    "short_burn": round(sb, 4),
                    "long_burn": round(lb, 4),
                    "state": st.state,
                    "since": round(st.since, 3) if st.since is not None else None,
                })
            worst = max(
                (r["state"] for r in rows), key=_STATES.index, default="ok")
        return {
            "spec": self.spec,
            "grade": "degraded" if worst == "page" else "ok",
            "state": worst,
            "window_sec": self.short_sec,
            "long_window_sec": self.long_sec,
            "objectives": rows,
            "transitions": transitions,
        }

    def state(self) -> str:
        with self._lock:
            return max(
                (s.state for s in self._states.values()),
                key=_STATES.index, default="ok")


def replay_ledger(
    entries: Sequence[dict],
    objectives: Sequence[SloObjective],
    *,
    step_sec: float = 1.0,
    short_sec: float = obswin.DEFAULT_WINDOW_SEC,
    long_sec: float = obswin.DEFAULT_LONG_WINDOW_SEC,
    hold_sec: float = HOLD_SEC,
) -> Tuple[List[dict], Dict[str, object]]:
    """Replay a loadgen/bench ledger offline through the same windows
    and state machines the live server runs.

    Each entry: ``{"t": seconds, "latency_ms": float, "outcome": str}``
    with outcome one of ``ok`` / ``error`` / ``shed``. Entries are fed
    in time order against a fake clock; the engine is stepped every
    ``step_sec`` of ledger time. Returns (all transitions, final block).
    """
    fake = [0.0]

    def clock() -> float:
        return fake[0]

    hist = obswin.WindowedHistogram(window_sec=long_sec, clock=clock)
    counters = {
        k: obswin.WindowedCounter(window_sec=long_sec, clock=clock)
        for k in ("ok", "errors", "shed")
    }
    engine = SloEngine(objectives, short_sec=short_sec, long_sec=long_sec,
                       hold_sec=hold_sec)

    def sample(span: float) -> WindowSample:
        return WindowSample(
            hist.merged(span),
            ok=counters["ok"].total(span),
            errors=counters["errors"].total(span),
            shed=counters["shed"].total(span),
        )

    def tick() -> Dict[str, object]:
        block = engine.evaluate(fake[0], sample(short_sec), sample(long_sec))
        transitions.extend(block["transitions"])
        return block

    ordered = sorted(entries, key=lambda e: float(e.get("t", 0.0)))
    transitions: List[dict] = []
    block: Dict[str, object] = {}
    next_eval = step_sec
    for e in ordered:
        t = float(e.get("t", 0.0))
        while t >= next_eval:
            fake[0] = next_eval
            block = tick()
            next_eval += step_sec
        fake[0] = t
        outcome = str(e.get("outcome", "ok"))
        if outcome == "ok":
            counters["ok"].add(1)
            if e.get("latency_ms") is not None:
                hist.record(float(e["latency_ms"]))
        elif outcome == "shed":
            counters["shed"].add(1)
        else:
            counters["errors"].add(1)
    # One final evaluation at the first step boundary past the last
    # entry: the reported state is the state AT THE END OF THE RUN. A
    # run that ends while still paging must report page (that is the
    # CLI's rc 1) — running the clock further would let every alert
    # quietly de-escalate and hide the ending.
    end = (float(ordered[-1].get("t", 0.0)) if ordered else 0.0) + step_sec
    while next_eval <= end:
        fake[0] = next_eval
        block = tick()
        next_eval += step_sec
    return transitions, block
