"""Serving observability: latency percentiles, occupancy, padding, compiles.

One :class:`ServingStats` instance rides a batcher for its whole life;
every number it reports is also a bench contract field
(``mixed_res_dir_images_per_sec``, bench.py) and the CLI's end-of-run
JSON stats block — the schema is documented in docs/SERVING.md and
pinned by tests/test_serving.py.
"""

from __future__ import annotations

import json
import random
import threading
from typing import Dict, List

#: Latency reservoir size: percentiles are computed over at most this many
#: uniformly-sampled requests (algorithm R), so a long-lived server's
#: stats stay O(1) memory instead of one float per request forever.
LATENCY_RESERVOIR = 65536


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list (0 when empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class ServingStats:
    """Thread-safe accumulators for the serving layer.

    * per-request **latency** (submit -> result ready), reported as
      p50/p95/p99 milliseconds;
    * **queue depth** observed by the dispatcher at each batch launch;
    * **batch occupancy**: real requests / device batch slots (padding
      a partial batch up to the compiled batch size keeps the executable
      count bounded but burns slots — occupancy is that cost);
    * **padding overhead**: 1 - real pixels / padded-canvas pixels
      (the price of serving a shape from a bucket larger than it);
    * **compiles**: executables built (warmup) + any mid-serve fallback
      compile (a native-shape forward for an oversize request). A
      mid-serve compile for a *bucketed* request is a bug — the
      compile-sentinel test pins that it never happens.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._latencies_s: List[float] = []  # bounded reservoir sample
        self._reservoir_rng = random.Random(0)
        self.requests = 0
        self.batches = 0
        self.real_slots = 0
        self.total_slots = 0
        self.real_px = 0
        self.padded_px = 0
        self.compiles = 0
        self.fallback_native = 0
        self._depth_sum = 0
        self.depth_max = 0

    def record_latency(self, seconds: float) -> None:
        with self._lock:
            self.requests += 1
            if len(self._latencies_s) < LATENCY_RESERVOIR:
                self._latencies_s.append(seconds)
            else:
                # Algorithm R: every request keeps an equal chance of
                # being in the sample, at O(1) memory for server
                # lifetimes of any length.
                j = self._reservoir_rng.randrange(self.requests)
                if j < LATENCY_RESERVOIR:
                    self._latencies_s[j] = seconds

    def record_batch(
        self, n_real: int, n_slots: int, real_px: int, padded_px: int,
        queue_depth: int = 0,
    ) -> None:
        with self._lock:
            self.batches += 1
            self.real_slots += n_real
            self.total_slots += n_slots
            self.real_px += real_px
            self.padded_px += padded_px
            self._depth_sum += queue_depth
            self.depth_max = max(self.depth_max, queue_depth)

    def record_compile(self, n: int = 1) -> None:
        with self._lock:
            self.compiles += n

    def record_fallback(self) -> None:
        with self._lock:
            self.fallback_native += 1

    def occupancy(self) -> float:
        with self._lock:
            return self.real_slots / self.total_slots if self.total_slots else 0.0

    def padding_overhead(self) -> float:
        with self._lock:
            return 1.0 - self.real_px / self.padded_px if self.padded_px else 0.0

    def latency_ms(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._latencies_s)
        return {
            "p50": round(_percentile(vals, 0.50) * 1e3, 3),
            "p95": round(_percentile(vals, 0.95) * 1e3, 3),
            "p99": round(_percentile(vals, 0.99) * 1e3, 3),
        }

    def summary(self) -> dict:
        """The JSON stats block (docs/SERVING.md schema)."""
        with self._lock:
            batches = self.batches
            depth_mean = self._depth_sum / batches if batches else 0.0
            depth_max = self.depth_max
            requests = self.requests
            compiles = self.compiles
            fallback = self.fallback_native
        return {
            "requests": requests,
            "batches": batches,
            "latency_ms": self.latency_ms(),
            "batch_occupancy": round(self.occupancy(), 4),
            "padding_overhead": round(self.padding_overhead(), 4),
            "compiles": compiles,
            "fallback_native_shapes": fallback,
            "queue_depth_mean": round(depth_mean, 2),
            "queue_depth_max": depth_max,
        }

    def to_json(self) -> str:
        return json.dumps({"serving_stats": self.summary()})
