"""Serving observability: latency percentiles, occupancy, padding, compiles.

One :class:`ServingStats` instance rides a batcher for its whole life;
every number it reports is also a bench contract field
(``mixed_res_dir_images_per_sec``, bench.py) and the CLI's end-of-run
JSON stats block — the schema is documented in docs/SERVING.md and
pinned by tests/test_serving.py.
"""

from __future__ import annotations

import json
import random
import threading
import time
from typing import Dict, List, Optional

from waternet_tpu.obs import window as obswin
from waternet_tpu.obs.slo import SloEngine, WindowSample
from waternet_tpu.analysis.looptrace import empty_loop_lag_block
from waternet_tpu.serving.reuse import empty_cache_block

#: Latency reservoir size: percentiles are computed over at most this many
#: uniformly-sampled requests (algorithm R), so a long-lived server's
#: stats stay O(1) memory instead of one float per request forever.
LATENCY_RESERVOIR = 65536


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile on an already-sorted list (0 when empty)."""
    if not sorted_vals:
        return 0.0
    idx = min(len(sorted_vals) - 1, max(0, int(round(q * (len(sorted_vals) - 1)))))
    return sorted_vals[idx]


class _ServingWindows:
    """The sliding-window view of one ServingStats instance.

    Every primitive here is self-locked (obs/window.py); the only state
    this class guards itself is the grow-only per-tier histogram dict.
    ServingStats feeds these OUTSIDE its own ``_lock`` so no
    stats-lock -> window-lock edge enters the lock-order graph.
    """

    def __init__(self, clock=None):
        self._clock = clock if clock is not None else time.monotonic
        span = obswin.DEFAULT_LONG_WINDOW_SEC
        # Latencies recorded in MILLISECONDS — the unit every quantile,
        # le-bucket, and SLO threshold in the schema speaks.
        self.latency = obswin.WindowedHistogram(span, clock=clock)
        self.queue_depth = obswin.WindowedHistogram(span, clock=clock)
        self.stream_frame = obswin.WindowedHistogram(span, clock=clock)
        self.ok = obswin.WindowedCounter(span, clock=clock)
        self.errors = obswin.WindowedCounter(span, clock=clock)
        self.shed = obswin.WindowedCounter(span, clock=clock)
        self._lock = threading.Lock()
        self._tier_latency: Dict[str, obswin.WindowedHistogram] = {}  # guarded-by: self._lock

    def now(self) -> float:
        return self._clock()

    def tier_hist(self, tier: str) -> obswin.WindowedHistogram:
        with self._lock:
            hist = self._tier_latency.get(tier)
            if hist is None:
                hist = obswin.WindowedHistogram(
                    obswin.DEFAULT_LONG_WINDOW_SEC, clock=self._clock)
                self._tier_latency[tier] = hist
        return hist

    def tier_names(self) -> List[str]:
        with self._lock:
            return sorted(self._tier_latency)

    def sample(self, span: float) -> WindowSample:
        """One window's observations in SLO-engine form."""
        return WindowSample(
            self.latency.merged(span),
            ok=self.ok.total(span),
            errors=self.errors.total(span),
            shed=self.shed.total(span),
        )

    def block(self) -> dict:
        """The ``window`` block of /stats: current-traffic quantiles and
        rates over the short window, sustained quantiles over the long,
        plus the raw le-ladder /metrics renders as a true histogram."""
        short = obswin.DEFAULT_WINDOW_SEC
        lat_short = self.latency.merged(short)
        lat_long = self.latency.merged()
        total = (self.ok.total(short) + self.errors.total(short)
                 + self.shed.total(short))
        return {
            "window_sec": short,
            "long_window_sec": obswin.DEFAULT_LONG_WINDOW_SEC,
            "latency_ms": obswin.quantile_block(lat_short),
            "latency_ms_long": obswin.quantile_block(lat_long),
            "latency_hist_ms": obswin.histogram_block(lat_long),
            "tiers": {
                t: obswin.quantile_block(self.tier_hist(t).merged(short))
                for t in self.tier_names()
            },
            "queue_depth": obswin.quantile_block(
                self.queue_depth.merged(short), digits=1),
            "stream_frame_ms": obswin.quantile_block(
                self.stream_frame.merged(short)),
            "requests_per_sec": round(self.ok.rate(short), 3),
            "shed_per_sec": round(self.shed.rate(short), 3),
            "error_rate": round(
                self.errors.total(short) / total, 6) if total else 0.0,
        }


class ServingStats:
    """Thread-safe accumulators for the serving layer.

    * per-request **latency** (submit -> result ready), reported as
      p50/p95/p99 milliseconds;
    * **queue depth** observed by the dispatcher at each batch launch;
    * **batch occupancy**: real requests / device batch slots (padding
      a partial batch up to the compiled batch size keeps the executable
      count bounded but burns slots — occupancy is that cost);
    * **padding overhead**: 1 - real pixels / padded-canvas pixels
      (the price of serving a shape from a bucket larger than it);
    * **compiles**: executables built (warmup) + any mid-serve fallback
      compile (a native-shape forward for an oversize request). A
      mid-serve compile for a *bucketed* request is a bug — the
      compile-sentinel test pins that it never happens. A replica pool
      warms ``len(buckets) x replicas`` executables;
    * **admission-control counters** (the front-door schema,
      docs/SERVING.md "Front door"): ``shed_count`` — requests refused
      at admission (queue watermark, ``QueueFull``, or an armed
      ``reject_admit`` fault); ``deadline_expired`` — requests whose
      ``X-Deadline-Ms`` budget ran out (rejected up front or dropped
      un-computed at dispatch); ``queue_depth`` — the LIVE
      outstanding-request backlog (queued, coalescing, or in flight on
      a replica), read through the probe the
      owning :class:`~waternet_tpu.serving.batcher.DynamicBatcher`
      registers (0 for stats objects nothing registered on);
    * **per-replica** occupancy / mean latency / busy seconds, plus the
      aggregate **images_per_sec** (requests completed over the
      first-dispatch -> last-completion span) and **load_imbalance**
      (max over mean per-replica request count; 1.0 = perfectly even);
    * **per-tier** request/batch counters (``tiers``): quality vs fast
      traffic split under per-request tier routing (docs/SERVING.md
      "Quality tiers"). Every configured tier appears (a served-nothing
      fast tier shows zeros); batchers without a fast engine report the
      quality tier alone;
    * **fault-isolation counters** (docs/SERVING.md "Fault isolation"):
      ``retried`` — requests re-dispatched onto a surviving replica after
      their batch demonstrably failed (crash / watchdog hang / bad
      output; never double-counts a delivered result); ``downgraded`` —
      opted-in quality requests served by the fast tier under brown-out
      pressure; ``nan_outputs`` — batches the output sanity guard
      rejected (non-finite or all-zero canvas); ``quarantines`` /
      ``reintegrations`` — replica state-machine transitions, with
      ``recovery_sec_max`` the longest quarantine→reintegration span;
      ``replica_health`` — the LIVE per-tier ``{replica: state}`` map,
      read through the probe the owning batcher registers (empty for
      stats objects nothing registered on);
    * **stream counters** (``streams``, docs/SERVING.md "Streaming"):
      session opens/refusals and per-frame accounting for the
      POST ``/stream`` session layer — ``frames_dropped`` (window
      overflow, queue shed, disconnect cleanup), ``frames_out_of_budget``
      (freshness deadline ran out), ``downgrades`` (stream frames served
      by the fast tier under brown-out), ``frames_reused`` (frames
      answered from the session's cached enhanced frame by temporal
      gating — never computed; docs/SERVING.md "Temporal reuse &
      response cache"), a frame end-to-end latency reservoir (read ->
      record written; computed frames only — reused frames resolve in
      encode time and would skew the compute signal), plus the LIVE
      ``active_streams`` gauge and per-session p99 map read through the
      probe the owning
      :class:`~waternet_tpu.serving.streams.StreamManager` registers
      (0 / {} for stats objects nothing registered on);
    * the **response cache** block (``cache``): hit/miss/evict counters
      and live entry/generation gauges read through the probe the
      owning :class:`~waternet_tpu.serving.reuse.ResponseCache`
      registers (an all-zeros ``enabled: false`` block for servers with
      no cache configured);
    * **sliding windows** (``latency_ms_window`` + the ``window`` block,
      docs/OBSERVABILITY.md "Windows & SLOs"): the same latency / queue
      / shed / error signals over the trailing 60 s / 300 s, so a
      post-incident scrape reports current health instead of the
      lifetime reservoir's history — and, when :meth:`arm_slo` armed an
      engine, the ``slo`` burn-rate block that grades /healthz.
    """

    def __init__(self, clock=None):
        self._lock = threading.Lock()
        # Windowed twin of the reservoirs below. Self-locked primitives,
        # fed OUTSIDE self._lock (see _ServingWindows); the clock is
        # injectable so window tests drive time without sleeping.
        self.window = _ServingWindows(clock)
        #: Armed SLO engine, or None. Assigned once by arm_slo before
        #: serving traffic (server construction), read thereafter.
        self._slo: Optional[SloEngine] = None
        # bounded reservoir sample (algorithm R)
        self._latencies_s: List[float] = []  # guarded-by: self._lock
        self._reservoir_rng = random.Random(0)  # guarded-by: self._lock
        self.requests = 0  # guarded-by: self._lock
        self.batches = 0  # guarded-by: self._lock
        self.real_slots = 0  # guarded-by: self._lock
        self.total_slots = 0  # guarded-by: self._lock
        self.real_px = 0  # guarded-by: self._lock
        self.padded_px = 0  # guarded-by: self._lock
        self.compiles = 0  # guarded-by: self._lock
        self.fallback_native = 0  # guarded-by: self._lock
        self.shed = 0  # guarded-by: self._lock
        self.deadline_expired = 0  # guarded-by: self._lock
        #: Live queue-depth gauge: a zero-arg callable the owning batcher
        #: registers (DynamicBatcher.queue_depth). Left None, the summary
        #: reports 0 — stats objects riding an ExactShapeBatcher or a bare
        #: test have no queue to report.
        self.queue_depth_probe = None
        #: Live replica-health gauge: a zero-arg callable returning the
        #: per-tier {replica_index: state} map (DynamicBatcher.health).
        #: Left None, the summary reports {} — bare stats objects have no
        #: replica pool to report on.
        self.replica_health_probe = None
        #: Live effective-coalescing-window gauge: a zero-arg callable
        #: returning {tier: eff_wait_ms} (DynamicBatcher.eff_wait_ms —
        #: the cap under --coalesce fixed, the controller's load-aware
        #: window under adaptive; docs/SERVING.md "Adaptive
        #: scheduling"). Left None, the summary reports {} — bare stats
        #: objects have no coalescing window to report.
        self.eff_wait_probe = None
        self.retried = 0  # guarded-by: self._lock
        self.downgraded = 0  # guarded-by: self._lock
        self.nan_outputs = 0  # guarded-by: self._lock
        self.quarantines = 0  # guarded-by: self._lock
        self.reintegrations = 0  # guarded-by: self._lock
        self._recovery_max_s = 0.0  # guarded-by: self._lock
        self._depth_sum = 0  # guarded-by: self._lock
        self.depth_max = 0  # guarded-by: self._lock
        self.replicas = 1  # guarded-by: self._lock
        # index -> per-replica accumulator dict
        self._rep = {}  # guarded-by: self._lock
        # tier -> {requests, batches}: populated by declare_tier (each
        # ReplicaPool registers its tier at construction) and by records;
        # a bare stats object (ExactShapeBatcher, tests) grows its tier
        # rows on first traffic.
        self._tiers = {}  # guarded-by: self._lock
        self._t_first_batch = None  # guarded-by: self._lock
        self._t_last_done = None  # guarded-by: self._lock
        # --- stream-session counters (POST /stream layer) ---
        self.streams_opened = 0  # guarded-by: self._lock
        self.streams_refused = 0  # guarded-by: self._lock
        self.stream_frames_in = 0  # guarded-by: self._lock
        self.stream_frames_delivered = 0  # guarded-by: self._lock
        self.stream_frames_dropped = 0  # guarded-by: self._lock
        self.stream_frames_out_of_budget = 0  # guarded-by: self._lock
        self.stream_frames_reused = 0  # guarded-by: self._lock
        self.stream_downgrades = 0  # guarded-by: self._lock
        # bounded reservoir sample (algorithm R)
        self._stream_lat_s: List[float] = []  # guarded-by: self._lock
        self._stream_rng = random.Random(1)  # guarded-by: self._lock
        #: Live stream gauge: a zero-arg callable the owning StreamManager
        #: registers, returning {"active_streams": int,
        #: "per_session_p99_ms": {stream_id: p99}}. Left None, the summary
        #: reports 0 / {} — most stats objects have no stream layer.
        self.stream_probe = None
        #: Live response-cache gauge: a zero-arg callable the owning
        #: ResponseCache registers (ResponseCache.counters). Left None,
        #: the summary reports the all-zeros enabled:false block — most
        #: servers run without a cache.
        self.cache_probe = None
        #: Live event-loop-lag gauge: a zero-arg callable the owning
        #: server registers when ``--obs-loop-lag`` is on (a LoopTracer
        #: with an infinite threshold wrapping Handle._run — docs/
        #: LINT.md "Asyncio rules"). Left None, the summary reports the
        #: all-zeros enabled:false block — sampling is opt-in.
        self.loop_lag_probe = None

    def declare_tier(self, tier: str) -> None:
        """Register a serving tier up front (a ReplicaPool does this at
        construction) so an idle tier still reports zeros — absence
        means 'not configured', not 'no traffic'."""
        with self._lock:
            self._tiers.setdefault(tier, {"requests": 0, "batches": 0})

    def set_replicas(self, n: int) -> None:
        """Declare the serving replica count (idle replicas must show up
        as imbalance, so every index gets an accumulator up front)."""
        with self._lock:
            self.replicas = int(n)
            for i in range(self.replicas):
                self._rep.setdefault(i, self._new_rep())

    @staticmethod
    def _new_rep() -> dict:
        return {
            "requests": 0, "batches": 0, "real_slots": 0, "total_slots": 0,
            "lat_sum_s": 0.0, "busy_s": 0.0,
        }

    def record_latency(
        self, seconds: float, replica: int = 0, tier: str = "quality"
    ) -> None:
        with self._lock:
            self.requests += 1
            rep = self._rep.setdefault(replica, self._new_rep())
            rep["requests"] += 1
            rep["lat_sum_s"] += seconds
            t = self._tiers.setdefault(tier, {"requests": 0, "batches": 0})
            t["requests"] += 1
            self._t_last_done = time.perf_counter()
            if len(self._latencies_s) < LATENCY_RESERVOIR:
                self._latencies_s.append(seconds)
            else:
                # Algorithm R: every request keeps an equal chance of
                # being in the sample, at O(1) memory for server
                # lifetimes of any length.
                j = self._reservoir_rng.randrange(self.requests)
                if j < LATENCY_RESERVOIR:
                    self._latencies_s[j] = seconds
        # Window feeds stay outside self._lock: the primitives are
        # self-locked, and nesting them under the stats lock would add
        # lock-order edges for nothing.
        ms = seconds * 1e3
        self.window.latency.record(ms)
        self.window.tier_hist(tier).record(ms)
        self.window.ok.add(1)

    def record_batch(
        self, n_real: int, n_slots: int, real_px: int, padded_px: int,
        queue_depth: int = 0, replica: int = 0, tier: str = "quality",
    ) -> None:
        with self._lock:
            self.batches += 1
            t = self._tiers.setdefault(tier, {"requests": 0, "batches": 0})
            t["batches"] += 1
            self.real_slots += n_real
            self.total_slots += n_slots
            self.real_px += real_px
            self.padded_px += padded_px
            self._depth_sum += queue_depth
            self.depth_max = max(self.depth_max, queue_depth)
            rep = self._rep.setdefault(replica, self._new_rep())
            rep["batches"] += 1
            rep["real_slots"] += n_real
            rep["total_slots"] += n_slots
            if self._t_first_batch is None:
                self._t_first_batch = time.perf_counter()
        self.window.queue_depth.record(queue_depth)

    def record_replica_busy(self, replica: int, seconds: float) -> None:
        """Launch->completion wall time of one batch on one replica —
        the device-occupancy proxy the pool reports per replica."""
        with self._lock:
            rep = self._rep.setdefault(replica, self._new_rep())
            rep["busy_s"] += seconds

    def record_compile(self, n: int = 1) -> None:
        with self._lock:
            self.compiles += n

    def record_shed(self) -> None:
        """One request refused at admission (watermark / QueueFull /
        reject_admit fault) — load that was shed, not served."""
        with self._lock:
            self.shed += 1
        self.window.shed.add(1)

    def record_deadline_expired(self) -> None:
        """One request whose deadline budget ran out before compute —
        rejected up front or dropped (not computed) at dispatch time."""
        with self._lock:
            self.deadline_expired += 1
        self.window.errors.add(1)

    def record_retry(self, n: int = 1) -> None:
        """``n`` requests re-dispatched onto a surviving replica after
        their batch demonstrably failed (crash, watchdog hang, or a
        guard-rejected output) — counted per re-dispatch, not per
        delivered result."""
        with self._lock:
            self.retried += n

    def record_downgrade(self) -> None:
        """One opted-in quality request served by the fast tier under
        brown-out pressure instead of being shed (docs/SERVING.md
        "Fault isolation")."""
        with self._lock:
            self.downgraded += 1

    def record_nan_output(self) -> None:
        """One completed batch rejected by the output sanity guard
        (non-finite values or an all-zero canvas after D2H)."""
        with self._lock:
            self.nan_outputs += 1
        self.window.errors.add(1)

    def record_quarantine(self) -> None:
        """One replica transitioned into quarantine (crash strikes or a
        watchdog-detected hang)."""
        with self._lock:
            self.quarantines += 1

    def record_reintegration(self, recovery_sec: float = 0.0) -> None:
        """One quarantined replica re-warmed and reintegrated;
        ``recovery_sec`` is its quarantine→reintegration span."""
        with self._lock:
            self.reintegrations += 1
            self._recovery_max_s = max(self._recovery_max_s, recovery_sec)

    def record_stream_open(self) -> None:
        """One stream session admitted on POST /stream."""
        with self._lock:
            self.streams_opened += 1

    def record_stream_refused(self) -> None:
        """One stream session refused at admission (503 + Retry-After:
        the third rung of the degradation ladder, or a draining server)."""
        with self._lock:
            self.streams_refused += 1

    def record_stream_frame_in(self) -> None:
        """One frame read off a stream session's upload."""
        with self._lock:
            self.stream_frames_in += 1

    def record_stream_frame_delivered(self, seconds: float) -> None:
        """One enhanced frame written back to its stream client;
        ``seconds`` is the end-to-end frame span (read -> record
        written), sampled into a bounded reservoir like request
        latency."""
        with self._lock:
            self.stream_frames_delivered += 1
            if len(self._stream_lat_s) < LATENCY_RESERVOIR:
                self._stream_lat_s.append(seconds)
            else:
                j = self._stream_rng.randrange(self.stream_frames_delivered)
                if j < LATENCY_RESERVOIR:
                    self._stream_lat_s[j] = seconds
        self.window.stream_frame.record(seconds * 1e3)

    def record_stream_drop(self, reason: str) -> None:
        """One stream frame deliberately not delivered. ``reason``
        ``"budget"`` (freshness deadline ran out) counts as
        out-of-budget; any other reason (``"window"`` overflow,
        ``"queue"`` shed, ``"disconnect"`` cleanup, ``"cancelled"``,
        ``"anchor"`` — a reuse child whose anchor never delivered)
        counts as a drop."""
        with self._lock:
            if reason == "budget":
                self.stream_frames_out_of_budget += 1
            else:
                self.stream_frames_dropped += 1

    def record_stream_frame_reused(self) -> None:
        """One stream frame answered from the session's cached enhanced
        frame by temporal gating (reuse.py) — delivered to the client
        as an ``R`` record without ever entering the batcher."""
        with self._lock:
            self.stream_frames_reused += 1

    def record_stream_downgrade(self) -> None:
        """One stream frame served by the fast tier under brown-out
        pressure (the first rung of the degradation ladder)."""
        with self._lock:
            self.stream_downgrades += 1

    def stream_latency_ms(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._stream_lat_s)
        return {
            "p50": round(_percentile(vals, 0.50) * 1e3, 3),
            "p99": round(_percentile(vals, 0.99) * 1e3, 3),
        }

    def record_fallback(self) -> None:
        with self._lock:
            self.fallback_native += 1
            # A fallback is a dispatch too: the throughput span must start
            # at the first dispatch of ANY kind, or an all-oversize stream
            # reports images_per_sec = 0.0 despite completing work.
            if self._t_first_batch is None:
                self._t_first_batch = time.perf_counter()

    def occupancy(self) -> float:
        with self._lock:
            return self.real_slots / self.total_slots if self.total_slots else 0.0

    def padding_overhead(self) -> float:
        with self._lock:
            return 1.0 - self.real_px / self.padded_px if self.padded_px else 0.0

    def latency_ms(self) -> Dict[str, float]:
        with self._lock:
            vals = sorted(self._latencies_s)
        return {
            "p50": round(_percentile(vals, 0.50) * 1e3, 3),
            "p95": round(_percentile(vals, 0.95) * 1e3, 3),
            "p99": round(_percentile(vals, 0.99) * 1e3, 3),
        }

    def latency_ms_window(self) -> Dict[str, float]:
        """Trailing-window latency quantiles — what the server is doing
        NOW, next to the lifetime reservoir's :meth:`latency_ms`."""
        return obswin.quantile_block(
            self.window.latency.merged(obswin.DEFAULT_WINDOW_SEC))

    def arm_slo(self, engine: SloEngine) -> None:
        """Attach an SLO engine (``--slo`` on the serving CLI). Called
        once at server construction, before traffic."""
        self._slo = engine

    def slo_state(self) -> Optional[dict]:
        """Evaluate the armed SLO engine against the current windows
        (one state-machine tick per call — scrape-driven, like every
        burn-rate evaluator). None when no engine is armed."""
        engine = self._slo
        if engine is None:
            return None
        return engine.evaluate(
            self.window.now(),
            self.window.sample(engine.short_sec),
            self.window.sample(engine.long_sec),
        )

    def images_per_sec(self) -> float:
        """Aggregate completed-requests throughput over the first-dispatch
        -> last-completion span (0.0 before any batch completes)."""
        with self._lock:
            if (
                self._t_first_batch is None
                or self._t_last_done is None
                or self._t_last_done <= self._t_first_batch
            ):
                return 0.0
            return self.requests / (self._t_last_done - self._t_first_batch)

    def load_imbalance(self) -> float:
        """max / mean per-replica request count over every configured
        replica (idle replicas count as 0, so they show up). 1.0 is a
        perfectly even pool; 1.0 by definition when nothing was served."""
        with self._lock:
            counts = [
                self._rep.get(i, {}).get("requests", 0)
                for i in range(self.replicas)
            ]
        total = sum(counts)
        if total == 0 or not counts:
            return 1.0
        return max(counts) / (total / len(counts))

    def per_replica(self) -> List[dict]:
        """Per-replica occupancy/latency rollup, by replica index."""
        with self._lock:
            reps = {i: dict(r) for i, r in self._rep.items()}
            for i in range(self.replicas):
                reps.setdefault(i, self._new_rep())
        out = []
        for i in sorted(reps):
            r = reps[i]
            out.append(
                {
                    "replica": i,
                    "requests": r["requests"],
                    "batches": r["batches"],
                    "occupancy": round(
                        r["real_slots"] / r["total_slots"], 4
                    )
                    if r["total_slots"]
                    else 0.0,
                    "latency_ms_mean": round(
                        r["lat_sum_s"] / r["requests"] * 1e3, 3
                    )
                    if r["requests"]
                    else 0.0,
                    "busy_sec": round(r["busy_s"], 3),
                }
            )
        return out

    def summary(self) -> dict:
        """The JSON stats block (docs/SERVING.md schema)."""
        with self._lock:
            batches = self.batches
            depth_mean = self._depth_sum / batches if batches else 0.0
            depth_max = self.depth_max
            requests = self.requests
            compiles = self.compiles
            fallback = self.fallback_native
            replicas = self.replicas
            shed = self.shed
            expired = self.deadline_expired
            probe = self.queue_depth_probe
            health_probe = self.replica_health_probe
            eff_wait_probe = self.eff_wait_probe
            retried = self.retried
            downgraded = self.downgraded
            nan_outputs = self.nan_outputs
            quarantines = self.quarantines
            reintegrations = self.reintegrations
            recovery_max = self._recovery_max_s
            tiers = {name: dict(c) for name, c in self._tiers.items()}
            stream_probe = self.stream_probe
            cache_probe = self.cache_probe
            loop_lag_probe = self.loop_lag_probe
            streams = {
                "opened": self.streams_opened,
                "refused": self.streams_refused,
                "frames_in": self.stream_frames_in,
                "frames_delivered": self.stream_frames_delivered,
                "frames_reused": self.stream_frames_reused,
                "frames_dropped": self.stream_frames_dropped,
                "frames_out_of_budget": self.stream_frames_out_of_budget,
                "downgrades": self.stream_downgrades,
            }
        live = (
            stream_probe()
            if stream_probe is not None
            else {"active_streams": 0, "per_session_p99_ms": {}}
        )
        streams["active_streams"] = live["active_streams"]
        streams["per_session_p99_ms"] = live["per_session_p99_ms"]
        streams["frame_latency_ms"] = self.stream_latency_ms()
        return {
            "requests": requests,
            "batches": batches,
            "latency_ms": self.latency_ms(),
            "latency_ms_window": self.latency_ms_window(),
            "batch_occupancy": round(self.occupancy(), 4),
            "padding_overhead": round(self.padding_overhead(), 4),
            "compiles": compiles,
            "fallback_native_shapes": fallback,
            "shed_count": shed,
            "deadline_expired": expired,
            "retried": retried,
            "downgraded": downgraded,
            "nan_outputs": nan_outputs,
            "quarantines": quarantines,
            "reintegrations": reintegrations,
            "recovery_sec_max": round(recovery_max, 3),
            "replica_health": (
                health_probe() if health_probe is not None else {}
            ),
            "queue_depth": int(probe()) if probe is not None else 0,
            "eff_wait_ms": (
                eff_wait_probe() if eff_wait_probe is not None else {}
            ),
            "queue_depth_mean": round(depth_mean, 2),
            "queue_depth_max": depth_max,
            "replicas": replicas,
            "images_per_sec": round(self.images_per_sec(), 2),
            "load_imbalance": round(self.load_imbalance(), 3),
            "tiers": tiers,
            "streams": streams,
            "cache": (
                cache_probe() if cache_probe is not None
                else empty_cache_block()
            ),
            "loop_lag": (
                loop_lag_probe() if loop_lag_probe is not None
                else empty_loop_lag_block()
            ),
            "per_replica": self.per_replica(),
            "window": self.window.block(),
            "slo": self.slo_state(),
        }

    def to_json(self) -> str:
        return json.dumps({"serving_stats": self.summary()})
