"""Multi-device serving scale-out: a pool of per-device replicas under the
dynamic batcher, with per-replica supervision and fault isolation
(docs/SERVING.md "Replica pool" and "Fault isolation").

WaterNet's serving forward is ~1 MFLOP/pixel with no cross-request state,
so aggregate images/sec should scale near-linearly with device count once
nothing serializes between devices — the data-parallel replica-pool shape
continuous-batching servers use (one request queue multiplexed over N
model replicas). PR 4's engine drove exactly one device; this pool places
**params and the AOT-warmed (bucket, max_batch) executable grid on every
serving device** and gives each replica its own launch and completion
threads, so

* host preprocessing + H2D + dispatch for replica *i*'s next batch,
* device compute on replica *j*, and
* D2H readback on replica *k*

all overlap freely — a blocking readback on one device never stalls
dispatch or compute on another (the PR-2 pipeline discipline, per
device). The batcher's dispatcher routes each coalesced micro-batch to
the **least-loaded available replica** (fewest outstanding batches, ties
to the lowest index — deterministic), and a bounded
``max_inflight_per_replica`` keeps every device double-buffered without
letting any of them run away with the queue.

**Fault isolation.** One sick device must not take the pool down with
it, so every replica runs a health state machine

    healthy -> suspect -> quarantined -> rewarming -> (reintegrated)

driven by a supervisor thread with per-batch **watchdog deadlines**:

* a batch that *raises* (XLA dispatch death, a poisoned transfer) marks
  its replica ``suspect`` and its requests re-dispatch onto surviving
  replicas (bounded per-request retries); the supervisor then
  quarantines the suspect;
* a batch that *hangs* past ``watchdog_sec`` (wedged driver, stalled
  device) is detected by the supervisor, its replica quarantined with
  fresh worker threads (the wedged ones are retired — they cannot be
  interrupted, only replaced), and its stranded requests re-dispatched;
* a completed batch whose host array fails the **output sanity guard**
  (non-finite values / all-zero canvas) is treated exactly like a crash:
  counted (``nan_outputs``) and retried;
* a quarantined replica is **re-warmed** — a probe batch through its
  existing AOT executables (reused, zero compiles) on its fresh threads,
  watchdog-guarded — and reintegrated on success, with exponential
  backoff on probe failure.

Retries are **byte-identical** by the replica-invariance argument below
(same params, same XLA program on every replica), and a batch is retried
only when it *demonstrably* failed: a claim protocol under the pool lock
guarantees exactly one delivery per request — a hung batch that
eventually completes after its requests were re-dispatched is discarded,
and a batch that completes before the watchdog fires is never recomputed.

Outputs are replica-count-invariant by construction: every replica runs
the same XLA program on the same params, and a request's output never
depends on its batchmates (the PR-4 exactness policy), so the same
request stream produces byte-identical results whether it lands on
replica 0 or 7 — or is transparently re-dispatched from a dying replica
to a surviving one (pinned in tests/test_serving.py and
tests/test_fault_isolation.py).

Scope: replicas are for unsharded engines (each replica is one whole
device). ``data_shards``/``spatial_shards`` engines already span their
mesh with a single executable and therefore always resolve to ONE
replica — the mesh *is* the parallelism there. Oversize requests (no
covering bucket) keep the jit-cache native-shape fallback, routed to the
lowest-index available replica, with the compile-count probe serialized
under a pool-level lock (quarantine can move the routing mid-stream, so
"one replica runs fallbacks" is no longer a structural guarantee).

All worker threads run under the input pipeline's ``THREAD_PREFIX`` so
the test suite's thread-leak guard covers pool shutdown too — and
:meth:`ReplicaPool.close` reports any thread that fails to join (a
wedged device) **loudly** instead of silently leaking it.
"""

from __future__ import annotations

import dataclasses
import queue
import sys
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from waternet_tpu.data.pipeline import THREAD_PREFIX
from waternet_tpu.obs import trace
from waternet_tpu.resilience import faults
from waternet_tpu.serving.bucketing import Bucket, BucketLadder
from waternet_tpu.serving.stats import ServingStats
from waternet_tpu.serving.warmup import probe_image, warmup
from waternet_tpu.utils.tensor import ten2arr

_CLOSE = object()

#: Replica health states (docs/SERVING.md "Fault isolation").
HEALTHY = "healthy"
SUSPECT = "suspect"
QUARANTINED = "quarantined"
REWARMING = "rewarming"

#: States in which a replica accepts new work. A suspect replica keeps
#: serving until the supervisor's next scan quarantines it — the window
#: is one scan interval, and claims keep any double-delivery impossible.
AVAILABLE_STATES = (HEALTHY, SUSPECT)


class ReplicaUnavailable(RuntimeError):
    """No replica in an available state could take the work: everything
    is quarantined (or the quarantined replica was the only one and its
    requests exhausted their retries). The HTTP front door answers 503 —
    and ``/healthz`` has been reporting the pool unhealthy since the
    last quarantine."""


class BadOutput(RuntimeError):
    """A completed batch failed the output sanity guard (non-finite
    values or an all-zero canvas after D2H) more times than the retry
    budget allows."""


@dataclasses.dataclass
class SupervisionConfig:
    """Knobs for the replica supervisor (docs/SERVING.md "Fault
    isolation"). The defaults are production-shaped: a generous watchdog
    (real batches finish in milliseconds; 30 s only ever fires on a
    genuinely wedged device) and a small re-warm backoff so a transient
    fault costs milliseconds of capacity, not minutes."""

    #: Seconds a dispatched batch may stay in flight (dequeue -> host
    #: delivery) before its replica is declared hung and quarantined.
    #: None disables the watchdog (crash isolation still works).
    watchdog_sec: Optional[float] = 30.0
    #: Watchdog for OVERSIZE FALLBACK launches, separate because their
    #: launch legitimately blocks on a first-time XLA compile of the
    #: native shape — routinely far longer than any sane bucketed-batch
    #: watchdog. None (the default) exempts fallbacks entirely (the
    #: pre-supervision behavior: a wedged fallback strands its launcher
    #: and whatever is queued behind it — the price of not
    #: false-quarantining every slow compile); operators whose oversize
    #: traffic matters set it ABOVE their worst native-shape compile
    #: time to get hang coverage there too.
    fallback_watchdog_sec: Optional[float] = None
    #: Per-request bound on re-dispatches after demonstrable batch
    #: failures; past it the request's future gets the causing error.
    max_retries: int = 2
    #: Delay before the first re-warm probe of a quarantined replica
    #: (doubles per failed probe up to ``max_rewarm_backoff_sec``).
    rewarm_backoff_sec: float = 0.05
    max_rewarm_backoff_sec: float = 5.0
    #: Supervisor scan cadence (watchdog resolution).
    scan_interval_sec: float = 0.02
    #: Check every completed batch for non-finite / all-zero output.
    output_guard: bool = True


class _Inflight:
    """One dispatched batch under watchdog supervision. ``state`` moves
    ``live -> claimed`` (a worker delivered or errored it) or ``live ->
    aborted`` (the supervisor declared it failed and re-dispatched its
    requests); the transition happens exactly once, under the pool lock
    — the single-delivery guarantee."""

    __slots__ = ("replica", "bucket", "reqs", "deadline", "state",
                 "probe", "t0")

    def __init__(self, replica, bucket, reqs, deadline, probe):
        self.replica = replica
        self.bucket = bucket
        self.reqs = reqs
        self.deadline = deadline
        self.state = "live"
        self.probe = probe
        self.t0 = None


class _ProbeRequest:
    """The single request of a re-warm probe batch: same attribute shape
    as the batcher's requests, never counted in serving stats."""

    __slots__ = ("image", "future", "t_submit", "retries", "tier")

    def __init__(self, image):
        self.image = image
        self.future = Future()
        self.t_submit = time.perf_counter()
        self.retries = 0
        self.tier = None


def engine_jit_cache_size(engine) -> int:
    """Total executable-cache size of the engine's jit entry points, 0 when
    this jax build exposes no introspection — the probe the serving layer
    uses to count *real* compiles (growth across a call = executables
    built). Sums the forward and both fused programs so device-preprocess
    fallbacks are counted too."""
    total = 0
    for attr in ("_forward", "_fused", "_fused_padded"):
        sizer = getattr(getattr(engine, attr, None), "_cache_size", None)
        if callable(sizer):
            total += sizer()
    return total


def resolve_replicas(spec, engine=None) -> int:
    """``'auto'`` / ``N`` / ``None`` -> a concrete replica count.

    ``auto`` (and None/empty) means every local device — the tentpole
    default: a v5e-8 host serves with 8 replicas unless told otherwise.
    Sharded engines always resolve to 1: their one executable already
    spans the mesh, and stacking replicas on top would oversubscribe it.
    """
    import jax

    sharded = engine is not None and (
        getattr(engine, "data_shards", 1) > 1
        or getattr(engine, "spatial_shards", 1) > 1
    )
    n_local = max(1, len(jax.local_devices()))
    # Validate the spec BEFORE the sharded override: a typo'd
    # --serve-replicas must fail the same way whether or not the engine
    # happens to be sharded.
    text = "auto" if spec is None else str(spec).strip().lower()
    if text in ("", "auto"):
        return 1 if sharded else n_local
    try:
        n = int(text)
    except ValueError:
        raise ValueError(
            f"--serve-replicas must be 'auto' or a positive integer, got "
            f"{spec!r}"
        ) from None
    if n < 1:
        raise ValueError(f"--serve-replicas must be >= 1, got {n}")
    if n > n_local:
        raise ValueError(
            f"--serve-replicas {n} exceeds the {n_local} local device(s)"
        )
    if sharded and n != 1:
        # An EXPLICIT multi-replica request contradicts a sharded engine
        # (its one executable already spans the mesh) — refuse loudly
        # rather than silently serving on one replica; 'auto' resolves to
        # 1 without complaint.
        raise ValueError(
            f"--serve-replicas {n} conflicts with a sharded engine "
            "(data_shards/spatial_shards engines serve as ONE mesh-"
            "spanning replica; use --serve-replicas auto or 1)"
        )
    return n


class _Replica:
    """One serving device: its params copy, its executable grid, a work
    queue feeding a launch thread (host preprocess + async dispatch), a
    bounded in-flight queue feeding a completion thread (the replica's
    one D2H sync point) — and a health state the supervisor drives.
    Worker threads are per-*generation*: a quarantine retires the current
    pair (wedged threads cannot be interrupted, only replaced) and spawns
    a fresh pair on fresh queues."""

    def __init__(self, pool: "ReplicaPool", index: int, device):
        self.pool = pool
        self.index = index
        self.device = device
        self.params = pool.engine.replica_params(device)
        self.executables: Dict[Tuple[Bucket, int], object] = {}
        self.outstanding = 0  # batches dispatched, not yet resolved (pool lock)
        self.state = HEALTHY
        self.gen = 0
        self.crashes = 0
        self.hangs = 0
        self.bad_outputs = 0
        self.quarantines = 0
        self.reintegrations = 0
        self._quarantined_at: Optional[float] = None
        self._rewarm_backoff = 0.0
        self._next_rewarm_at = 0.0
        self._probe: Optional[Future] = None
        self._spawn()

    def _spawn(self) -> None:
        """Fresh queues + worker threads for the current generation (not
        started — callers start them; respawn() starts immediately)."""
        self.work: queue.Queue = queue.Queue()
        # Launch at most max_inflight batches ahead of this replica's
        # completion sync: the device stays double-buffered, and a slow
        # D2H cannot pile unbounded device allocations behind it.
        self.inflight: queue.Queue = queue.Queue(maxsize=self.pool.max_inflight)
        suffix = f"-{self.index}" if self.gen == 0 else f"-{self.index}g{self.gen}"
        self._launcher = threading.Thread(
            target=self._launch_loop,
            args=(self.work, self.inflight, self.gen),
            name=f"{THREAD_PREFIX}-serve-launch{suffix}",
            daemon=True,
        )
        self._completer = threading.Thread(
            target=self._complete_loop,
            args=(self.inflight,),
            name=f"{THREAD_PREFIX}-serve-complete{suffix}",
            daemon=True,
        )

    def start(self) -> None:
        self._launcher.start()
        self._completer.start()

    def respawn(self):
        """Retire the current worker generation (caller holds the pool
        lock and has already bumped ``gen``): returns the old (work
        queue, threads) and installs started fresh ones."""
        old_work, old_threads = self.work, [self._launcher, self._completer]
        self._spawn()
        self.start()
        return old_work, old_threads

    # -- launch side ---------------------------------------------------

    def _launch_loop(self, work_q, inflight_q, gen) -> None:
        pool = self.pool
        while True:
            item = work_q.get()
            if item is _CLOSE:
                inflight_q.put(_CLOSE)
                return
            bucket, reqs, depth, probe = item
            if bucket is None:
                self._launch_fallback(reqs, inflight_q, work_q)
                continue
            entry = pool._register(self, bucket, reqs, probe)
            try:
                if not probe:
                    # Deterministic serving-side fault hooks
                    # (docs/RESILIENCE.md): slow_replica stalls this
                    # launch, replica_crash raises, replica_hang blocks
                    # until the plan is cleared (the releasable wedge).
                    fault = faults.replica_launch_fault()
                    if fault.delay > 0.0:
                        time.sleep(fault.delay)
                    if fault.hang is not None:
                        fault.hang.wait()  # released by faults.clear/install
                        if entry.state != "live" or gen != self.gen:
                            # This generation was retired mid-hang. If
                            # the watchdog took our batch it was already
                            # re-dispatched (claim fails, nothing to do);
                            # but a quarantine triggered by a DIFFERENT
                            # batch leaves ours live with no one else
                            # responsible — hand it back to the pool
                            # rather than stranding its futures until
                            # (or past, with the watchdog off) expiry.
                            if pool._claim(entry):
                                pool._redispatch(
                                    bucket, reqs,
                                    ReplicaUnavailable(
                                        f"replica {self.index} retired "
                                        "its worker generation mid-hang"
                                    ),
                                    count_retry=False,
                                )
                            inflight_q.put(_CLOSE)
                            return
                    if fault.crash:
                        raise RuntimeError(
                            f"injected replica_crash on replica {self.index}"
                        )
                n_slots = pool.max_batch
                exe = self.executables[(bucket, n_slots)]
                images = [r.image for r in reqs]
                t0 = time.perf_counter()
                out = pool.engine.enhance_padded_async(
                    images, bucket, n_slots=n_slots, executable=exe,
                    params=self.params, device=self.device,
                )
                if not probe:
                    bh, bw = bucket
                    pool.stats.record_batch(
                        n_real=len(reqs),
                        n_slots=n_slots,
                        real_px=sum(
                            im.shape[0] * im.shape[1] for im in images
                        ),
                        padded_px=n_slots * bh * bw,
                        queue_depth=depth,
                        replica=self.index,
                        tier=pool.tier,
                    )
                entry.t0 = t0
                if trace.enabled():
                    # Replica launch: host preprocess + async dispatch.
                    # The span closes here — the device itself is still
                    # computing; its span closes at the completion
                    # thread's existing D2H, never via a new sync.
                    t_disp = time.perf_counter()
                    for r in reqs:
                        trace.record_span(
                            "replica_launch", "serving", t0, t_disp,
                            args={
                                "request_id": getattr(r, "req_id", None),
                                "replica": self.index,
                                "tier": pool.tier,
                                "bucket": f"{bucket[0]}x{bucket[1]}",
                                "batch": len(reqs),
                            },
                        )
                inflight_q.put((out, entry))
            except BaseException as err:
                pool._on_batch_failure(entry, err, kind="crash")

    def _launch_fallback(self, reqs, inflight_q, work_q) -> None:
        """Oversize for every bucket: native-shape forwards, one request
        each (mixed oversize shapes cannot stack). These go through the
        engine's jit cache on its default device, so any compile they
        cause is real — count it (stats.compiles is "executables built",
        warmup AND fallback). Routed to the lowest-index available
        replica; because quarantine can move that routing mid-stream
        (two launch threads could interleave their before/after cache
        probes on the shared engine), the probe+dispatch bracket is
        serialized under the pool's fallback lock — dispatch is async,
        so the lock never covers device compute or D2H."""
        pool = self.pool
        # ONE request per work item: the rest of a group goes back on our
        # queue as a fresh item, where it stays visible to the supervisor
        # — not-yet-started requests held in this thread's locals would
        # be invisible to generation retirement if this launch wedges,
        # stranding their futures and leaking outstanding counts.
        r, rest = reqs[0], list(reqs[1:])
        if rest:
            work_q.put((None, rest, 0, False))
        # Take the accounting lock BEFORE registering the watchdog entry:
        # time spent waiting behind another replica's fallback must not
        # count against this batch's deadline — otherwise one wedged
        # fallback would cascade false hang-quarantines through every
        # replica queued on the lock. The bound is sized to FALLBACK
        # compile scale (a first-time native-shape compile legitimately
        # runs minutes — the same reason fallback_watchdog_sec defaults
        # to exempt), so only a genuine wedge ever trips it: past it we
        # launch WITHOUT the compile-count bracket (availability over
        # accounting).
        fb_wd = pool.supervision.fallback_watchdog_sec
        locked = pool._fallback_lock.acquire(
            timeout=fb_wd if fb_wd is not None else 600.0
        )
        entry = pool._register(self, None, [r], False)
        try:
            try:
                pool.stats.record_fallback()
                before = (
                    engine_jit_cache_size(pool.engine) if locked else None
                )
                t0 = time.perf_counter()
                out = pool.engine.enhance_async(r.image[None])
                if locked:
                    grew = engine_jit_cache_size(pool.engine) - before
                    if grew > 0:
                        pool.stats.record_compile(grew)
            finally:
                # Released before the bounded inflight put: D2H
                # backpressure must never be felt through the lock.
                if locked:
                    pool._fallback_lock.release()
                    locked = False
            entry.t0 = t0
            inflight_q.put((out, entry))
        except BaseException as err:
            pool._on_batch_failure(entry, err, kind="crash")

    # -- completion side -----------------------------------------------

    def _complete_loop(self, inflight_q) -> None:
        pool = self.pool
        while True:
            item = inflight_q.get()
            if item is _CLOSE:
                return
            out_dev, entry = item
            t_d2h0 = time.perf_counter() if trace.enabled() else None
            try:
                raw = np.asarray(out_dev)  # this replica's one D2H sync
            except BaseException as err:
                pool._on_batch_failure(entry, err, kind="crash")
                continue
            if not entry.probe:
                # nan_output@K: poison the host copy on cue so the guard
                # below is deterministically testable.
                raw = faults.poison_replica_output(raw)
            if pool.supervision.output_guard and not _output_ok(
                raw, entry.reqs
            ):
                pool._on_batch_failure(
                    entry,
                    BadOutput(
                        f"replica {self.index} produced a non-finite or "
                        "all-zero output canvas"
                    ),
                    kind="bad_output",
                )
                continue
            if not pool._claim(entry):
                # The watchdog aborted this batch while we were syncing
                # and its requests were re-dispatched elsewhere — discard
                # the late result (single delivery; byte-identical either
                # way).
                continue
            arr = ten2arr(raw)
            t_done = time.perf_counter()
            if entry.probe:
                entry.reqs[0].future.set_result(True)
                continue
            for i, r in enumerate(entry.reqs):
                if r.future.done():
                    continue
                h, w = r.image.shape[:2]
                r.future.set_result(arr[i, :h, :w])
                pool.stats.record_latency(
                    t_done - r.t_submit, replica=self.index, tier=pool.tier
                )
                if t_d2h0 is not None:
                    # Device span closed at the existing D2H above (no
                    # added sync); the serve span is the request's whole
                    # submit -> result wall, the trace's per-request root.
                    rid = getattr(r, "req_id", None)
                    common = {"request_id": rid, "replica": self.index,
                              "tier": pool.tier}
                    if entry.t0 is not None:
                        trace.record_span(
                            "device", "serving", entry.t0, t_done,
                            args=common,
                        )
                    trace.record_span(
                        "d2h", "serving", t_d2h0, t_done, args=common,
                    )
                    trace.record_span(
                        "serve", "serving", r.t_submit, t_done,
                        args=dict(common, retries=getattr(r, "retries", 0)),
                    )
            if entry.t0 is not None:
                pool.stats.record_replica_busy(self.index, t_done - entry.t0)

def _output_ok(raw: np.ndarray, reqs) -> bool:
    """The output sanity guard: False for non-finite values (a NaN that
    crept through the forward) or an all-zero canvas (a transfer that
    delivered an unwritten buffer) — the two cheap whole-batch
    signatures of device corruption. One float64 sum is the whole fast
    path: NaN/Inf propagate through it (no canvas-sized bool temporary
    like ``np.isfinite(raw).all()`` would allocate), outputs are bounded
    so the f64 accumulation cannot overflow, and a nonzero sum proves a
    nonzero canvas. The element scans only run on the rare zero-sum
    path. The all-zero arm only fires when some INPUT pixel was nonzero:
    a legitimately all-black frame maps to an all-black enhancement, and
    quarantining a healthy replica over it (then failing the request
    after byte-identical retries) would turn one dark upload into an
    availability incident."""
    total = np.sum(raw, dtype=np.float64)
    if not np.isfinite(total):
        return False
    if total != 0.0:
        return True
    if raw.any():  # exact cancellation of signed values: nonzero canvas
        return True
    return not any(r.image.any() for r in reqs)


class ReplicaPool:
    """Place the serving executable grid on ``n_replicas`` local devices
    and multiplex dispatched micro-batches over them, under supervision.

    Warmup compiles the full ``len(ladder) x len(batch_sizes) x
    n_replicas`` executable grid before construction returns, fanning the
    per-device compiles out over threads (serving/warmup.py) — no request
    ever pays a compile, on any replica, and the engine's jit caches
    never grow mid-serve (the PR-4 sentinel guarantee, now
    ``len(buckets) x replicas`` executables — re-warm probes REUSE the
    grid, so quarantine cycles never compile either).
    """

    def __init__(
        self,
        engine,
        ladder: BucketLadder,
        batch_sizes: Sequence[int],
        n_replicas: int = 1,
        max_inflight_per_replica: int = 2,
        stats: Optional[ServingStats] = None,
        warmup_verbose: bool = False,
        tier: str = "quality",
        supervision: Optional[SupervisionConfig] = None,
    ):
        import jax

        if max_inflight_per_replica < 1:
            raise ValueError(
                f"max_inflight_per_replica must be >= 1, got "
                f"{max_inflight_per_replica}"
            )
        sharded = engine.data_shards > 1 or engine.spatial_shards > 1
        if sharded and n_replicas != 1:
            raise ValueError(
                "sharded engines serve as ONE replica spanning their mesh; "
                f"got n_replicas={n_replicas} with data_shards="
                f"{engine.data_shards}, spatial_shards={engine.spatial_shards}"
            )
        devices = jax.local_devices()
        if n_replicas > len(devices):
            raise ValueError(
                f"n_replicas={n_replicas} exceeds the {len(devices)} local "
                "device(s)"
            )
        self.engine = engine
        self.max_batch = max(int(b) for b in batch_sizes)
        self.max_inflight = int(max_inflight_per_replica)
        self.stats = stats if stats is not None else ServingStats()
        self.stats.set_replicas(n_replicas)
        self.supervision = supervision if supervision is not None else SupervisionConfig()
        # Which serving tier this pool's batches/requests count under
        # (docs/SERVING.md "Quality tiers"): "quality" for the PR-4/5
        # teacher pipeline, "fast" for the CAN-student pool a tier-routing
        # DynamicBatcher stacks next to it on the same devices.
        self.tier = str(tier)
        self.stats.declare_tier(self.tier)
        self._lock = threading.Lock()
        # Serializes the oversize-fallback jit-cache probe bracket: the
        # lowest-AVAILABLE-index routing can move across replicas during
        # a quarantine window, and two interleaved before/after cache
        # probes would mis-count compiles.
        self._fallback_lock = threading.Lock()
        self._closed = False  # guarded-by: self._lock
        # live _Inflight entries (watchdog scope)
        self._watch: set = set()  # guarded-by: self._lock
        self._old_threads: List[threading.Thread] = []  # guarded-by: self._lock
        self.leaked_threads: List[str] = []  # guarded-by: self._lock
        self._probe_bucket = min(ladder, key=lambda b: b[0] * b[1])
        # A single replica keeps the engine's default placement (device
        # None) — byte-for-byte the PR-4 single-device behavior, and the
        # only valid form for sharded engines.
        dev_list = [None] if n_replicas == 1 else list(devices[:n_replicas])
        self._replicas: List[_Replica] = [
            _Replica(self, i, dev) for i, dev in enumerate(dev_list)
        ]
        grids = warmup(
            engine, ladder, batch_sizes, stats=self.stats,
            verbose=warmup_verbose,
            replicas=[(r.index, r.device, r.params) for r in self._replicas],
        )
        for r in self._replicas:
            r.executables = grids[r.index]
        for r in self._replicas:
            r.start()
        self._stop_supervisor = threading.Event()
        self._supervisor = threading.Thread(
            target=self._supervise,
            name=f"{THREAD_PREFIX}-serve-supervisor-{self.tier}",
            daemon=True,
        )
        self._supervisor.start()

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def health(self) -> Dict[int, str]:
        """Live per-replica health states, by index."""
        with self._lock:
            return {r.index: r.state for r in self._replicas}

    def has_idle_replica(self) -> bool:
        """True when some available replica has nothing dispatched and
        nothing in flight — i.e. a batch flushed right now would start
        computing immediately instead of queueing behind earlier
        batches. The adaptive dispatcher's work-conserving hold reads
        this (serving/batcher.py): while it is False, flushing a partial
        bucket early cannot improve latency, it only locks in a
        slot-padded partial batch."""
        with self._lock:
            return any(
                r.state in AVAILABLE_STATES and r.outstanding == 0
                for r in self._replicas
            )

    # -- dispatch ------------------------------------------------------

    def _pick_replica(self, bucket, exclude=None) -> _Replica:
        """Least-loaded available replica (lowest index on ties; lowest
        available index for fallback groups), preferring any replica
        other than ``exclude``. Caller holds the pool lock. Raises
        :class:`ReplicaUnavailable` when everything is quarantined."""
        avail = [r for r in self._replicas if r.state in AVAILABLE_STATES]
        if not avail:
            raise ReplicaUnavailable(
                f"all {len(self._replicas)} replica(s) of the "
                f"{self.tier!r} pool are quarantined"
            )
        others = [r for r in avail if r is not exclude]
        pool = others or avail
        if bucket is None:
            return min(pool, key=lambda r: r.index)
        return min(pool, key=lambda r: (r.outstanding, r.index))

    def dispatch(self, bucket: Optional[Bucket], reqs, queue_depth: int = 0) -> None:
        """Route one coalesced micro-batch (or a fallback group for
        ``bucket is None``) to the least-loaded available replica. Never
        blocks: work queues are unbounded — the per-replica in-flight
        bound throttles device memory, not the dispatcher. Raises
        :class:`ReplicaUnavailable` when every replica is quarantined
        (the batcher turns that into per-request errors; the front door
        has been answering 503 on /healthz since the last quarantine)."""
        if not reqs:
            return
        with self._lock:
            replica = self._pick_replica(bucket)
            # Fallback groups launch one forward per request.
            replica.outstanding += len(reqs) if bucket is None else 1
            replica.work.put((bucket, reqs, queue_depth, False))

    # -- supervision core ----------------------------------------------

    def _register(self, replica, bucket, reqs, probe) -> _Inflight:
        """A launch thread started work on a batch: put it under watchdog
        supervision. Oversize fallbacks (``bucket is None``) use the
        separate ``fallback_watchdog_sec`` (default None = exempt): their
        launch blocks on a legitimate first-time XLA compile of the
        native shape, which any bucketed-batch-sized watchdog would
        misread as a hang — see :class:`SupervisionConfig` for the
        tradeoff."""
        wd = (
            self.supervision.fallback_watchdog_sec
            if bucket is None
            else self.supervision.watchdog_sec
        )
        deadline = None if wd is None else time.perf_counter() + wd
        entry = _Inflight(replica, bucket, reqs, deadline, probe)
        with self._lock:
            self._watch.add(entry)
        return entry

    def _claim(self, entry: _Inflight) -> bool:
        """Atomically take ownership of a live batch (exactly one of:
        the completer delivering it, a failure handler retrying it, or
        the watchdog aborting it wins). False means someone else already
        owns it — the caller must discard its copy."""
        with self._lock:
            if entry.state != "live":
                return False
            entry.state = "claimed"
            self._watch.discard(entry)
            if not entry.probe:
                entry.replica.outstanding -= 1
            return True

    def _on_batch_failure(self, entry: _Inflight, err, kind: str) -> None:
        """A batch demonstrably failed (launch raised, D2H raised, or
        the output guard rejected the result): record the strike on its
        replica and transparently re-dispatch its requests."""
        if not self._claim(entry):
            return  # the watchdog already took it (hang abort)
        replica = entry.replica
        if entry.probe:
            if not entry.reqs[0].future.done():
                entry.reqs[0].future.set_exception(err)
            return
        if kind == "bad_output":
            self.stats.record_nan_output()
        if entry.bucket is None:
            # Oversize fallbacks run on the ENGINE'S DEFAULT device
            # regardless of which replica's launch thread carried them:
            # their failure says nothing about that replica's health, so
            # no strike and no exclusion — the bounded retry (same
            # device, transient faults only) is all re-dispatch can buy.
            self._redispatch(entry.bucket, entry.reqs, err)
            return
        with self._lock:
            if kind == "bad_output":
                replica.bad_outputs += 1
            else:
                replica.crashes += 1
            if replica.state == HEALTHY:
                # One strike -> suspect; the supervisor quarantines and
                # re-warms on its next scan. (A quarantined/rewarming
                # replica can still report failures from batches launched
                # before the transition — those stay where they are.)
                replica.state = SUSPECT
        self._redispatch(entry.bucket, entry.reqs, err, exclude=replica)

    def _redispatch(
        self, bucket, reqs, err, count_retry: bool = True, exclude=None
    ) -> None:
        """Re-queue requests from a failed (or never-started, when
        ``count_retry=False``) batch onto a surviving replica —
        ``exclude`` (the replica that just failed, usually still only
        SUSPECT and therefore available) is avoided whenever any other
        replica can take the work, so a persistently sick device cannot
        burn the whole retry budget before the supervisor's next scan
        quarantines it. Bounded by the per-request retry budget. Results
        are byte-identical to a first-try serve (replica invariance),
        and only demonstrably failed work ever gets here — successes are
        never recomputed (the claim protocol). Requests whose deadline
        passed while their batch was failing are dropped here with the
        same un-computed-504 policy the dispatcher applies at flush — a
        response nobody waits for is wasted device time, and the retry
        path must not be the one door that serves dead work late."""
        now = time.perf_counter()
        live: List = []
        for r in reqs:
            if r.future.done():
                continue
            if getattr(r.future, "abandoned", False):
                # The caller walked away (stream disconnect /
                # drop-oldest) while the batch was failing; the claim
                # protocol hands this path sole ownership, so resolving
                # here cannot race the completion thread.
                from waternet_tpu.serving.batcher import RequestCancelled

                r.future.set_exception(
                    RequestCancelled(
                        "request abandoned by its caller; dropped "
                        "instead of retried"
                    )
                )
                continue
            deadline = getattr(r, "deadline", None)
            if deadline is not None and deadline <= now:
                from waternet_tpu.serving.batcher import DeadlineExpired

                self.stats.record_deadline_expired()
                r.future.set_exception(
                    DeadlineExpired(
                        "deadline expired while the batch was being "
                        "retried; dropped un-computed"
                    )
                )
                continue
            live.append(r)
        if not live:
            return
        retryable: List = []
        for r in live:
            if count_retry:
                r.retries = getattr(r, "retries", 0) + 1
            if getattr(r, "retries", 0) <= self.supervision.max_retries:
                retryable.append(r)
            else:
                if not r.future.done():
                    r.future.set_exception(err)
        if not retryable:
            return
        try:
            with self._lock:
                replica = self._pick_replica(bucket, exclude=exclude)
                replica.outstanding += (
                    len(retryable) if bucket is None else 1
                )
                replica.work.put((bucket, retryable, 0, False))
            if count_retry:
                self.stats.record_retry(len(retryable))
            if trace.enabled():
                # Re-dispatch hop markers, outside the pool lock: a
                # re-dispatched request's span chain shows the hop
                # between its failed and its serving replica.
                t_hop = time.perf_counter()
                for r in retryable:
                    trace.record_instant(
                        "redispatch", "serving", t=t_hop,
                        args={
                            "request_id": getattr(r, "req_id", None),
                            "retry": getattr(r, "retries", 0),
                            "to_replica": replica.index,
                            "tier": self.tier,
                            "error": type(err).__name__
                            if err is not None else None,
                        },
                    )
        except ReplicaUnavailable as unavailable:
            final = unavailable if err is None else err
            for r in retryable:
                if not r.future.done():
                    r.future.set_exception(final)

    def _retire_generation(self, replica: _Replica):  # guarded-by: self._lock
        """Replace a replica's current worker generation (caller holds
        the pool lock): bump ``gen`` so a later-waking wedged thread
        knows to exit, spawn fresh threads on fresh queues, keep the old
        threads joinable for :meth:`close`, and drain the old work queue
        — adjusting ``outstanding`` for dispatched (non-probe) items.
        Returns ``(old_work_queue, drained_items)``; the caller must put
        ``_CLOSE`` on the old queue AFTER releasing the lock and dispose
        of the drained items (re-dispatch vs fail, depending on why the
        generation died)."""
        replica.gen += 1
        old_work, old_threads = replica.respawn()
        self._old_threads.extend(old_threads)
        drained: List = []
        try:
            while True:
                item = old_work.get_nowait()
                if item is _CLOSE:
                    continue
                drained.append(item)
                if not item[3]:  # probes never count toward outstanding
                    replica.outstanding -= (
                        len(item[1]) if item[0] is None else 1
                    )
        except queue.Empty:
            pass
        return old_work, drained

    def _quarantine(self, replica: _Replica, reason: str) -> None:
        """Take a replica out of rotation: bump its worker generation
        (retiring possibly-wedged threads), drain its never-started work
        back to the pool, and schedule a re-warm probe. In-flight batches
        keep their watchdog entries — a live one either completes through
        the old completer (claims still win) or expires and re-dispatches."""
        with self._lock:
            # No generation churn once close() latched _closed (both
            # hold this lock): a respawn here would create fresh threads
            # close() never sees — an unjoined, unreported leak.
            if self._closed or replica.state in (QUARANTINED, REWARMING):
                return
            replica.state = QUARANTINED
            replica.quarantines += 1
            now = time.perf_counter()
            replica._quarantined_at = now
            replica._rewarm_backoff = self.supervision.rewarm_backoff_sec
            replica._next_rewarm_at = now + replica._rewarm_backoff
            replica._probe = None
            old_work, stranded = self._retire_generation(replica)
        old_work.put(_CLOSE)  # retire an idle (non-wedged) old launcher
        self.stats.record_quarantine()
        for bucket, reqs, _depth, _probe in stranded:
            # Never-started work: re-route without burning retry budget
            # (nothing was computed, nothing demonstrably failed).
            self._redispatch(
                bucket, reqs,
                ReplicaUnavailable(
                    f"replica {replica.index} quarantined ({reason}) with "
                    "queued work and no surviving replica"
                ),
                count_retry=False,
            )

    def _supervise(self) -> None:
        while not self._stop_supervisor.wait(
            self.supervision.scan_interval_sec
        ):
            try:
                self._supervise_once()
            except Exception as err:  # pragma: no cover - defensive
                print(
                    f"ReplicaPool supervisor error ({self.tier}): "
                    f"{type(err).__name__}: {err}",
                    file=sys.stderr,
                    flush=True,
                )

    def _supervise_once(self) -> None:
        now = time.perf_counter()
        expired: List[_Inflight] = []
        with self._lock:
            if self._closed:
                return
            for e in list(self._watch):
                if (
                    e.state == "live"
                    and e.deadline is not None
                    and e.deadline <= now
                ):
                    e.state = "aborted"
                    self._watch.discard(e)
                    if not e.probe:
                        e.replica.outstanding -= 1
                    expired.append(e)
        for e in expired:
            r = e.replica
            if e.probe:
                # The re-warm probe itself hung: the device is still
                # sick. The fresh launcher is now wedged on it, so a
                # respawn is mandatory — without one, the next probe
                # would queue behind the wedged thread forever and the
                # replica would strand in REWARMING.
                self._probe_failed(r, now, respawn=True)
                if not e.reqs[0].future.done():
                    e.reqs[0].future.set_exception(
                        ReplicaUnavailable("re-warm probe timed out")
                    )
                continue
            if e.bucket is None:
                # A hung OVERSIZE FALLBACK (fallback_watchdog_sec armed):
                # the wedge is the carrier THREAD and the engine's
                # default device — like fallback crashes, it says
                # nothing about this replica's health. Replace the
                # worker generation (freeing the queued work behind the
                # wedged launcher) WITHOUT a quarantine strike, and
                # requeue everything.
                with self._lock:
                    if self._closed:
                        continue
                    old_work, drained = self._retire_generation(r)
                old_work.put(_CLOSE)
                for item in drained:
                    self._redispatch(
                        item[0], item[1],
                        ReplicaUnavailable(
                            "work retired behind a hung oversize fallback"
                        ),
                        count_retry=False,
                    )
                self._redispatch(
                    e.bucket, e.reqs,
                    ReplicaUnavailable(
                        "oversize fallback hung past "
                        f"fallback_watchdog_sec="
                        f"{self.supervision.fallback_watchdog_sec}"
                    ),
                )
                continue
            with self._lock:
                r.hangs += 1
            self._quarantine(r, reason="hang")
            self._redispatch(
                e.bucket, e.reqs,
                ReplicaUnavailable(
                    f"replica {r.index} hung past the "
                    f"{self.supervision.watchdog_sec}s watchdog"
                ),
                exclude=r,
            )
        # The replica flag checks below run on a SNAPSHOT taken under
        # the pool lock: worker threads flip ``state`` under the lock
        # (crash -> SUSPECT in _on_batch_failure), and an unlocked scan
        # could pair a fresh state with a stale ``_next_rewarm_at`` /
        # ``_probe`` left over from the previous quarantine cycle. Every
        # transition helper re-checks state under the lock before
        # acting, so the snapshot is safe as well as consistent.
        with self._lock:
            scan = [
                (r, r.state, r._next_rewarm_at, r._probe)
                for r in self._replicas
            ]
        # Promote suspects to quarantine (their failed batch already
        # re-dispatched in _on_batch_failure).
        for r, state, _, _ in scan:
            if state == SUSPECT:
                self._quarantine(r, reason="crash")
        # Re-warm due quarantined replicas; reintegrate finished probes.
        for r, state, next_rewarm_at, probe in scan:
            if state == QUARANTINED and now >= next_rewarm_at:
                self._start_probe(r)
            elif state == REWARMING and probe is not None and probe.done():
                if probe.exception() is None:
                    self._reintegrate(r)
                else:
                    # The probe raised (launcher alive): back off and
                    # retry later — no respawn needed.
                    self._probe_failed(r, now, respawn=False)

    def _probe_failed(self, replica: _Replica, now: float, respawn: bool) -> None:
        """A re-warm probe hung (``respawn=True`` — its launcher is
        wedged and must be replaced) or raised (``respawn=False``): stay
        quarantined with a doubled backoff, ready for the next probe."""
        stale_probes: List = []
        with self._lock:
            if self._closed:
                return  # close() owns thread lifecycle from here on
            if replica.state == REWARMING:
                replica.state = QUARANTINED
            replica._rewarm_backoff = min(
                max(replica._rewarm_backoff, self.supervision.rewarm_backoff_sec) * 2,
                self.supervision.max_rewarm_backoff_sec,
            )
            replica._next_rewarm_at = now + replica._rewarm_backoff
            replica._probe = None
            if respawn:
                old_work, drained = self._retire_generation(replica)
                # A quarantined replica's queue only ever holds probes;
                # fail any stale ones rather than re-routing them.
                for item in drained:
                    stale_probes.extend(item[1])
        if respawn:
            old_work.put(_CLOSE)
        for p in stale_probes:
            if not p.future.done():
                p.future.set_exception(
                    ReplicaUnavailable("stale re-warm probe retired")
                )

    def _start_probe(self, replica: _Replica) -> None:
        """Push one watchdog-guarded probe batch through the replica's
        fresh threads and EXISTING executables (reused — zero compiles,
        which is what keeps the compile sentinel green across quarantine
        cycles)."""
        req = _ProbeRequest(probe_image(self._probe_bucket))
        with self._lock:
            if self._closed or replica.state != QUARANTINED:
                return
            replica.state = REWARMING
            replica._probe = req.future
            replica.work.put((self._probe_bucket, [req], 0, True))

    def _reintegrate(self, replica: _Replica) -> None:
        with self._lock:
            if replica.state != REWARMING:
                return
            replica.state = HEALTHY
            replica.reintegrations += 1
            replica._probe = None
            recovery = (
                time.perf_counter() - replica._quarantined_at
                if replica._quarantined_at is not None
                else 0.0
            )
            replica._quarantined_at = None
        self.stats.record_reintegration(recovery)

    # -- params / lifecycle --------------------------------------------

    def set_params(self, params) -> None:
        """Hot weight reload: place ``params`` on every replica's device
        and swap each replica's reference between batches.

        Attribute assignment is atomic under the GIL and a launch thread
        reads ``replica.params`` exactly once per batch, so every batch
        runs entirely on old or entirely on new weights — in-flight
        batches complete on the params they were launched with, and no
        request is dropped. The engine's own params swap too, so oversize
        fallbacks (the jit-cache path) serve the new weights as well.
        Callers validate tree structure / shapes / dtypes first (the AOT
        executables were lowered against them); see serving/server.py's
        reload endpoint.
        """
        self.engine.params = params
        for r in self._replicas:
            r.params = self.engine.replica_params(r.device)

    def close(self, timeout: float = 60.0) -> List[str]:
        """Drain every replica's queued work, stop the supervisor and all
        worker threads, and join them. A thread that fails to join within
        ``timeout`` (wedged in device work — the watchdog's quarry) is
        reported **loudly** on stderr and returned by name, never
        silently leaked: the caller (and the test suite's thread-leak
        guard) can see exactly which worker is stuck. Idempotent; safe
        from ``finally``."""
        with self._lock:
            if self._closed:
                return list(self.leaked_threads)
            self._closed = True
        self._stop_supervisor.set()
        threads: List[threading.Thread] = [self._supervisor]
        for r in self._replicas:
            r.work.put(_CLOSE)
            threads.extend([r._launcher, r._completer])
        threads.extend(self._old_threads)
        deadline = time.monotonic() + timeout
        for t in threads:
            t.join(timeout=max(0.0, deadline - time.monotonic()))
        leaked = [t.name for t in threads if t.is_alive()]
        # Published under the lock: a concurrent close() (batcher close
        # racing a test's finally) returns this list through the locked
        # early-exit above, and an unlocked publish could hand it a torn
        # view — the race threadlint R101 surfaced when leaked_threads
        # gained its guarded-by declaration.
        with self._lock:
            self.leaked_threads = leaked
        if leaked:
            print(
                f"ReplicaPool.close ({self.tier}): {len(leaked)} worker "
                f"thread(s) failed to join within {timeout:.1f}s — wedged "
                f"in device work and cannot be interrupted, only "
                f"abandoned: {leaked}",
                file=sys.stderr,
                flush=True,
            )
        return leaked
