"""Multi-device serving scale-out: a pool of per-device replicas under the
dynamic batcher (docs/SERVING.md "Replica pool").

WaterNet's serving forward is ~1 MFLOP/pixel with no cross-request state,
so aggregate images/sec should scale near-linearly with device count once
nothing serializes between devices — the data-parallel replica-pool shape
continuous-batching servers use (one request queue multiplexed over N
model replicas). PR 4's engine drove exactly one device; this pool places
**params and the AOT-warmed (bucket, max_batch) executable grid on every
serving device** and gives each replica its own launch and completion
threads, so

* host preprocessing + H2D + dispatch for replica *i*'s next batch,
* device compute on replica *j*, and
* D2H readback on replica *k*

all overlap freely — a blocking ``ten2arr`` on one device never stalls
dispatch or compute on another (the PR-2 pipeline discipline, per
device). The batcher's dispatcher routes each coalesced micro-batch to
the **least-loaded replica** (fewest outstanding batches, ties to the
lowest index — deterministic), and a bounded ``max_inflight_per_replica``
keeps every device double-buffered without letting any of them run away
with the queue.

Outputs are replica-count-invariant by construction: every replica runs
the same XLA program on the same params, and a request's output never
depends on its batchmates (the PR-4 exactness policy), so the same
request stream produces byte-identical results whether it lands on
replica 0 or 7 — pinned in tests/test_serving.py.

Scope: replicas are for unsharded engines (each replica is one whole
device). ``data_shards``/``spatial_shards`` engines already span their
mesh with a single executable and therefore always resolve to ONE
replica — the mesh *is* the parallelism there. Oversize requests (no
covering bucket) keep the jit-cache native-shape fallback and are pinned
to replica 0 so their compile accounting stays race-free.

All worker threads run under the input pipeline's ``THREAD_PREFIX`` so
the test suite's thread-leak guard covers pool shutdown too.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from waternet_tpu.data.pipeline import THREAD_PREFIX
from waternet_tpu.resilience import faults
from waternet_tpu.serving.bucketing import Bucket, BucketLadder
from waternet_tpu.serving.stats import ServingStats
from waternet_tpu.serving.warmup import warmup
from waternet_tpu.utils.tensor import ten2arr

_CLOSE = object()


def engine_jit_cache_size(engine) -> int:
    """Total executable-cache size of the engine's jit entry points, 0 when
    this jax build exposes no introspection — the probe the serving layer
    uses to count *real* compiles (growth across a call = executables
    built). Sums the forward and both fused programs so device-preprocess
    fallbacks are counted too."""
    total = 0
    for attr in ("_forward", "_fused", "_fused_padded"):
        sizer = getattr(getattr(engine, attr, None), "_cache_size", None)
        if callable(sizer):
            total += sizer()
    return total


def resolve_replicas(spec, engine=None) -> int:
    """``'auto'`` / ``N`` / ``None`` -> a concrete replica count.

    ``auto`` (and None/empty) means every local device — the tentpole
    default: a v5e-8 host serves with 8 replicas unless told otherwise.
    Sharded engines always resolve to 1: their one executable already
    spans the mesh, and stacking replicas on top would oversubscribe it.
    """
    import jax

    sharded = engine is not None and (
        getattr(engine, "data_shards", 1) > 1
        or getattr(engine, "spatial_shards", 1) > 1
    )
    n_local = max(1, len(jax.local_devices()))
    # Validate the spec BEFORE the sharded override: a typo'd
    # --serve-replicas must fail the same way whether or not the engine
    # happens to be sharded.
    text = "auto" if spec is None else str(spec).strip().lower()
    if text in ("", "auto"):
        return 1 if sharded else n_local
    try:
        n = int(text)
    except ValueError:
        raise ValueError(
            f"--serve-replicas must be 'auto' or a positive integer, got "
            f"{spec!r}"
        ) from None
    if n < 1:
        raise ValueError(f"--serve-replicas must be >= 1, got {n}")
    if n > n_local:
        raise ValueError(
            f"--serve-replicas {n} exceeds the {n_local} local device(s)"
        )
    if sharded and n != 1:
        # An EXPLICIT multi-replica request contradicts a sharded engine
        # (its one executable already spans the mesh) — refuse loudly
        # rather than silently serving on one replica; 'auto' resolves to
        # 1 without complaint.
        raise ValueError(
            f"--serve-replicas {n} conflicts with a sharded engine "
            "(data_shards/spatial_shards engines serve as ONE mesh-"
            "spanning replica; use --serve-replicas auto or 1)"
        )
    return n


class _Replica:
    """One serving device: its params copy, its executable grid, a work
    queue feeding a launch thread (host preprocess + async dispatch), and
    a bounded in-flight queue feeding a completion thread (the replica's
    one D2H sync point)."""

    def __init__(self, pool: "ReplicaPool", index: int, device):
        self.pool = pool
        self.index = index
        self.device = device
        self.params = pool.engine.replica_params(device)
        self.executables: Dict[Tuple[Bucket, int], object] = {}
        self.outstanding = 0  # batches dispatched, not yet completed (pool lock)
        self.work: queue.Queue = queue.Queue()
        # Launch at most max_inflight batches ahead of this replica's
        # completion sync: the device stays double-buffered, and a slow
        # D2H cannot pile unbounded device allocations behind it.
        self.inflight: queue.Queue = queue.Queue(maxsize=pool.max_inflight)
        self._launcher = threading.Thread(
            target=self._launch_loop,
            name=f"{THREAD_PREFIX}-serve-launch-{index}",
            daemon=True,
        )
        self._completer = threading.Thread(
            target=self._complete_loop,
            name=f"{THREAD_PREFIX}-serve-complete-{index}",
            daemon=True,
        )

    def start(self) -> None:
        self._launcher.start()
        self._completer.start()

    # -- launch side ---------------------------------------------------

    def _launch_loop(self) -> None:
        pool = self.pool
        while True:
            item = self.work.get()
            if item is _CLOSE:
                self.inflight.put(_CLOSE)
                return
            bucket, reqs, depth = item
            try:
                if bucket is None:
                    self._launch_fallback(reqs)
                    continue
                # Deterministic serving-side fault hook (docs/RESILIENCE.md):
                # an armed slow_replica@K stalls the K-th launch so drain /
                # deadline / shed paths can hold work in flight on cue.
                delay = faults.replica_launch_delay()
                if delay > 0.0:
                    time.sleep(delay)
                n_slots = pool.max_batch
                exe = self.executables[(bucket, n_slots)]
                images = [r.image for r in reqs]
                t0 = time.perf_counter()
                out = pool.engine.enhance_padded_async(
                    images, bucket, n_slots=n_slots, executable=exe,
                    params=self.params, device=self.device,
                )
                bh, bw = bucket
                pool.stats.record_batch(
                    n_real=len(reqs),
                    n_slots=n_slots,
                    real_px=sum(im.shape[0] * im.shape[1] for im in images),
                    padded_px=n_slots * bh * bw,
                    queue_depth=depth,
                    replica=self.index,
                    tier=pool.tier,
                )
                self.inflight.put((out, reqs, t0))
            except BaseException as err:
                for r in reqs:
                    if not r.future.done():
                        r.future.set_exception(err)
                self._done()

    def _launch_fallback(self, reqs) -> None:
        """Oversize for every bucket: native-shape forwards, one request
        each (mixed oversize shapes cannot stack). These go through the
        engine's jit cache on its default device, so any compile they
        cause is real — count it (stats.compiles is "executables built",
        warmup AND fallback). Always runs on replica 0, which keeps the
        cache-size probe single-threaded and race-free."""
        pool = self.pool
        for r in reqs:
            try:
                pool.stats.record_fallback()
                before = engine_jit_cache_size(pool.engine)
                t0 = time.perf_counter()
                out = pool.engine.enhance_async(r.image[None])
                grew = engine_jit_cache_size(pool.engine) - before
                if grew > 0:
                    pool.stats.record_compile(grew)
                self.inflight.put((out, [r], t0))
            except BaseException as err:
                if not r.future.done():
                    r.future.set_exception(err)
                self._done()

    # -- completion side -----------------------------------------------

    def _complete_loop(self) -> None:
        pool = self.pool
        while True:
            item = self.inflight.get()
            if item is _CLOSE:
                return
            out_dev, reqs, t0 = item
            try:
                arr = ten2arr(out_dev)  # this replica's one D2H sync
            except BaseException as err:
                for r in reqs:
                    if not r.future.done():
                        r.future.set_exception(err)
                self._done()
                continue
            t_done = time.perf_counter()
            for i, r in enumerate(reqs):
                h, w = r.image.shape[:2]
                r.future.set_result(arr[i, :h, :w])
                pool.stats.record_latency(
                    t_done - r.t_submit, replica=self.index, tier=pool.tier
                )
            pool.stats.record_replica_busy(self.index, t_done - t0)
            self._done()

    def _done(self) -> None:
        with self.pool._lock:
            self.outstanding -= 1

    def join(self, timeout: float) -> None:
        self._launcher.join(timeout=timeout)
        self._completer.join(timeout=timeout)


class ReplicaPool:
    """Place the serving executable grid on ``n_replicas`` local devices
    and multiplex dispatched micro-batches over them.

    Warmup compiles the full ``len(ladder) x len(batch_sizes) x
    n_replicas`` executable grid before construction returns, fanning the
    per-device compiles out over threads (serving/warmup.py) — no request
    ever pays a compile, on any replica, and the engine's jit caches
    never grow mid-serve (the PR-4 sentinel guarantee, now
    ``len(buckets) x replicas`` executables).
    """

    def __init__(
        self,
        engine,
        ladder: BucketLadder,
        batch_sizes: Sequence[int],
        n_replicas: int = 1,
        max_inflight_per_replica: int = 2,
        stats: Optional[ServingStats] = None,
        warmup_verbose: bool = False,
        tier: str = "quality",
    ):
        import jax

        if max_inflight_per_replica < 1:
            raise ValueError(
                f"max_inflight_per_replica must be >= 1, got "
                f"{max_inflight_per_replica}"
            )
        sharded = engine.data_shards > 1 or engine.spatial_shards > 1
        if sharded and n_replicas != 1:
            raise ValueError(
                "sharded engines serve as ONE replica spanning their mesh; "
                f"got n_replicas={n_replicas} with data_shards="
                f"{engine.data_shards}, spatial_shards={engine.spatial_shards}"
            )
        devices = jax.local_devices()
        if n_replicas > len(devices):
            raise ValueError(
                f"n_replicas={n_replicas} exceeds the {len(devices)} local "
                "device(s)"
            )
        self.engine = engine
        self.max_batch = max(int(b) for b in batch_sizes)
        self.max_inflight = int(max_inflight_per_replica)
        self.stats = stats if stats is not None else ServingStats()
        self.stats.set_replicas(n_replicas)
        # Which serving tier this pool's batches/requests count under
        # (docs/SERVING.md "Quality tiers"): "quality" for the PR-4/5
        # teacher pipeline, "fast" for the CAN-student pool a tier-routing
        # DynamicBatcher stacks next to it on the same devices.
        self.tier = str(tier)
        self.stats.declare_tier(self.tier)
        self._lock = threading.Lock()
        self._closed = False
        # A single replica keeps the engine's default placement (device
        # None) — byte-for-byte the PR-4 single-device behavior, and the
        # only valid form for sharded engines.
        dev_list = [None] if n_replicas == 1 else list(devices[:n_replicas])
        self._replicas: List[_Replica] = [
            _Replica(self, i, dev) for i, dev in enumerate(dev_list)
        ]
        grids = warmup(
            engine, ladder, batch_sizes, stats=self.stats,
            verbose=warmup_verbose,
            replicas=[(r.index, r.device, r.params) for r in self._replicas],
        )
        for r in self._replicas:
            r.executables = grids[r.index]
        for r in self._replicas:
            r.start()

    @property
    def n_replicas(self) -> int:
        return len(self._replicas)

    def dispatch(self, bucket: Optional[Bucket], reqs, queue_depth: int = 0) -> None:
        """Route one coalesced micro-batch (or a fallback group for
        ``bucket is None``) to the least-loaded replica. Never blocks:
        work queues are unbounded — the per-replica in-flight bound
        throttles device memory, not the dispatcher."""
        if not reqs:
            return
        with self._lock:
            if bucket is None:
                replica = self._replicas[0]
            else:
                replica = min(
                    self._replicas, key=lambda r: (r.outstanding, r.index)
                )
            # Fallback groups launch one forward per request.
            replica.outstanding += len(reqs) if bucket is None else 1
        replica.work.put((bucket, reqs, queue_depth))

    def set_params(self, params) -> None:
        """Hot weight reload: place ``params`` on every replica's device
        and swap each replica's reference between batches.

        Attribute assignment is atomic under the GIL and a launch thread
        reads ``replica.params`` exactly once per batch, so every batch
        runs entirely on old or entirely on new weights — in-flight
        batches complete on the params they were launched with, and no
        request is dropped. The engine's own params swap too, so oversize
        fallbacks (replica 0's jit-cache path) serve the new weights as
        well. Callers validate tree structure / shapes / dtypes first
        (the AOT executables were lowered against them); see
        serving/server.py's reload endpoint.
        """
        self.engine.params = params
        for r in self._replicas:
            r.params = self.engine.replica_params(r.device)

    def close(self) -> None:
        """Drain every replica's queued work, stop and join all worker
        threads. Idempotent; safe from ``finally``."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        for r in self._replicas:
            r.work.put(_CLOSE)
        for r in self._replicas:
            r.join(timeout=60.0)
