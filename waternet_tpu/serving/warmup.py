"""AOT warmup: compile every (bucket, batch) executable before serving.

A mid-serve XLA compile is a multi-second stall on the request path — the
exact pathology bucketing exists to remove — so the batcher refuses to
rely on jit's compile-on-first-call. At startup this module
``.lower().compile()``s one executable per (bucket, batch-slot) shape via
:meth:`InferenceEngine.aot_compile_padded`; dispatch then calls those
executables directly and the engine's jit cache is never consulted for a
bucketed request. That makes the no-recompile guarantee *testable*: the
PR-3 ``compile_sentinel`` fixture arms ``engine._forward`` after warmup
and any growth during serving fails the test
(tests/test_serving.py::test_bucketed_stream_compiles_len_buckets_executables).
"""

from __future__ import annotations

import time
from typing import Dict, Optional, Sequence, Tuple

from waternet_tpu.serving.bucketing import Bucket, BucketLadder
from waternet_tpu.serving.stats import ServingStats


def warmup(
    engine,
    ladder: BucketLadder,
    batch_sizes: Sequence[int],
    stats: Optional[ServingStats] = None,
    verbose: bool = False,
) -> Dict[Tuple[Bucket, int], object]:
    """Compile the full (bucket x batch-size) executable grid.

    Returns ``{((bh, bw), n): executable}``; every compile is counted in
    ``stats`` (the bench contract's ``compiles`` field). With the
    persistent XLA compile cache enabled (utils/platform.py) repeated
    server startups deserialize instead of recompiling, but each shape
    still counts as one executable here — the number the acceptance
    criterion bounds is executables built, not cache misses.
    """
    executables: Dict[Tuple[Bucket, int], object] = {}
    for bucket in ladder:
        for n in sorted(set(int(b) for b in batch_sizes)):
            t0 = time.perf_counter()
            executables[(bucket, n)] = engine.aot_compile_padded(n, bucket)
            if stats is not None:
                stats.record_compile()
            if verbose:
                bh, bw = bucket
                print(
                    f"serving warmup: compiled {n}x{bh}x{bw} in "
                    f"{time.perf_counter() - t0:.1f}s"
                )
    return executables
