"""AOT warmup: compile every (bucket, batch[, replica]) executable before
serving.

A mid-serve XLA compile is a multi-second stall on the request path — the
exact pathology bucketing exists to remove — so the batcher refuses to
rely on jit's compile-on-first-call. At startup this module
``.lower().compile()``s one executable per (bucket, batch-slot) shape via
:meth:`InferenceEngine.aot_compile_padded` — and, when a replica pool is
serving, one per **replica device** (``replicas=[(index, device, params),
...]``), fanning the per-device compiles out over a thread pool so an
N-replica server's warmup approaches the cost of one device's, not N
times it (XLA compilation releases the GIL). Dispatch then calls those
executables directly and the engine's jit caches are never consulted for
a bucketed request. That makes the no-recompile guarantee *testable*: the
PR-3 ``compile_sentinel`` fixture arms the engine's jits after warmup and
any growth during serving fails the test
(tests/test_serving.py::test_bucketed_stream_compiles_len_buckets_executables).
"""

from __future__ import annotations

import time
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

from waternet_tpu.data.pipeline import THREAD_PREFIX
from waternet_tpu.serving.bucketing import Bucket, BucketLadder
from waternet_tpu.serving.stats import ServingStats

#: Upper bound on concurrent warmup compile threads: enough to cover a
#: full pod-slice host (8 replicas) without turning a many-bucket ladder
#: into a thread stampede.
MAX_WARMUP_THREADS = 8


def probe_image(bucket: Bucket) -> np.ndarray:
    """Deterministic uint8 probe canvas at exactly ``bucket`` shape, for
    replica re-warm (docs/SERVING.md "Fault isolation"): after a
    quarantine, the supervisor pushes one probe batch through the
    replica's existing AOT executables — the exact-fit shape means zero
    pad work and zero compiles (warmup already built the executable; a
    re-warm REUSES it, which is what keeps the no-mid-serve-compile
    sentinel green across quarantine cycles). A fixed gradient rather
    than zeros, so the probe rides the same output-sanity-guard path
    real batches do without tripping the all-zero-canvas detector on
    degenerate params."""
    bh, bw = bucket
    yy, xx = np.mgrid[0:bh, 0:bw]
    plane = ((yy * 3 + xx * 5) % 251).astype(np.uint8)
    return np.repeat(plane[..., None], 3, axis=-1)


def warmup(
    engine,
    ladder: BucketLadder,
    batch_sizes: Sequence[int],
    stats: Optional[ServingStats] = None,
    verbose: bool = False,
    replicas=None,
):
    """Compile the full (bucket x batch-size[, replica]) executable grid.

    Without ``replicas`` (the pre-pool form, kept for direct callers):
    returns ``{((bh, bw), n): executable}`` compiled for the engine's
    default placement. With ``replicas`` — a list of ``(index, device,
    params)`` triples from the pool — returns ``{index: {((bh, bw), n):
    executable}}`` with each grid pinned to its replica's device, the
    compiles running in parallel threads.

    Every compile is counted in ``stats`` (the bench contract's
    ``compiles`` field): an N-replica pool builds exactly
    ``len(ladder) * len(batch_sizes) * N`` executables. With the
    persistent XLA compile cache enabled (utils/platform.py) repeated
    server startups deserialize instead of recompiling, but each shape
    still counts as one executable here — the number the acceptance
    criterion bounds is executables built, not cache misses.
    """
    sizes = sorted(set(int(b) for b in batch_sizes))
    if replicas is None:
        jobs = [(None, None, None, bucket, n) for bucket in ladder for n in sizes]
    else:
        jobs = [
            (index, device, params, bucket, n)
            for (index, device, params) in replicas
            for bucket in ladder
            for n in sizes
        ]

    def compile_one(job):
        index, device, params, bucket, n = job
        t0 = time.perf_counter()
        exe = engine.aot_compile_padded(n, bucket, device=device, params=params)
        if stats is not None:
            stats.record_compile()
        if verbose:
            bh, bw = bucket
            where = "" if index is None else f" on replica {index}"
            print(
                f"serving warmup: compiled {n}x{bh}x{bw}{where} in "
                f"{time.perf_counter() - t0:.1f}s"
            )
        return index, bucket, n, exe

    if len(jobs) == 1 or replicas is None:
        results = [compile_one(j) for j in jobs]
    else:
        # Deliberate compile fan-out: this is server startup, the one
        # place compiles belong; everything after dispatches prebuilt
        # executables.
        with ThreadPoolExecutor(
            max_workers=min(MAX_WARMUP_THREADS, len(jobs)),
            thread_name_prefix=f"{THREAD_PREFIX}-serve-warmup",
        ) as pool:
            results = list(pool.map(compile_one, jobs))

    if replicas is None:
        return {(bucket, n): exe for _, bucket, n, exe in results}
    grids: Dict[int, Dict[Tuple[Bucket, int], object]] = {
        index: {} for (index, _, _) in replicas
    }
    for index, bucket, n, exe in results:
        grids[index][(bucket, n)] = exe
    return grids
