"""Serving front door: an asyncio, stdlib-only HTTP gateway over the
dynamic batcher (docs/SERVING.md "Front door").

The PR-4/5 serving engine was fast but unreachable — only the
`inference.py` CLI could feed it. This process turns it into a service:
``python -m waternet_tpu.serving.server --weights w.npz`` (or the
``waternet-serve`` console entry) listens on one port and feeds decoded
request images straight into the :class:`DynamicBatcher` queue, hardened
for the traffic patterns a library never sees:

* **Admission control + bounded backpressure.** The batcher's request
  queue is bounded (``max_queue``); past the ``admit_watermark`` the
  server sheds with ``429 Too Many Requests`` + ``Retry-After`` instead
  of queueing forever — under overload, queueing delay and RSS stay
  bounded and the client is told to back off. Every shed is counted
  (``shed_count``), and no admitted request is ever silently dropped:
  each one resolves to a response or a counted deadline expiry.
* **Per-request deadlines.** An ``X-Deadline-Ms`` header becomes an
  absolute deadline propagated into the batcher: a budget that cannot be
  met is rejected up front with ``504``; a pending request whose budget
  runs out is dropped at dispatch with a counter (not computed); and the
  deadline CLAMPS the coalescing window, so a lone request never waits
  out a ``max_wait_ms`` it cannot afford.
* **Graceful drain.** SIGTERM/SIGINT (latched by the PR-1 resilience
  control plane's :class:`PreemptionGuard` — a flag, no work in the
  handler) stops admission (``503`` + ``Connection: close``), drains
  every in-flight batch through the replica pool, flushes the stats
  JSON, and exits 0 within ``grace_sec``.
* **Hot weight reload.** ``POST /admin/reload`` swaps
  ``replica_params`` atomically between batches without dropping
  in-flight requests, validating tree structure / shapes / dtypes
  through the same :func:`params_mismatch_report` path the trainer's
  restore uses and rolling back (no swap) on mismatch. The AOT
  executables take params as a runtime argument, so a valid reload
  never recompiles — the compile-sentinel guarantee holds across it.
* **Readiness + observability.** ``GET /healthz`` reports ready only
  after AOT warmup completes (and not-draining), and carries the
  replica-supervision verdict: ``ok`` / ``degraded`` (some replicas
  quarantined, still 200 — the pool is serving) / ``unhealthy`` (a tier
  with zero available replicas, 503), with the per-tier
  ``{replica: state}`` map. ``GET /stats`` exposes the live
  :class:`ServingStats` schema (docs/SERVING.md), including
  ``queue_depth`` / ``shed_count`` / ``deadline_expired`` and the
  fault-isolation counters (``retried`` / ``downgraded`` /
  ``quarantines`` / ``reintegrations`` / ``nan_outputs``).
* **Fault isolation + brown-out.** The batcher's replica pools run under
  supervision (docs/SERVING.md "Fault isolation"): a crashing or hung
  replica is quarantined, its requests transparently re-dispatched
  (byte-identical results), and the replica re-warmed and reintegrated.
  Quality requests that opt in via ``X-Tier-Allow-Downgrade: 1`` are
  served by the fast tier instead of shed once the queue passes the
  downgrade watermark; ``X-Tier-Served`` on the response names the tier
  that actually served.

* **Stream sessions.** ``POST /stream`` opens a live video session
  (waternet_tpu/serving/streams.py, docs/SERVING.md "Streaming"):
  length-prefixed JPEG/PNG frames in, enhanced frames out in strict
  submit order on the same connection, each frame under a freshness
  budget derived from the stream's declared fps, with explicit
  drop-oldest / brown-out / refuse-new-sessions degradation under
  overload. Stream admission is bounded by ``--max-streams``; the
  per-session delivery window by ``--stream-window``.

* **Compute reuse** (waternet_tpu/serving/reuse.py, both off by
  default). ``--stream-reuse-threshold`` arms per-stream temporal
  gating: a frame whose cheap decimated delta against the last
  computed frame is under threshold is answered from the cached
  enhanced frame (an ``R`` record) without entering the batcher,
  bounded by the ``--stream-max-reuse-run`` staleness cap.
  ``--response-cache N`` arms a bounded LRU over rendered ``/enhance``
  answers keyed on (payload digest, tier, bucket ladder, params
  generation) — hits stamp ``X-Cache: hit``, reloads invalidate, and
  downgraded answers are never stored.

Endpoints: ``POST /enhance`` (image file bytes in, PNG out — the body
is whatever ``cv2.imdecode`` reads, which is exactly what ``cv2.imread``
reads on the local path, so the CLI and the service stay behaviorally
interchangeable via ``inference.py --serve-url``); ``POST /stream``
(length-prefixed frame session); ``GET /healthz``; ``GET /stats``;
``GET /metrics`` (the same stats in Prometheus text format);
``POST /admin/reload``.

The HTTP layer is deliberately hand-rolled on ``asyncio.start_server``
(persistent connections, Content-Length bodies): the container bakes no
HTTP framework, and the protocol surface a batcher front door needs is
four routes. Request decode runs in the loop's default executor and
response encode in a sized ``--encode-threads`` pool with per-thread
reusable staging buffers (the copy-lean response path), so the event
loop never blocks on cv2 and encode bursts never starve control work.
``--coalesce`` picks the batching window policy (adaptive by default;
docs/SERVING.md "Adaptive scheduling") and ``--png-level`` trades
response-encode CPU for bytes.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import signal
import sys
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Optional, Tuple

import numpy as np

from waternet_tpu.data.pipeline import THREAD_PREFIX
from waternet_tpu.obs import trace
from waternet_tpu.obs.prometheus import render_prometheus
from waternet_tpu.resilience import faults
from waternet_tpu.resilience.heartbeat import (
    ENV_WORKER_GENERATION,
    ENV_WORKER_ID,
    ENV_WORKER_SLOT,
    HeartbeatWriter,
)
from waternet_tpu.resilience.preemption import PreemptionGuard
from waternet_tpu.serving.batcher import (
    DeadlineExpired,
    DynamicBatcher,
    QueueFull,
    UnknownTier,
    resolve_ladder,
)
from waternet_tpu.serving.replicas import (
    AVAILABLE_STATES,
    ReplicaUnavailable,
    SupervisionConfig,
)
from waternet_tpu.serving.reuse import DEFAULT_MAX_REUSE_RUN, ResponseCache
from waternet_tpu.serving.stats import ServingStats
from waternet_tpu.serving.streams import StreamConfig, StreamManager

_REASONS = {
    200: "OK",
    400: "Bad Request",
    404: "Not Found",
    405: "Method Not Allowed",
    409: "Conflict",
    413: "Payload Too Large",
    429: "Too Many Requests",
    500: "Internal Server Error",
    503: "Service Unavailable",
    504: "Gateway Timeout",
}

#: Request bodies above this are refused with 413 before buffering: a
#: front door that buffers arbitrary uploads is an OOM, not a service.
MAX_BODY_BYTES = 64 << 20


class ReloadMismatch(RuntimeError):
    """Hot reload refused: the new weights do not fit the serving model
    (tree / shape / dtype diff in ``args[0]``). Nothing was swapped."""


def _request_id(headers: dict) -> str:
    """The request's correlation id: the client's ``X-Request-Id`` when
    it is a sane header token, else a fresh one. The id is echoed back
    verbatim in a response header, so anything that could smuggle CRLF
    or grow unbounded is replaced, not escaped."""
    raw = headers.get("x-request-id", "").strip()
    if (
        raw
        and len(raw) <= 128
        and all(c.isalnum() or c in "-_.:/" for c in raw)
    ):
        return raw
    return trace.new_request_id()


def _content_length(headers: dict) -> int:
    """Parsed Content-Length, 0 for absent/malformed/negative — the ONE
    parse both the reader and the router use, so a header like ``abc``
    (or ``-1``, which would make ``readexactly`` raise) degrades to an
    empty body instead of an unhandled ValueError."""
    try:
        return max(0, int(headers.get("content-length", "0")))
    except ValueError:
        return 0


def _decode_request_image(body: bytes):
    """Image file bytes -> (bgr, rgb) exactly as the local CLI decodes
    them (``cv2.imdecode`` == ``cv2.imread`` on file bytes), or None.

    None for anything undecodable, INCLUDING the empty body: imdecode
    returns None for garbage bytes but RAISES on an empty buffer, and a
    raise here would kill the connection handler instead of answering
    400."""
    import cv2

    if not body:
        return None
    try:
        bgr = cv2.imdecode(np.frombuffer(body, np.uint8), cv2.IMREAD_COLOR)
    except cv2.error:
        return None
    if bgr is None:
        return None
    return cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB)


# Reusable per-thread BGR staging canvas for the encode path (the
# copy-lean response path, docs/SERVING.md "Adaptive scheduling"):
# cvtColor writes into a thread-local dst instead of allocating a fresh
# canvas per response, so a sized encode pool settles on one buffer per
# thread per shape. threading.local IS the guard — no cross-thread
# sharing exists, so no lock (and no guarded-by) is needed.
_ENCODE_TL = threading.local()


def _encode_response_png(
    rgb: np.ndarray, png_level: Optional[int] = None
) -> bytes:
    """Enhanced RGB -> PNG bytes in file orientation (BGR), the inverse
    of :func:`_decode_request_image` — a client that imdecodes + imwrites
    the response produces byte-identical files to local serving.

    ``png_level`` (0-9) maps to ``IMWRITE_PNG_COMPRESSION``; None (the
    default) omits the parameter entirely, so the output stays
    byte-identical to every release before the knob existed."""
    import cv2

    bgr = getattr(_ENCODE_TL, "bgr", None)
    if bgr is None or bgr.shape != rgb.shape:
        bgr = np.empty_like(rgb)
        _ENCODE_TL.bgr = bgr
    cv2.cvtColor(rgb, cv2.COLOR_RGB2BGR, dst=bgr)
    params = (
        [] if png_level is None
        else [int(cv2.IMWRITE_PNG_COMPRESSION), int(png_level)]
    )
    ok, buf = cv2.imencode(".png", bgr, params)
    if not ok:
        raise RuntimeError("PNG encode failed")
    return buf.tobytes()


class ServingServer:
    """One HTTP front door over one engine + one :class:`DynamicBatcher`.

    Lifecycle: construct (cheap — no jax work), then either
    :meth:`run` (blocking; the ``main()`` path, installs the
    PreemptionGuard) or :meth:`start_background` (tests/bench: serves
    from a daemon thread; stop with :meth:`request_drain` +
    :meth:`join`). The batcher — and its AOT warmup — is built on a
    background thread after the socket is already listening, so
    ``/healthz`` answers (not ready) during warmup and a load balancer
    can health-check a starting server.
    """

    def __init__(
        self,
        engine,
        ladder,
        host: str = "127.0.0.1",
        port: int = 0,
        max_batch: int = 8,
        max_wait_ms: float = 10.0,
        replicas=1,
        max_queue: int = 256,
        admit_watermark: Optional[int] = None,
        grace_sec: float = 30.0,
        min_deadline_ms: float = 0.0,
        stats: Optional[ServingStats] = None,
        fast_engine=None,
        supervision: Optional[SupervisionConfig] = None,
        downgrade_watermark: Optional[int] = None,
        max_streams: int = 8,
        stream_window: int = 8,
        slo: Optional[str] = None,
        stream_reuse_threshold: Optional[float] = None,
        stream_max_reuse_run: int = DEFAULT_MAX_REUSE_RUN,
        response_cache: int = 0,
        obs_loop_lag: bool = False,
        coalesce: str = "fixed",
        png_level: Optional[int] = None,
        encode_threads: int = 2,
    ):
        if png_level is not None and not (0 <= int(png_level) <= 9):
            raise ValueError(
                f"png_level must be in [0, 9] (zlib levels), got {png_level}"
            )
        if encode_threads < 1:
            raise ValueError(
                f"encode_threads must be >= 1, got {encode_threads}"
            )
        if admit_watermark is None:
            # Shed before QueueFull would fire: the watermark is the soft
            # limit with headroom for requests already racing past it.
            admit_watermark = max(1, (3 * max_queue) // 4)
        if downgrade_watermark is None:
            # Brown-out trips where shedding would: an opted-in quality
            # request at the admit watermark downgrades instead of 429ing
            # (only meaningful with a fast engine configured).
            downgrade_watermark = admit_watermark
        self.engine = engine
        self.fast_engine = fast_engine
        self.ladder = ladder
        self.host = host
        self.port = int(port)
        self.max_batch = int(max_batch)
        self.max_wait_ms = float(max_wait_ms)
        # Coalescing mode (docs/SERVING.md "Adaptive scheduling"):
        # "fixed" holds every partial batch for max_wait_ms (the
        # constructor default — the historical behavior); "adaptive"
        # treats max_wait_ms as a CAP and sizes the effective window
        # from the live arrival rate (the CLI default). Validated by
        # the batcher's CoalesceController at warmup.
        self.coalesce = str(coalesce)
        self.png_level = None if png_level is None else int(png_level)
        self.encode_threads = int(encode_threads)
        self._encode_pool = None  # built in _main, closed in its finally
        self.replicas = replicas
        self.max_queue = int(max_queue)
        self.admit_watermark = int(admit_watermark)
        self.grace_sec = float(grace_sec)
        self.min_deadline_ms = float(min_deadline_ms)
        self.supervision = supervision
        self.downgrade_watermark = int(downgrade_watermark)
        self.max_streams = int(max_streams)
        self.stream_window = int(stream_window)
        # Temporal reuse (docs/SERVING.md "Temporal reuse & response
        # cache"): the server-wide default gating threshold (None = off;
        # sessions override per connection with X-Stream-Reuse) and the
        # staleness cap on consecutive reuses.
        self.stream_reuse_threshold = (
            None if stream_reuse_threshold is None
            else float(stream_reuse_threshold)
        )
        self.stream_max_reuse_run = int(stream_max_reuse_run)
        self.stats = stats if stats is not None else ServingStats()
        # Content-addressed /enhance response cache (0 entries = off).
        # Keyed on (payload digest, tier, ladder identity, params
        # generation); only never-downgraded answers are stored, so a
        # hit is policy-correct for any requester of that tier.
        self.response_cache = (
            ResponseCache(
                response_cache, ladder_id=",".join(ladder.describe())
            )
            if response_cache
            else None
        )
        if self.response_cache is not None:
            self.stats.cache_probe = self.response_cache.counters
        # Event-loop-lag sampler (--obs-loop-lag, default off): a
        # LoopTracer with an infinite threshold — gauges only, never
        # raises — feeding the loop_lag block on /stats and /metrics.
        # Installed/armed in _main, so the probe reports zeros until
        # the loop actually serves.
        self.obs_loop_lag = bool(obs_loop_lag)
        self._loop_tracer = None
        self.slo_spec = slo
        if slo:
            from waternet_tpu.obs.slo import SloEngine, parse_slo

            # Parse errors surface at construction (bad --slo exits the
            # CLI before any engine warms), and the armed engine grades
            # /healthz and annotates /stats + /metrics from then on.
            self.stats.arm_slo(SloEngine(parse_slo(slo), spec=slo))
        # Fleet identity (docs/SERVING.md "Fleet"): when the fleet router
        # spawned this process it named it via env; the name is stamped
        # on every /enhance answer and stream head as X-Worker-Id so
        # client-side ledgers can split accounting by the worker that
        # actually served (waternet-loadgen --per-worker).
        self.worker_id = os.environ.get(ENV_WORKER_ID) or None
        self._ident: Tuple = (
            (("X-Worker-Id", self.worker_id),) if self.worker_id else ()
        )
        self.batcher: Optional[DynamicBatcher] = None
        self.streams: Optional[StreamManager] = None
        self.bound_port: Optional[int] = None
        self.ready = threading.Event()
        self.draining = threading.Event()
        self._bound = threading.Event()
        self._drain_flag = False
        self._inflight = 0  # guarded-by: self._inflight_lock
        self._inflight_lock = threading.Lock()
        self._reload_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._exit_code: Optional[int] = None
        self._error: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------

    def run(self, install_signal_handlers: bool = True) -> int:
        """Serve until drain completes; returns the process exit code
        (0 = clean drain within the grace window)."""
        return asyncio.run(self._main(install_signal_handlers))

    def start_background(self, timeout: float = 30.0) -> "ServingServer":
        """Tests/bench entry: serve from a daemon thread (no signal
        handlers — trigger shutdown with :meth:`request_drain`). Returns
        once the socket is bound (``bound_port`` is set); warmup may
        still be running — poll :meth:`wait_ready`."""

        def _target():
            try:
                self._exit_code = self.run(install_signal_handlers=False)
            except BaseException as err:  # surfaced by wait_ready/join
                self._error = err
                self._exit_code = 1
                self._bound.set()

        self._thread = threading.Thread(
            target=_target, name=f"{THREAD_PREFIX}-serve-http", daemon=True
        )
        self._thread.start()
        if not self._bound.wait(timeout):
            raise RuntimeError("server did not bind within the timeout")
        if self._error is not None:
            raise RuntimeError("server failed to start") from self._error
        return self

    def wait_ready(self, timeout: float = 120.0) -> None:
        deadline = time.monotonic() + timeout
        while not self.ready.wait(0.1):
            if self._error is not None:
                raise RuntimeError("server died during warmup") from self._error
            if time.monotonic() > deadline:
                raise RuntimeError("server warmup did not finish in time")

    def request_drain(self) -> None:
        """Thread-safe drain trigger — what SIGTERM does, callable."""
        self._drain_flag = True

    def join(self, timeout: float = 120.0) -> int:
        """Wait for a background server to finish; returns its exit code."""
        assert self._thread is not None, "server was not started in background"
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise RuntimeError("server did not exit within the timeout")
        return int(self._exit_code)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.bound_port}"

    async def _main(self, install_signals: bool) -> int:
        guard = PreemptionGuard() if install_signals else None
        if guard is not None:
            guard.__enter__()
        server = None
        beat_task = None
        if self.obs_loop_lag:
            from waternet_tpu.analysis.looptrace import LoopTracer

            # Infinite threshold: production sampling records max/p99
            # lag for the loop_lag gauge but never raises — the test
            # fixture (conftest looptrace) is where thresholds fail.
            self._loop_tracer = LoopTracer(threshold_ms=float("inf"))
            self._loop_tracer.install()
            tracer = self._loop_tracer
            self.stats.loop_lag_probe = lambda: {
                "enabled": True, **tracer.gauge()
            }
        try:
            server = await asyncio.start_server(
                self._handle_connection, self.host, self.port
            )
            self.bound_port = server.sockets[0].getsockname()[1]
            self._bound.set()
            print(
                f"waternet-serve: listening on http://{self.host}:"
                f"{self.bound_port}",
                flush=True,
            )

            # Fleet heartbeats (resilience/heartbeat.py env contract):
            # startup-phase beats announce the warmup, serve-phase beats
            # prove steady state. The beat task rides THIS event loop on
            # purpose — a wedged loop (gateway_hang) stops beats exactly
            # when /healthz stops answering, so the router's two health
            # signals agree by construction.
            hb = HeartbeatWriter.resolve(
                process_id=int(os.environ.get(ENV_WORKER_SLOT, "0") or 0),
                generation=int(
                    os.environ.get(ENV_WORKER_GENERATION, "0") or 0
                ),
            )
            if hb is not None:
                hb.beat(phase="startup", force=True)

                async def _beat_loop():
                    while True:
                        hb.beat(
                            step=self.stats.requests,
                            phase=(
                                "serve" if self.ready.is_set()
                                else "startup"
                            ),
                        )
                        await asyncio.sleep(hb.min_interval_sec / 2)

                beat_task = asyncio.get_running_loop().create_task(
                    _beat_loop()
                )

            # AOT warmup in the executor: /healthz answers (503,
            # ready:false) the whole time, so orchestrators see a
            # live-but-not-ready process instead of a connection refusal.
            def _build_batcher():
                return DynamicBatcher(
                    self.engine,
                    self.ladder,
                    max_batch=self.max_batch,
                    max_wait_ms=self.max_wait_ms,
                    stats=self.stats,
                    replicas=self.replicas,
                    max_queue=self.max_queue,
                    fast_engine=self.fast_engine,
                    supervision=self.supervision,
                    downgrade_watermark=self.downgrade_watermark,
                    coalesce=self.coalesce,
                )

            # Sized encode pool (the copy-lean response path): response
            # PNG encodes get their OWN bounded pool instead of the
            # loop's shared default executor, so a burst of encodes can
            # never starve decode / reload / heartbeat work — and each
            # pool thread settles on one reusable BGR staging buffer.
            self._encode_pool = ThreadPoolExecutor(
                max_workers=self.encode_threads,
                thread_name_prefix=f"{THREAD_PREFIX}-serve-encode",
            )
            loop = asyncio.get_running_loop()
            self.batcher = await loop.run_in_executor(None, _build_batcher)
            self.streams = StreamManager(
                batcher=self.batcher,
                stats=self.stats,
                max_streams=self.max_streams,
                window=self.stream_window,
                admit_watermark=self.admit_watermark,
                decode=_decode_request_image,
                encode=self._encode_png,
                draining=self.draining,
            )
            self.ready.set()
            print(
                f"waternet-serve: ready ({len(self.ladder)} buckets x "
                f"{self.batcher.n_replicas} replicas x "
                f"{len(self.batcher.tiers)} tiers "
                f"[{', '.join(self.batcher.tiers)}] warmed, batch "
                f"{self.batcher.max_batch}, coalesce "
                f"{self.batcher.coalesce_mode} cap {self.max_wait_ms:g} ms)",
                flush=True,
            )

            # Serve until a drain is requested (signal or request_drain).
            while not (
                self._drain_flag or (guard is not None and guard.requested)
            ):
                await asyncio.sleep(0.05)

            # Drain: admission is off the moment this is set (handlers
            # answer 503 + Connection: close); everything already
            # admitted flows through the replica pool to completion.
            self.draining.set()
            print("waternet-serve: draining", flush=True)
            self.batcher.drain()  # flush partial batches immediately
            deadline = time.monotonic() + self.grace_sec
            clean = False
            while time.monotonic() < deadline:
                with self._inflight_lock:
                    inflight = self._inflight
                if (
                    inflight == 0
                    and self.batcher.queue_depth() == 0
                    and (
                        self.streams is None
                        or self.streams.active_count() == 0
                    )
                ):
                    clean = True
                    break
                await asyncio.sleep(0.02)
            # Let the last response bytes reach their sockets before the
            # loop (and its connections) goes away.
            await asyncio.sleep(0.05)
            return 0 if clean else 1
        finally:
            if self._loop_tracer is not None:
                self._loop_tracer.uninstall()
            if self._encode_pool is not None:
                self._encode_pool.shutdown(wait=True)
            if beat_task is not None:
                beat_task.cancel()
            if server is not None:
                server.close()
                await server.wait_closed()
            if self.batcher is not None:
                self.batcher.close()
            if guard is not None:
                guard.__exit__(None, None, None)
            # Stats flush: the drain contract — the run's numbers survive
            # the process, in the same JSON block the CLI prints.
            print(self.stats.to_json(), flush=True)

    def _encode_png(self, rgb: np.ndarray) -> bytes:
        """The server's configured encode: :func:`_encode_response_png`
        at this server's ``--png-level`` (None = cv2's default, byte-
        identical to pre-knob releases)."""
        return _encode_response_png(rgb, self.png_level)

    def _config_block(self) -> dict:
        """The ``config`` block of /stats: the scheduling knobs an
        operator needs to interpret the gauges next to them
        (docs/SERVING.md "Adaptive scheduling")."""
        return {
            "coalesce": self.coalesce,
            "max_wait_ms": self.max_wait_ms,
            "max_batch": self.max_batch,
            "png_level": self.png_level,
            "encode_threads": self.encode_threads,
        }

    # -- HTTP plumbing -------------------------------------------------

    async def _handle_connection(self, reader, writer):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                keep = await self._dispatch(req, reader, writer)
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away; nothing to answer
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(
        self, reader
    ) -> Optional[Tuple[str, str, dict, bytes]]:
        """One HTTP/1.1 request -> (method, path, headers, body); None on
        a cleanly closed connection."""
        # readline converts LimitOverrunError to ValueError past the
        # stream's 64 KiB limit — an oversized request/header line from a
        # hostile client must close the connection, not kill the handler.
        try:
            line = await reader.readline()
        except (ConnectionError, asyncio.LimitOverrunError, ValueError):
            return None
        if not line or not line.strip():
            return None
        parts = line.decode("latin-1").strip().split()
        if len(parts) < 2:
            return None
        method, target = parts[0].upper(), parts[1]
        headers = {}
        while True:
            try:
                line = await reader.readline()
            except (ConnectionError, asyncio.LimitOverrunError, ValueError):
                return None
            if not line or line in (b"\r\n", b"\n"):
                break
            name, _, value = line.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        length = _content_length(headers)
        if length > MAX_BODY_BYTES:
            return (method, target, headers, b"")  # handler answers 413
        body = await reader.readexactly(length) if length else b""
        return method, target.split("?", 1)[0], headers, body

    def _respond(
        self,
        writer,
        status: int,
        body: bytes,
        ctype: str = "application/json",
        extra=(),
        close: bool = False,
    ) -> bool:
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
        )
        for name, value in extra:
            head += f"{name}: {value}\r\n"
        if close:
            head += "Connection: close\r\n"
        writer.write(head.encode("latin-1") + b"\r\n" + body)
        return not close

    def _json(self, writer, status, payload, extra=(), close=False) -> bool:
        return self._respond(
            writer,
            status,
            json.dumps(payload).encode(),
            extra=extra,
            close=close,
        )

    # -- routing -------------------------------------------------------

    async def _dispatch(self, req, reader, writer) -> bool:
        method, path, headers, body = req
        want_close = headers.get("connection", "").lower() == "close"
        if _content_length(headers) > MAX_BODY_BYTES:
            return self._json(
                writer, 413, {"error": "payload too large"}, close=True
            )
        if path == "/stream":
            if method != "POST":
                return self._json(
                    writer,
                    405,
                    {"error": "POST a length-prefixed frame stream "
                     "to /stream"},
                )
            # A stream session owns the rest of the connection (the
            # upload has no Content-Length); it always closes.
            await self._stream(headers, reader, writer)
            return False
        if path == "/healthz":
            return self._healthz(writer) and not want_close
        if path == "/stats":
            # The summary plus the server's config block: gauges like
            # eff_wait_ms only mean something next to the mode and cap
            # that produced them.
            payload = self.stats.summary()
            payload["config"] = self._config_block()
            return (
                self._json(writer, 200, payload)
                and not want_close
            )
        if path == "/metrics":
            # Prometheus text format, derived from the SAME summary dict
            # /stats serves — one vocabulary, two wire formats
            # (docs/OBSERVABILITY.md "/metrics").
            return (
                self._respond(
                    writer,
                    200,
                    render_prometheus(self.stats.summary()).encode(),
                    ctype="text/plain; version=0.0.4; charset=utf-8",
                )
                and not want_close
            )
        if path in ("/enhance", "/v1/enhance"):
            if method != "POST":
                return self._json(
                    writer, 405, {"error": "POST image bytes to /enhance"}
                )
            return await self._enhance(headers, body, writer) and not want_close
        if path == "/admin/reload":
            if method != "POST":
                return self._json(
                    writer, 405, {"error": "POST {\"weights\": path}"}
                )
            return await self._reload(body, writer) and not want_close
        if path == "/admin/policy":
            if method != "POST":
                return self._json(
                    writer,
                    405,
                    {"error": 'POST {"downgrade_watermark": N|null}'},
                )
            return self._policy(body, writer) and not want_close
        return self._json(writer, 404, {"error": f"no route {path}"})

    def _healthz(self, writer) -> bool:
        """Readiness + replica health (docs/SERVING.md "Fault
        isolation"): ``ok`` when every replica of every tier is
        available; ``degraded`` (still 200 — the pool is serving) when
        some replicas are quarantined/re-warming but every tier keeps at
        least one available; ``unhealthy`` (503) when any tier has zero
        available replicas. Warming and draining stay 503 as before."""
        ready = self.ready.is_set() and not self.draining.is_set()
        payload = {
            "ready": ready,
            "worker_id": self.worker_id,
            "warmed": self.ready.is_set(),
            "draining": self.draining.is_set(),
            # Streams open right now: an honest readiness signal keeps
            # reporting ready while sessions are live (they're traffic,
            # not a fault), but orchestrators can see the load.
            "active_streams": (
                self.streams.active_count()
                if self.streams is not None
                else 0
            ),
        }
        if not self.ready.is_set():
            payload["status"] = "warming"
            return self._json(writer, 503, payload)
        health = self.batcher.health()  # {tier: {index: state}}
        payload["replicas"] = {
            t: {str(i): s for i, s in sorted(m.items())}
            for t, m in sorted(health.items())
        }
        tier_available = {
            t: any(s in AVAILABLE_STATES for s in m.values())
            for t, m in health.items()
        }
        any_sick = any(
            s not in AVAILABLE_STATES for m in health.values()
            for s in m.values()
        )
        if self.draining.is_set():
            payload["status"] = "draining"
            return self._json(writer, 503, payload)
        if not all(tier_available.values()):
            payload["ready"] = False  # a tier with zero available replicas
            payload["status"] = "unhealthy"
            return self._json(writer, 503, payload)
        # An armed SLO engine grades health too: a paging objective turns
        # an otherwise-green pool "degraded" (still 200 — it is serving,
        # just out of budget; docs/OBSERVABILITY.md "Windows & SLOs").
        slo_block = self.stats.slo_state()
        slo_degraded = False
        if slo_block is not None:
            payload["slo"] = {
                "grade": slo_block["grade"],
                "state": slo_block["state"],
                "spec": slo_block["spec"],
            }
            slo_degraded = slo_block["grade"] == "degraded"
        payload["status"] = (
            "degraded" if (any_sick or slo_degraded) else "ok"
        )
        return self._json(writer, 200, payload)

    # -- /enhance ------------------------------------------------------

    async def _enhance(self, headers, body, writer) -> bool:
        # X-Request-Id correlation (docs/OBSERVABILITY.md): accept the
        # client's id or generate one, echo it on EVERY response, and
        # stamp it on every span this request touches — a failed loadgen
        # request can be found in the server trace by its id.
        req_id = _request_id(headers)
        rid = (("X-Request-Id", req_id),) + self._ident

        def jresp(status, payload, extra=(), close=False):
            return self._json(
                writer, status, payload, extra=tuple(extra) + rid,
                close=close,
            )

        # Deterministic gateway faults (docs/RESILIENCE.md): the K-th
        # /enhance ARRIVAL — counted before admission, so fault ordinals
        # are arrival ordinals — can kill this whole process or wedge it.
        gate = faults.gateway_fault()
        if gate.crash:
            # SIGKILL semantics on purpose: no goodbye bytes, the
            # connection just drops mid-request — the failover the fleet
            # router must absorb.
            os.kill(os.getpid(), signal.SIGKILL)
        if gate.hang is not None:
            # Blocking the LOOP thread is the point: /healthz, the beat
            # task, and every open connection freeze together, which is
            # exactly the wedge the router's hang detection must catch.
            gate.hang.wait()  # jaxlint: disable=R201 fault injection: wedging the loop IS the test

        t_req0 = time.perf_counter() if trace.enabled() else None
        if self.draining.is_set():
            # Drain contract: late arrivals are refused AND the
            # connection closes, so pooled clients re-resolve elsewhere.
            return jresp(503, {"error": "draining"}, close=True)
        if not self.ready.is_set():
            return jresp(
                503,
                {"error": "warming up"},
                extra=(("Retry-After", "1"),),
            )

        # Tier routing (docs/SERVING.md "Quality tiers"): X-Tier selects
        # the serving model per request; unknown names — and "fast" on a
        # server started without --student-weights — are 400, loudly:
        # a tier is a quality contract, not a routing hint.
        tier = headers.get("x-tier", "quality").strip().lower()
        if tier not in ("quality", "fast"):
            return jresp(
                400,
                {
                    "error": f"unknown tier {tier!r}",
                    "tiers": list(self.batcher.tiers),
                },
            )
        if tier not in self.batcher.tiers:
            return jresp(
                400,
                {
                    "error": "fast tier not configured on this server "
                    "(start waternet-serve with --student-weights)",
                    "tiers": list(self.batcher.tiers),
                },
            )
        # Brown-out opt-in (docs/SERVING.md "Fault isolation"): an
        # X-Tier-Allow-Downgrade'd quality request under saturation is
        # served by the fast tier instead of shed; the response names the
        # tier that actually served via X-Tier-Served. Never applied
        # without the opt-in.
        allow_downgrade = headers.get(
            "x-tier-allow-downgrade", ""
        ).strip().lower() in ("1", "true", "yes")
        downgrade_eligible = (
            allow_downgrade
            and tier == "quality"
            and "fast" in self.batcher.tiers
        )

        # Deadline parse + up-front feasibility: a budget the server
        # already knows it cannot meet is refused before it queues.
        deadline = None
        raw = headers.get("x-deadline-ms")
        if raw is not None:
            try:
                budget_ms = float(raw)
            except ValueError:
                return jresp(400, {"error": f"bad X-Deadline-Ms {raw!r}"})
            if budget_ms <= 0 or budget_ms < self.min_deadline_ms:
                self.stats.record_deadline_expired()
                return jresp(
                    504,
                    {
                        "error": "deadline cannot be met",
                        "budget_ms": budget_ms,
                        "min_deadline_ms": self.min_deadline_ms,
                    },
                )
            deadline = time.perf_counter() + budget_ms / 1e3

        # Content-addressed response cache (docs/SERVING.md "Temporal
        # reuse & response cache"; off unless --response-cache): a
        # digest hit replays the stored PNG without admission, decode,
        # or compute. The key's tier component plus the store-side
        # downgrade filter make a hit policy-correct for any requester
        # of that tier, opted in or not.
        cache_key = None
        if self.response_cache is not None:
            cache_key = self.response_cache.key(body, tier)
            cached = self.response_cache.get(cache_key)
            if cached is not None:
                keep = self._respond(
                    writer, 200, cached, ctype="image/png",
                    extra=(
                        ("X-Tier-Served", tier), ("X-Cache", "hit"),
                    ) + rid,
                )
                await writer.drain()
                if t_req0 is not None:
                    trace.record_span(
                        "response_cache", "serving", t_req0,
                        time.perf_counter(),
                        args={"request_id": req_id, "tier": tier,
                              "result": "hit", "bytes": len(cached)},
                    )
                return keep

        # Admission control: the deterministic fault hook, then the
        # queue-depth watermark — both shed with 429 + Retry-After.
        if faults.admit_should_reject():
            self.stats.record_shed()
            return jresp(
                429,
                {"error": "admission rejected (fault injection)"},
                extra=(("Retry-After", "1"),),
            )
        depth = self.batcher.queue_depth()
        if depth >= self.admit_watermark:
            # Brown-out exemption ONLY when the downgrade will actually
            # fire (the batcher's gauge is the QUALITY-tier backlog):
            # under a fast-tier flood the quality backlog is small, no
            # downgrade would happen, and admitting past the watermark
            # would just queue to QueueFull — shed instead.
            will_downgrade = (
                downgrade_eligible
                and self.batcher.downgrade_watermark is not None
                and self.batcher.tier_depth("quality")
                >= self.batcher.downgrade_watermark
            )
            if not will_downgrade:
                self.stats.record_shed()
                return jresp(
                    429,
                    {"error": "overloaded", "queue_depth": depth},
                    extra=(("Retry-After", "1"),),
                )

        loop = asyncio.get_running_loop()
        # In-flight from BEFORE the decode: the drain poll must not see
        # zero while an admitted request is still in the executor — the
        # batcher would close under it and drop an accepted request.
        with self._inflight_lock:
            self._inflight += 1
        try:
            rgb = await loop.run_in_executor(
                None, _decode_request_image, body
            )
            if t_req0 is not None:
                trace.record_span(
                    "decode", "serving", t_req0, time.perf_counter(),
                    args={"request_id": req_id, "tier": tier,
                          "bytes": len(body)},
                )
            if rgb is None:
                return jresp(
                    400, {"error": "body is not a decodable image"}
                )
            try:
                fut = self.batcher.submit(
                    rgb, deadline=deadline, tier=tier,
                    allow_downgrade=allow_downgrade,
                    request_id=req_id,
                )
            except UnknownTier as err:
                return jresp(400, {"error": str(err)})
            except QueueFull as err:
                return jresp(
                    429,
                    {"error": str(err)},
                    extra=(("Retry-After", "1"),),
                )
            except DeadlineExpired as err:
                return jresp(504, {"error": str(err)})
            except RuntimeError:
                # Batcher closed between the draining check and submit
                # (drain finished while we decoded): a late arrival.
                return jresp(503, {"error": "draining"}, close=True)
            try:
                out = await asyncio.wrap_future(fut)
            except DeadlineExpired as err:
                return jresp(504, {"error": str(err)})
            except ReplicaUnavailable as err:
                # Every replica quarantined (healthz has been reporting
                # unhealthy): tell clients to come back, not that the
                # request was malformed.
                return jresp(
                    503,
                    {"error": str(err)},
                    extra=(("Retry-After", "1"),),
                )
            except Exception as err:
                return jresp(
                    500, {"error": f"{type(err).__name__}: {err}"}
                )
            t_enc0 = time.perf_counter() if trace.enabled() else None
            png = await loop.run_in_executor(
                self._encode_pool, self._encode_png, out
            )
            served = getattr(fut, "tier", tier)
            cache_extra = ()
            if cache_key is not None:
                # Brown-out policy: a downgraded answer (served != the
                # requested tier) must never be stored — a later
                # non-opt-in request with the same bytes would hit it.
                if served == tier:
                    self.response_cache.put(cache_key, png)
                cache_extra = (("X-Cache", "miss"),)
            keep = self._respond(
                writer, 200, png, ctype="image/png",
                extra=(("X-Tier-Served", served),) + cache_extra + rid,
            )
            # Flush before the in-flight decrement: the drain poll must
            # not declare the server empty while this response is still
            # in the transport's user-space buffer — asyncio.run would
            # cancel the handler and truncate it on a slow client.
            await writer.drain()
            if t_enc0 is not None:
                trace.record_span(
                    "response_write", "serving", t_enc0,
                    time.perf_counter(),
                    args={"request_id": req_id, "tier": served,
                          "bytes": len(png)},
                )
            return keep
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    # -- /stream -------------------------------------------------------

    async def _stream(self, headers, reader, writer) -> None:
        """One stream session end to end (docs/SERVING.md "Streaming").

        Admission mirrors ``/enhance`` — draining and warming answer
        503; tier names are validated loudly — plus the stream-specific
        third degradation rung: past ``--max-streams`` open sessions or
        a saturated queue, NEW sessions get 503 + Retry-After while
        established sessions keep their QoS. Admitted sessions get the
        ``application/x-waternet-stream`` response head and then run
        entirely inside the :class:`StreamManager`."""
        # Session-level X-Request-Id, exactly as on /enhance: echoed on
        # every refusal and on the stream head; frame spans derive
        # per-frame ids as "<id>/<seq>" (docs/OBSERVABILITY.md).
        req_id = _request_id(headers)
        rid = (("X-Request-Id", req_id),) + self._ident

        def jresp(status, payload, extra=()):
            self._json(
                writer, status, payload, extra=tuple(extra) + rid,
                close=True,
            )

        if self.draining.is_set():
            jresp(503, {"error": "draining"})
            return
        if not self.ready.is_set():
            jresp(
                503,
                {"error": "warming up"},
                extra=(("Retry-After", "1"),),
            )
            return
        try:
            cfg = StreamConfig.from_headers(
                headers,
                self.stream_window,
                default_reuse=self.stream_reuse_threshold,
                default_max_reuse_run=self.stream_max_reuse_run,
            )
        except ValueError as err:
            jresp(400, {"error": str(err)})
            return
        if cfg.tier not in ("quality", "fast"):
            jresp(
                400,
                {
                    "error": f"unknown tier {cfg.tier!r}",
                    "tiers": list(self.batcher.tiers),
                },
            )
            return
        if cfg.tier not in self.batcher.tiers:
            jresp(
                400,
                {
                    "error": "fast tier not configured on this server "
                    "(start waternet-serve with --student-weights)",
                    "tiers": list(self.batcher.tiers),
                },
            )
            return
        refusal = self.streams.refusal()
        if refusal is not None:
            # Degradation rung 3: refuse NEW sessions, protect the
            # established ones. 503 (not 429): the service is telling
            # orchestrators to place the stream elsewhere for a while.
            self.stats.record_stream_refused()
            jresp(
                503,
                {"error": refusal},
                extra=(("Retry-After", "1"),),
            )
            return
        # In-flight for the drain poll, like /enhance: the batcher must
        # not close under an admitted session.
        with self._inflight_lock:
            self._inflight += 1
        try:
            head = (
                "HTTP/1.1 200 OK\r\n"
                "Content-Type: application/x-waternet-stream\r\n"
                f"X-Request-Id: {req_id}\r\n"
            )
            if self.worker_id:
                head += f"X-Worker-Id: {self.worker_id}\r\n"
            head += "Connection: close\r\n\r\n"
            writer.write(head.encode("latin-1"))
            await writer.drain()
            await self.streams.handle(cfg, reader, writer, request_id=req_id)
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away; the session already cleaned up
        finally:
            with self._inflight_lock:
                self._inflight -= 1

    # -- /admin/reload -------------------------------------------------

    def _do_reload(self, path: str):
        """Load + validate + swap (worker thread). Any raise = rollback:
        nothing is swapped until validation passes."""
        from waternet_tpu.hub import resolve_weights
        from waternet_tpu.utils.checkpoint import params_mismatch_report

        if getattr(self.engine, "quantized", False):
            raise ReloadMismatch(
                "quantized engines cannot hot-reload raw weights (the "
                "serving params are a calibrated int8 tree); restart with "
                "the new checkpoint instead"
            )
        new = resolve_weights(path)
        if new is None:
            raise FileNotFoundError(f"no weights at {path!r}")
        report = params_mismatch_report(
            new, self.engine.params, check_dtype=True
        )
        if report:
            raise ReloadMismatch(
                f"new weights do not fit the serving model — rolling back "
                f"(in-flight and future requests keep the current "
                f"weights):\n{report}"
            )
        self.batcher.set_params(new)
        if self.response_cache is not None:
            # Invalidate AFTER the swap: answers computed under the old
            # params must never serve again, and a put racing the swap
            # carries the old generation in its key and is refused.
            self.response_cache.invalidate()

    async def _reload(self, body, writer) -> bool:
        if not self.ready.is_set() or self.draining.is_set():
            return self._json(
                writer, 503, {"error": "not ready for reload"}
            )
        try:
            payload = json.loads(body or b"{}")
            path = payload["weights"]  # TypeError when payload isn't a dict
        except (ValueError, KeyError, TypeError):
            return self._json(
                writer,
                400,
                {"error": 'body must be JSON {"weights": "<path>"}'},
            )
        loop = asyncio.get_running_loop()

        def _locked_reload():
            # Lock taken INSIDE the worker thread: acquiring it on the
            # event loop would block the loop on a concurrent reload.
            with self._reload_lock:
                self._do_reload(path)

        try:
            await loop.run_in_executor(None, _locked_reload)
        except ReloadMismatch as err:
            return self._json(
                writer, 409, {"error": str(err), "reloaded": False}
            )
        except Exception as err:
            return self._json(
                writer,
                400,
                {
                    "error": f"{type(err).__name__}: {err}",
                    "reloaded": False,
                },
            )
        print(f"waternet-serve: reloaded weights from {path}", flush=True)
        return self._json(writer, 200, {"reloaded": True, "weights": path})

    # -- /admin/policy -------------------------------------------------

    def _policy(self, body, writer) -> bool:
        """Runtime brown-out control (docs/SERVING.md "Fleet"): the fleet
        router POSTs a lowered ``downgrade_watermark`` on sustained SLO
        ``page`` burn so opted-in quality traffic downgrades earlier
        fleet-wide, and restores it on sustained ``ok``. The watermark is
        a plain attribute the batcher reads at dispatch time, so the
        shift applies to the next coalesced batch — no restart, no
        reconfigure."""
        if not self.ready.is_set():
            return self._json(writer, 503, {"error": "not ready"})
        try:
            payload = json.loads(body or b"{}")
            if not isinstance(payload, dict):
                raise ValueError
        except ValueError:
            return self._json(
                writer,
                400,
                {"error": 'body must be JSON {"downgrade_watermark": '
                 'N|null}'},
            )
        if "downgrade_watermark" in payload:
            value = payload["downgrade_watermark"]
            bad = value is not None and (
                isinstance(value, bool)
                or not isinstance(value, int)
                or value < 1
            )
            if bad:
                return self._json(
                    writer,
                    400,
                    {
                        "error": "downgrade_watermark must be a positive "
                        f"int or null, got {value!r}"
                    },
                )
            self.batcher.downgrade_watermark = value
        return self._json(
            writer,
            200,
            {
                "policy": {
                    "downgrade_watermark": self.batcher.downgrade_watermark,
                    "admit_watermark": self.admit_watermark,
                }
            },
        )


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="waternet-serve", description=__doc__
    )
    parser.add_argument("--host", type=str, default="127.0.0.1")
    parser.add_argument(
        "--port", type=int, default=8080,
        help="0 = ephemeral (the chosen port is printed on the "
        "'listening on' line)",
    )
    parser.add_argument(
        "--weights", type=str, default=None,
        help="Model weights (.npz native or reference .pt); defaults to "
        "local weight resolution.",
    )
    parser.add_argument(
        "--serve-buckets", type=str, default="auto",
        help="Compile-bucket ladder: 'auto' (the default square ladder — "
        "a server has no directory to scan) or an explicit comma list "
        "like '256,512,1080x1920'.",
    )
    parser.add_argument(
        "--max-batch", type=int, default=8,
        help="Compiled batch-slot count per bucket.",
    )
    parser.add_argument(
        "--max-wait-ms", type=float, default=10.0,
        help="Coalescing CAP, not a constant hold: the longest a partial "
        "batch may wait for batchmates. Under --coalesce adaptive (the "
        "default) the EFFECTIVE window moves inside [0, cap] with the "
        "live arrival rate; --coalesce fixed holds every partial batch "
        "for exactly the cap. Per-request deadlines clamp the effective "
        "window either way.",
    )
    parser.add_argument(
        "--coalesce", type=str, default="adaptive",
        choices=["adaptive", "fixed"],
        help="Coalescing-window policy (docs/SERVING.md 'Adaptive "
        "scheduling'): 'adaptive' sizes each (tier, bucket)'s window "
        "from its EWMA arrival rate — an empty-queue request flushes "
        "immediately (p50 drops by ~the cap) and the window grows "
        "toward --max-wait-ms as load rises; 'fixed' is the historical "
        "constant hold. Responses are byte-identical across modes.",
    )
    parser.add_argument(
        "--png-level", type=int, default=None, metavar="0-9",
        help="PNG compression level for /enhance responses "
        "(IMWRITE_PNG_COMPRESSION; lower = faster encode, larger "
        "bytes). Unset keeps cv2's default — byte-identical responses "
        "to servers without the knob.",
    )
    parser.add_argument(
        "--encode-threads", type=int, default=2,
        help="Response-encode pool size: PNG encodes run on their own "
        "bounded pool (with per-thread reusable staging buffers) "
        "instead of the loop's shared default executor, so encode "
        "bursts cannot starve decode or control work.",
    )
    parser.add_argument(
        "--serve-replicas", type=str, default="auto",
        help="Replica-pool size: 'auto' (every local device) or N.",
    )
    parser.add_argument(
        "--max-queue", type=int, default=256,
        help="Hard bound on OUTSTANDING requests — queued, coalescing, "
        "or in flight on a replica (QueueFull past it; each one holds "
        "host RAM until its response resolves).",
    )
    parser.add_argument(
        "--admit-watermark", type=int, default=None,
        help="Queue depth past which admission sheds with 429 + "
        "Retry-After (default: 3/4 of --max-queue).",
    )
    parser.add_argument(
        "--grace-sec", type=float, default=30.0,
        help="Drain window after SIGTERM: in-flight work must finish "
        "within it for exit 0.",
    )
    parser.add_argument(
        "--min-deadline-ms", type=float, default=0.0,
        help="Reject X-Deadline-Ms budgets below this up front with 504 "
        "(operators set it to their known serving floor; 0 disables).",
    )
    parser.add_argument(
        "--student-weights", type=str, default=None,
        help="CAN student checkpoint (a train.py --distill product): "
        "enables the fast tier — requests with 'X-Tier: fast' are served "
        "by the student (raw RGB in, no WB/GC/CLAHE anywhere) from its "
        "own AOT-warmed executable grid. Without it, fast-tier requests "
        "are refused with 400 (docs/SERVING.md 'Quality tiers').",
    )
    parser.add_argument(
        "--student-quantize", action="store_true", default=False,
        help="Serve the fast tier as static int8 (models/quant.py "
        "quantize_can: MXU double-rate path; error bound vs the float "
        "student pinned in tests). Requires --student-weights.",
    )
    parser.add_argument(
        "--device-preprocess", action="store_true", default=False,
        help="Run WB/GC/CLAHE on the accelerator (ops/masked.py).",
    )
    parser.add_argument(
        "--watchdog-sec", type=float, default=30.0,
        help="Per-batch watchdog: a replica whose batch stays in flight "
        "past this is declared hung, quarantined, and its requests "
        "re-dispatched onto surviving replicas (docs/SERVING.md 'Fault "
        "isolation'). 0 disables the watchdog (crash isolation remains).",
    )
    parser.add_argument(
        "--serve-max-retries", type=int, default=2,
        help="Per-request re-dispatch budget after demonstrable batch "
        "failures (crash / hang / bad output); past it the request "
        "errors out.",
    )
    parser.add_argument(
        "--downgrade-watermark", type=int, default=None,
        help="Queue depth past which a quality request that opted in "
        "(X-Tier-Allow-Downgrade: 1) is served by the fast tier instead "
        "of shed (default: --admit-watermark). Needs --student-weights; "
        "never applied to requests that didn't opt in.",
    )
    parser.add_argument(
        "--max-streams", type=int, default=8,
        help="Open stream-session bound: past it NEW POST /stream "
        "sessions are refused with 503 + Retry-After while established "
        "streams keep their QoS (docs/SERVING.md 'Streaming').",
    )
    parser.add_argument(
        "--stream-window", type=int, default=8,
        help="Default per-stream delivery window: frames awaiting "
        "delivery past it are dropped oldest-first with an explicit "
        "drop record (clients override per session with "
        "X-Stream-Window).",
    )
    parser.add_argument(
        "--stream-reuse-threshold", type=float, default=None,
        help="Enable temporal frame reuse for streams: a frame whose "
        "decimated mean-abs delta against the last computed frame is "
        "at or below this threshold (uint8 scale) is answered from the "
        "cached enhanced frame as an R record, without compute. 0 "
        "reuses only byte-exact static frames; unset (the default) "
        "disables reuse. Sessions override per connection with "
        "X-Stream-Reuse (docs/SERVING.md 'Temporal reuse & response "
        "cache').",
    )
    parser.add_argument(
        "--stream-max-reuse-run", type=int,
        default=DEFAULT_MAX_REUSE_RUN,
        help="Staleness cap on temporal reuse: after this many "
        "consecutive reused frames the next frame is recomputed "
        "regardless of the delta score, so a stuck detector can never "
        "freeze a stream (sessions override with "
        "X-Stream-Max-Reuse-Run).",
    )
    parser.add_argument(
        "--response-cache", type=int, default=0, metavar="N",
        help="Content-addressed /enhance response cache: keep up to N "
        "rendered answers keyed on (payload digest, tier, bucket "
        "ladder, params generation), invalidated on /admin/reload. "
        "Hits replay the stored PNG without decode or compute and "
        "stamp X-Cache: hit. 0 (the default) disables the cache.",
    )
    parser.add_argument(
        "--obs-loop-lag", action="store_true",
        help="Sample event-loop callback wall time (a Handle._run wrap, "
        "docs/LINT.md 'Asyncio rules') and expose max/p99 loop lag as "
        "the loop_lag block on /stats and waternet_loop_lag_* gauges "
        "on /metrics. Off by default: the wrap costs one perf_counter "
        "pair per callback.",
    )
    parser.add_argument(
        "--slo", type=str, default=None, metavar="SPEC",
        help="Arm the SLO engine with a comma-separated objective list, "
        'e.g. "p99_ms<=250,error_rate<=0.01,availability>=0.999". '
        "Objectives are evaluated as multi-window burn rates; a paging "
        "objective grades /healthz degraded, and /stats + /metrics gain "
        "per-objective state and burn (docs/OBSERVABILITY.md "
        "'Windows & SLOs').",
    )
    parser.add_argument(
        "--precision", type=str, default="fp32", choices=["fp32", "bf16"],
    )
    return parser.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    from waternet_tpu.utils.platform import (
        enable_compile_cache,
        ensure_platform,
    )

    ensure_platform()
    enable_compile_cache()
    faults.install_from_env()  # WATERNET_FAULTS serving-side fault kinds

    import jax.numpy as jnp

    from waternet_tpu.inference_engine import InferenceEngine

    if args.student_quantize and not args.student_weights:
        # Pure flag validation — fail before any engine is built.
        raise SystemExit(
            "--student-quantize needs --student-weights (there is no "
            "student to quantize)"
        )
    if args.downgrade_watermark is not None and not args.student_weights:
        raise SystemExit(
            "--downgrade-watermark needs --student-weights: brown-out "
            "downgrades route saturated quality traffic to the fast "
            "tier, and without a student there is no fast tier to "
            "downgrade to (docs/SERVING.md 'Fault isolation')"
        )
    engine = InferenceEngine(
        weights=args.weights,
        device_preprocess=args.device_preprocess,
        dtype=jnp.bfloat16 if args.precision == "bf16" else jnp.float32,
    )
    fast_engine = None
    if args.student_weights:
        from waternet_tpu.inference_engine import StudentEngine

        fast_engine = StudentEngine(
            weights=args.student_weights,
            dtype=jnp.bfloat16 if args.precision == "bf16" else jnp.float32,
            quantize=args.student_quantize,
        )
    ladder = resolve_ladder(args.serve_buckets)
    server = ServingServer(
        engine,
        ladder,
        fast_engine=fast_engine,
        host=args.host,
        port=args.port,
        max_batch=args.max_batch,
        max_wait_ms=args.max_wait_ms,
        replicas=args.serve_replicas,
        max_queue=args.max_queue,
        admit_watermark=args.admit_watermark,
        grace_sec=args.grace_sec,
        min_deadline_ms=args.min_deadline_ms,
        supervision=SupervisionConfig(
            watchdog_sec=(
                None if args.watchdog_sec <= 0 else args.watchdog_sec
            ),
            max_retries=args.serve_max_retries,
        ),
        downgrade_watermark=args.downgrade_watermark,
        max_streams=args.max_streams,
        stream_window=args.stream_window,
        slo=args.slo,
        stream_reuse_threshold=args.stream_reuse_threshold,
        stream_max_reuse_run=args.stream_max_reuse_run,
        response_cache=args.response_cache,
        obs_loop_lag=args.obs_loop_lag,
        coalesce=args.coalesce,
        png_level=args.png_level,
        encode_threads=args.encode_threads,
    )
    return server.run(install_signal_handlers=True)


if __name__ == "__main__":
    sys.exit(main())
