"""Dynamic micro-batching over shape buckets, plus the legacy exact-shape
batcher the inference CLI used before this subsystem existed.

:class:`DynamicBatcher` is the serving engine's core: a request queue
with ``max_batch`` / ``max_wait_ms`` deadlines that coalesces concurrent
requests per compile bucket and hands each coalesced micro-batch to a
:class:`waternet_tpu.serving.replicas.ReplicaPool` — one replica per
serving device (``replicas=1`` by default; the CLI defaults to every
local device), each with its own launch thread (host preprocess + async
dispatch) and completion thread (that replica's one D2H sync), so
preprocessing, device compute, and readback overlap per device AND across
devices — the H2D / compute / D2H discipline of
:class:`waternet_tpu.data.pipeline.OrderedPipeline`, multiplied by the
device count. Results are delivered through per-request futures, so
output ordering is whatever the caller makes it; consuming futures in
submission order (:meth:`DynamicBatcher.map_ordered`, the CLI path) is
deterministic regardless of how requests happened to coalesce into
batches or which replica served them, because the conv forward is
per-sample independent and every replica runs the same program on the
same params — a request's output never depends on its batchmates or its
replica (pinned in tests/test_serving.py).

Batches are padded up to the compiled ``max_batch`` slot count (last
image repeated) so every bucket is served by exactly ONE executable per
replica — that is what bounds the stream's compile count at
``len(buckets) x replicas``, all paid at warmup.
Occupancy (real requests / slots) is the price, reported per run by
:class:`waternet_tpu.serving.stats.ServingStats`.

Worker threads run under the input pipeline's ``THREAD_PREFIX`` so the
test suite's thread-leak guard (tests/conftest.py) covers serving
shutdown bugs too.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from waternet_tpu.data.pipeline import THREAD_PREFIX
from waternet_tpu.obs import trace
from waternet_tpu.serving.adaptive import CoalesceController
from waternet_tpu.serving.bucketing import BucketLadder
from waternet_tpu.serving.replicas import (
    ReplicaPool,
    SupervisionConfig,
    engine_jit_cache_size,
    resolve_replicas,
)
from waternet_tpu.serving.stats import ServingStats

_CLOSE = object()
_TICK = object()


class QueueFull(RuntimeError):
    """submit() refused: the batcher's bounded request queue is at
    ``max_queue``. Under overload, admission — not memory — is the thing
    that gives; callers shed (the HTTP front door answers 429) or retry
    later instead of queueing without bound."""


class UnknownTier(ValueError):
    """submit() refused: the requested serving tier is not served by this
    batcher — either a name outside {quality, fast}, or ``fast`` on a
    batcher built without a ``fast_engine``. Raised loudly (the HTTP
    front door answers 400) instead of silently serving the wrong model:
    a tier is a quality contract, not a routing hint."""


class RequestCancelled(RuntimeError):
    """A request's caller walked away before compute — a stream session
    disconnected or drop-oldest evicted the frame. The owner marks the
    request's future with ``abandoned = True`` (never ``Future.cancel()``,
    which would race the replica completion thread's ``set_result``);
    the dispatcher and the re-dispatch path honor the mark by setting
    this exception instead of computing, so batch-mates from other
    sessions are untouched."""


class DeadlineExpired(RuntimeError):
    """A request's deadline ran out before its batch was computed. Raised
    from submit() when the deadline is already past at admission, and set
    on the request's future when the deadline expires while the request
    waits for dispatch — the batch is launched without it (dropped with
    ``stats.deadline_expired``, not computed)."""


class _Request:
    __slots__ = ("image", "future", "t_submit", "t_admit", "deadline",
                 "tier", "retries", "allow_downgrade", "req_id")

    def __init__(
        self,
        image: np.ndarray,
        deadline: Optional[float] = None,
        tier: str = "quality",
        allow_downgrade: bool = False,
        req_id: Optional[str] = None,
    ):
        self.image = image
        self.tier = tier
        # Correlation id stamped on every span this request touches
        # (docs/OBSERVABILITY.md); the front door echoes it in
        # ``X-Request-Id``. None = uncorrelated (library callers).
        self.req_id = req_id
        # Re-dispatch budget consumed by the replica pool when this
        # request's batch demonstrably fails (docs/SERVING.md "Fault
        # isolation"); ``allow_downgrade`` is the brown-out opt-in.
        self.retries = 0
        self.allow_downgrade = allow_downgrade
        self.future: Future = Future()
        # t_submit anchors the reported request latency; t_admit (set when
        # the dispatcher moves the request into its bucket's pending list)
        # anchors the max_wait deadline — the knob bounds time spent
        # WAITING FOR BATCHMATES, not queueing delay, which under overload
        # is capacity-bound and shared by all traffic. ``deadline`` is an
        # absolute perf_counter instant (None = no deadline): it CLAMPS
        # the coalescing wait (a lone request never waits out a window it
        # cannot afford) and, once past, drops the request at dispatch.
        self.t_submit = time.perf_counter()
        self.t_admit = self.t_submit
        self.deadline = deadline


class DynamicBatcher:
    """Coalesce an arbitrary request stream into full, bucket-shaped
    device batches behind AOT-compiled executables.

    * ``max_batch`` — compiled batch-slot count per bucket (with
      ``data_shards`` engines, make it a multiple of the shard count);
    * ``max_wait_ms`` — the coalescing CAP: the longest a bucket's
      oldest admitted request may wait for batchmates before the
      partial batch flushes. The clock starts at dispatcher admission,
      so it bounds coalescing delay specifically — queueing delay under
      overload is capacity-bound and shared by all traffic. With
      ``coalesce="fixed"`` (the library default) the effective window
      IS the cap — the historical constant hold. With
      ``coalesce="adaptive"`` (the serving CLI default) a per-(tier,
      bucket) :class:`~waternet_tpu.serving.adaptive.CoalesceController`
      sets the effective window inside [0, cap] from the EWMA arrival
      rate: an empty-queue request flushes immediately (its p50 drops
      by ~the cap) and the window grows toward the cap as load rises
      (occupancy preserved). Either way, per-request deadlines clamp
      the effective window identically;
    * ``replicas`` — serving devices (``'auto'`` = every local device;
      sharded engines always resolve to 1 — their executable spans the
      mesh). Each flush goes to the least-loaded replica;
      ``max_inflight_per_replica`` bounds how far any one device's launch
      side may run ahead of its D2H sync (2 = double buffering);
    * oversize requests (no covering bucket) fall back to a per-shape
      native forward through the jit cache and are counted in
      ``stats.fallback_native_shapes`` — they pay the compile the ladder
      could not absorb;
    * ``max_queue`` — bound on OUTSTANDING requests (submitted and not
      yet resolved: queued, coalescing, or in flight on a replica). At
      the bound, submit() raises :class:`QueueFull` instead of queueing
      forever: every outstanding request holds host RAM until its future
      resolves, so this is the knob that keeps RSS and queueing delay
      bounded under overload. The default is generous (the CLI's own
      windowing never comes near it); servers set it to their real
      watermark (docs/SERVING.md "Front door");
    * ``fast_engine`` — a :class:`~waternet_tpu.inference_engine.
      StudentEngine` enabling per-request tier routing (docs/SERVING.md
      "Quality tiers"): the distilled CAN student gets its OWN replica
      pool on the same devices and ladder, requests pick a tier at
      submit (``tier="fast"``; default "quality" is byte-identical to a
      tier-less batcher), coalescing is per (tier, bucket), and
      unknown/unconfigured tiers raise :class:`UnknownTier`.
    """

    def __init__(
        self,
        engine,
        ladder: BucketLadder,
        max_batch: int = 8,
        max_wait_ms: float = 10.0,
        stats: Optional[ServingStats] = None,
        warmup_verbose: bool = False,
        replicas=1,
        max_inflight_per_replica: int = 2,
        max_queue: int = 8192,
        fast_engine=None,
        tier_name: str = "quality",
        supervision: Optional[SupervisionConfig] = None,
        downgrade_watermark: Optional[int] = None,
        coalesce: str = "fixed",
    ):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if max_queue < 1:
            raise ValueError(f"max_queue must be >= 1, got {max_queue}")
        if downgrade_watermark is not None and downgrade_watermark < 1:
            raise ValueError(
                f"downgrade_watermark must be >= 1 (or None to disable "
                f"brown-out downgrades), got {downgrade_watermark}"
            )
        # ``tier_name`` labels the PRIMARY engine's pool in the stats —
        # "fast" when the CLI serves a StudentEngine alone (--tier fast),
        # so the stats block names the tier that actually served. A
        # two-tier batcher keeps the primary as "quality" (the fast pool
        # is always the student).
        if tier_name not in ("quality", "fast"):
            raise ValueError(
                f"tier_name must be 'quality' or 'fast', got {tier_name!r}"
            )
        if fast_engine is not None and tier_name != "quality":
            raise ValueError(
                "a two-tier batcher's primary engine IS the quality tier; "
                "tier_name overrides are for single-engine batchers"
            )
        self._default_tier = tier_name
        self.engine = engine
        self.max_batch = int(max_batch)
        if engine.data_shards > 1 and self.max_batch % engine.data_shards:
            # The AOT executable's batch shape is fixed, and a data-sharded
            # lowering needs equal per-shard slices — round the slot count
            # up instead of failing warmup with a cryptic pjit error.
            self.max_batch += engine.data_shards - (
                self.max_batch % engine.data_shards
            )
        self.ladder = ladder = fit_ladder_to_engine(ladder, engine)
        self.max_wait_s = float(max_wait_ms) / 1e3
        # Effective-window authority: fixed mode returns the cap from
        # every read — byte- and timing-identical to the historical
        # constant hold; adaptive mode shrinks/grows inside [0, cap]
        # from the EWMA arrival rate (serving/adaptive.py). Validates
        # the mode name loudly here, at construction.
        self._coalesce = CoalesceController(self.max_wait_s, mode=coalesce)
        self.stats = stats if stats is not None else ServingStats()
        # No request ever pays a compile: the whole per-replica executable
        # grid is built before the first submit is accepted.
        self.supervision = (
            supervision if supervision is not None else SupervisionConfig()
        )
        self.downgrade_watermark = downgrade_watermark
        self._pool = ReplicaPool(
            engine, ladder, [self.max_batch],
            n_replicas=resolve_replicas(replicas, engine),
            max_inflight_per_replica=max_inflight_per_replica,
            stats=self.stats, warmup_verbose=warmup_verbose,
            tier=tier_name, supervision=self.supervision,
        )
        # Per-request tier routing (docs/SERVING.md "Quality tiers"):
        # ``fast_engine`` (a StudentEngine) gets its OWN replica pool on
        # the same devices, same ladder, same slot count — its own
        # AOT-warmed executable grid, launch/completion threads, and
        # per-tier stats — while quality traffic flows through the pool
        # above byte-identically to a tier-less batcher. Without it,
        # tier="fast" submits are refused loudly (UnknownTier).
        self._pools = {tier_name: self._pool}
        if fast_engine is not None:
            if getattr(fast_engine, "data_shards", 1) > 1 or getattr(
                fast_engine, "spatial_shards", 1
            ) > 1:
                raise ValueError(
                    "the fast tier's student engine is never sharded "
                    "(its whole point is fitting on one chip)"
                )
            self._pools["fast"] = ReplicaPool(
                fast_engine, ladder, [self.max_batch],
                n_replicas=self._pool.n_replicas,
                max_inflight_per_replica=max_inflight_per_replica,
                stats=self.stats, warmup_verbose=warmup_verbose,
                tier="fast", supervision=self.supervision,
            )
        self._requests: queue.Queue = queue.Queue()
        self._closed = False  # guarded-by: self._submit_lock
        self.max_queue = int(max_queue)
        # Per-tier outstanding counts (submit lock): the quality tier's
        # backlog is the brown-out pressure gauge — past
        # ``downgrade_watermark``, opted-in quality requests route to the
        # fast tier instead of queueing (docs/SERVING.md "Fault
        # isolation").
        self._tier_backlog = {t: 0 for t in self._pools}  # guarded-by: self._submit_lock
        # Outstanding-request count: submitted and not yet RESOLVED —
        # queued, coalescing, or in flight on a replica. This is the
        # admission-control gauge and the QueueFull bound: the
        # dispatcher itself only routes (it hands coalesced batches to
        # per-replica work queues in microseconds), so a bound on the
        # undispatched slice alone would never trip under overload —
        # what grows without limit is work admitted faster than devices
        # finish it, and every such request holds host RAM until its
        # future resolves. Decremented by a future done-callback, which
        # covers every resolution path (result, error, deadline drop).
        self._backlog = 0  # guarded-by: self._submit_lock
        self.stats.queue_depth_probe = self.queue_depth
        self.stats.replica_health_probe = self.health
        self.stats.eff_wait_probe = self._coalesce.eff_wait_ms
        # Makes the closed-check + enqueue atomic vs close(): without it a
        # racing submit() could land its request BEHIND the _CLOSE
        # sentinel, where the dispatcher never looks — the caller would
        # block forever on a future that cannot resolve.
        self._submit_lock = threading.Lock()
        self._dispatcher = threading.Thread(
            target=self._dispatch_loop,
            name=f"{THREAD_PREFIX}-serve-dispatch",
            daemon=True,
        )
        self._dispatcher.start()

    @property
    def coalesce_mode(self) -> str:
        """The configured coalescing mode: "fixed" (constant hold at the
        ``max_wait_ms`` cap) or "adaptive" (load-aware window inside
        [0, cap]) — surfaced in the server banner and /stats config."""
        return self._coalesce.mode

    def eff_wait_ms(self) -> dict:
        """Live per-tier effective coalescing window (ms) — the
        ``eff_wait_ms`` gauge of /stats and /metrics."""
        return self._coalesce.eff_wait_ms()

    @property
    def n_replicas(self) -> int:
        return self._pool.n_replicas

    @property
    def tiers(self) -> Tuple[str, ...]:
        """The tier names this batcher serves (always includes
        "quality"; "fast" iff a ``fast_engine`` was configured)."""
        return tuple(sorted(self._pools))

    # -- public API ----------------------------------------------------

    def submit(
        self,
        image: np.ndarray,
        deadline: Optional[float] = None,
        tier: Optional[str] = None,
        allow_downgrade: bool = False,
        request_id: Optional[str] = None,
    ) -> Future:
        """Queue one (H, W, 3) uint8 image; resolves to its enhanced
        native-shape uint8 array. Thread-safe.

        ``request_id`` is an optional correlation id: when tracing is
        armed (waternet_tpu/obs) every span this request touches —
        queue wait, coalesce, device, re-dispatch hop — carries it, so a
        failed loadgen request can be found in the server trace.

        ``deadline`` is an absolute ``time.perf_counter()`` instant.
        Already past at admission -> :class:`DeadlineExpired` here (the
        up-front rejection); still pending when it expires -> the future
        gets :class:`DeadlineExpired` and the batch launches without the
        request. Either way ``stats.deadline_expired`` counts it. Raises
        :class:`QueueFull` at the ``max_queue`` bound — admission control
        instead of unbounded queueing.

        ``tier`` selects the serving model per request (None defaults to
        the batcher's primary tier — "quality" unless ``tier_name``
        renamed a single-engine batcher — byte-identical to a tier-less
        batcher): "quality" is the full WaterNet pipeline, "fast" the
        CAN student pool. Any other name — or a tier this batcher does
        not serve — raises :class:`UnknownTier`.

        ``allow_downgrade`` is the brown-out opt-in (docs/SERVING.md
        "Fault isolation"): when the quality tier's outstanding count
        sits at/past ``downgrade_watermark`` and a fast pool is
        configured, an opted-in quality request is served by the fast
        tier instead of queueing (counted in ``stats.downgraded``).
        Requests that did not opt in are NEVER downgraded. The returned
        future carries the tier that actually serves it as ``.tier``.
        """
        tier = self._default_tier if tier is None else str(tier).lower()
        if tier not in ("quality", "fast"):
            raise UnknownTier(
                f"unknown tier {tier!r}: valid tiers are 'quality' and "
                "'fast'"
            )
        if tier not in self._pools:
            hint = (
                " — the fast tier needs a student engine (server: "
                "--student-weights)"
                if tier == "fast"
                else ""
            )
            raise UnknownTier(
                f"tier {tier!r} is not configured on this batcher "
                f"(serving: {', '.join(sorted(self._pools))}){hint}"
            )
        if image.ndim != 3 or image.shape[-1] != 3:
            raise ValueError(
                f"expected one (H, W, 3) image, got shape {image.shape}"
            )
        if image.dtype != np.uint8:
            # Validated HERE, loudly: a non-uint8 image would raise at
            # LAUNCH instead, where the supervised pool cannot tell a
            # poison-pill request from a sick device — one bad submit
            # could strike (and cascade-quarantine) healthy replicas.
            raise ValueError(
                f"expected a uint8 image, got dtype {image.dtype} (the "
                "serving contract is (H, W, 3) uint8)"
            )
        if deadline is not None and deadline <= time.perf_counter():
            self.stats.record_deadline_expired()
            raise DeadlineExpired(
                "deadline already past at admission (the coalescing window "
                "plus compute cannot finish in negative time)"
            )
        req = _Request(
            image, deadline=deadline, tier=tier,
            allow_downgrade=allow_downgrade, req_id=request_id,
        )
        # The callback reads the served tier off the FUTURE (set below,
        # before enqueue — resolution cannot precede dispatch), not off a
        # captured request: Future keeps its callbacks after resolution,
        # so a req-capturing closure would pin every input image for as
        # long as the caller holds the future.
        req.future.add_done_callback(self._on_request_resolved)
        downgraded = False
        with self._submit_lock:
            if self._closed:
                raise RuntimeError("DynamicBatcher is closed")
            if self._backlog >= self.max_queue:
                self.stats.record_shed()
                raise QueueFull(
                    f"{self._backlog} requests outstanding, max_queue="
                    f"{self.max_queue}: shedding instead of queueing forever"
                )
            if (
                allow_downgrade
                and req.tier == "quality"
                and "fast" in self._pools
                and self.downgrade_watermark is not None
                and self._tier_backlog.get("quality", 0)
                >= self.downgrade_watermark
            ):
                # Brown-out: the quality queue is saturated and the
                # request opted in — a fast-tier answer now beats a 429.
                req.tier = "fast"
                downgraded = True
            req.future.tier = req.tier  # the tier that will actually serve
            self._backlog += 1
            self._tier_backlog[req.tier] = (
                self._tier_backlog.get(req.tier, 0) + 1
            )
            self._requests.put(req)
        if downgraded:
            self.stats.record_downgrade()
        return req.future

    def _on_request_resolved(self, future) -> None:
        """Done-callback on every request future: runs on whichever
        thread resolves it (replica completion, error path, deadline
        drop), so the outstanding counts — global and per-tier — can
        never leak. The tier rides the future itself (``future.tier``,
        stamped at submit before enqueue)."""
        tier = getattr(future, "tier", None)
        with self._submit_lock:
            self._backlog -= 1
            if tier is not None:
                self._tier_backlog[tier] = (
                    self._tier_backlog.get(tier, 0) - 1
                )

    def queue_depth(self) -> int:
        """Live outstanding-request count (queued + coalescing + in
        flight) — the admission-control gauge the HTTP front door's
        watermark reads, exported as ``queue_depth`` in
        ``stats.summary()``."""
        with self._submit_lock:
            return self._backlog

    def tier_depth(self, tier: str) -> int:
        """Live outstanding-request count for one tier — the quality
        tier's is the brown-out pressure gauge."""
        with self._submit_lock:
            return self._tier_backlog.get(tier, 0)

    def health(self) -> dict:
        """Live per-tier replica health map, ``{tier: {index: state}}``
        (docs/SERVING.md "Fault isolation") — what ``/healthz`` degrades
        on and ``stats.summary()['replica_health']`` reports."""
        return {t: pool.health() for t, pool in self._pools.items()}

    def set_params(self, params) -> None:
        """Hot weight reload of the QUALITY tier: atomically swap every
        replica's params between batches (in-flight batches keep the
        params they were launched with; no request is dropped). The
        caller validates shapes/dtypes first — the AOT executables take
        params as a runtime argument, so same-structure params never
        recompile. The fast tier's student is a separate checkpoint and
        keeps serving its own weights (restart to swap a student)."""
        self._pool.set_params(params)

    def map_ordered(
        self, images: Iterable[np.ndarray], tier: Optional[str] = None
    ) -> List[np.ndarray]:
        """Submit everything, then collect results in submission order —
        the deterministic whole-stream entry point (bench A/B uses it)."""
        futures = [self.submit(im, tier=tier) for im in images]
        self.drain()
        return [f.result() for f in futures]

    def drain(self) -> None:
        """Flush all pending partial batches without closing: everything
        submitted before the call resolves without waiting out deadlines."""
        self._requests.put(_TICK)

    def close(self) -> None:
        """Flush pending requests, stop the dispatcher and every
        replica's workers, join them all. Idempotent; safe from
        ``finally``."""
        with self._submit_lock:
            if self._closed:
                return
            self._closed = True
            self._requests.put(_CLOSE)
        # The dispatcher's finally closes the pool (draining every
        # replica's queued work and joining its threads), so one join
        # covers the whole serving stack.
        self._dispatcher.join(timeout=120.0)

    def __enter__(self) -> "DynamicBatcher":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    # -- dispatcher ----------------------------------------------------

    def _dispatch_loop(self) -> None:
        pending: dict = {}  # (tier, bucket) -> [requests, FIFO]

        def flush_all():
            for key in list(pending):
                self._flush(key, pending.pop(key))

        try:
            while True:
                timeout = self._next_deadline(pending)
                try:
                    item = self._requests.get(timeout=timeout)
                except queue.Empty:
                    item = None  # a deadline expired while the queue was idle
                if item is _CLOSE:
                    flush_all()
                    break
                if item is _TICK:
                    flush_all()
                    continue
                if item is not None:
                    self._admit(item, pending)
                    self._sweep(pending)
                # Coalescing-friendly burst drain: admit everything that
                # was already queued when this cycle started, so a burst
                # forms full batches instead of deadline-split fragments
                # (burst admits are microseconds apart, far inside any
                # real wait budget, so the per-admit sweep stays quiet).
                # BOUNDED by the qsize snapshot — items arriving during
                # the drain's inline flushes wait for the next cycle.
                # Sweeping after every admit means sustained traffic in
                # OTHER buckets cannot hold a sparse bucket's request
                # past its wait budget by more than ~one batch dispatch.
                closing = False
                for _ in range(self._requests.qsize()):
                    try:
                        nxt = self._requests.get_nowait()
                    except queue.Empty:
                        break
                    if nxt is _CLOSE:
                        closing = True
                        break
                    if nxt is _TICK:
                        flush_all()
                        continue
                    self._admit(nxt, pending)
                    self._sweep(pending)
                if closing:
                    flush_all()
                    break
                self._sweep(pending)  # idle-queue cycles: deadlines fire here
        finally:
            for pool in self._pools.values():
                pool.close()

    def _admit(self, req: _Request, pending: dict) -> None:
        req.t_admit = time.perf_counter()
        if trace.enabled():
            # Queue wait: submit -> dispatcher admission, from timestamps
            # the batcher already keeps — arming adds no clock reads.
            trace.record_span(
                "queue_wait", "serving", req.t_submit, req.t_admit,
                args={"request_id": req.req_id, "tier": req.tier},
            )
        h, w = req.image.shape[:2]
        bucket = self.ladder.bucket_for(h, w)
        # Coalescing is per (tier, bucket): tiers never share a device
        # batch — a micro-batch runs ONE model on one executable. The
        # controller sees every admission: its arrival-rate estimate is
        # what sizes the NEXT effective window for this key.
        key = (req.tier, bucket)
        self._coalesce.observe_arrival(req.tier, bucket, req.t_admit)
        pending.setdefault(key, []).append(req)
        if bucket is None or len(pending[key]) >= self.max_batch:
            self._flush(key, pending.pop(key))

    def _eff_deadline(self, req: _Request, window_s: float) -> float:
        """When this request's bucket must flush on its account: the
        effective coalescing budget (``window_s`` — the cap under fixed
        mode, the controller's load-aware window under adaptive),
        CLAMPED by the request's own deadline — a request with 5 ms
        left never waits out a 20 ms window it cannot afford."""
        t = req.t_admit + window_s
        if req.deadline is not None:
            t = min(t, req.deadline)
        return t

    def _window_for(self, key, now: float, busy_cache: dict) -> float:
        """The effective coalescing window for one (tier, bucket): the
        controller's load-aware window, EXTENDED back to the cap while
        every replica of the tier is busy. The extension is
        work-conserving: with no idle replica, flushing a partial bucket
        early cannot start its compute any sooner — the batch would sit
        in the pool queue while its (slot-padded, so full-price) partial
        fill is locked in. Held buckets still flush the instant they
        fill (``_admit``) and each request's own deadline still clamps
        in ``_eff_deadline``. Fixed mode already sits at the cap, so the
        probe is skipped and behavior is bit-for-bit the historical
        hold. ``busy_cache`` memoizes one pool probe per tier per
        dispatcher pass."""
        tier, bucket = key
        w = self._coalesce.window_s(tier, bucket, now)
        if w >= self.max_wait_s:
            return w
        busy = busy_cache.get(tier)
        if busy is None:
            busy = not self._pools[tier].has_idle_replica()
            busy_cache[tier] = busy
        return self.max_wait_s if busy else w

    def _sweep(self, pending: dict) -> None:
        """Flush every bucket holding a request whose effective deadline
        (coalescing budget clamped by its own deadline) has passed
        (cheap: O(pending requests) clock checks, one controller read
        per pending bucket, at most one pool-idleness probe per tier)."""
        now = time.perf_counter()
        busy_cache: dict = {}
        for key in list(pending):
            reqs = pending[key]
            if not reqs:
                continue
            w = self._window_for(key, now, busy_cache)
            if min(self._eff_deadline(r, w) for r in reqs) <= now:
                self._flush(key, pending.pop(key))

    def _next_deadline(self, pending: dict) -> Optional[float]:
        soonest = None
        now = time.perf_counter()
        busy_cache: dict = {}
        for key, reqs in pending.items():
            if not reqs:
                continue
            w = self._window_for(key, now, busy_cache)
            for r in reqs:
                t = self._eff_deadline(r, w)
                soonest = t if soonest is None else min(soonest, t)
        if soonest is None:
            return None  # idle: block until the next request
        return max(0.0, soonest - now)

    def _flush(self, key, reqs: List[_Request]) -> None:
        """Hand one coalesced micro-batch to its tier's least-loaded
        replica. Host preprocessing, the async device launch, and the D2H
        sync all happen on that replica's own threads (serving/replicas.py),
        so this dispatcher only ever routes — a slow readback on one device
        cannot delay coalescing or launches for the others. Requests whose
        deadline has already passed are dropped here with a counter, not
        computed: a response nobody is waiting for is pure wasted device
        time under exactly the overload that made it late."""
        tier, bucket = key
        if not reqs:
            return
        now = time.perf_counter()
        live: List[_Request] = []
        for r in reqs:
            if getattr(r.future, "abandoned", False):
                # Caller walked away (stream disconnect / drop-oldest):
                # the dispatcher solely owns un-dispatched pending
                # requests, so resolving here cannot race a replica.
                if not r.future.done():
                    r.future.set_exception(
                        RequestCancelled(
                            "request abandoned by its caller; "
                            "dropped un-computed at dispatch"
                        )
                    )
            elif r.deadline is not None and r.deadline <= now:
                self.stats.record_deadline_expired()
                if not r.future.done():
                    r.future.set_exception(
                        DeadlineExpired(
                            "deadline expired while waiting for dispatch; "
                            "request dropped un-computed"
                        )
                    )
            else:
                live.append(r)
        if bucket is not None and live:
            # Occupancy feedback: what this flush's fill looked like —
            # the controller's EWMA gauge (bench serve_adaptive reports
            # it). Fallback natives (bucket None) always flush alone
            # and would only skew the gauge.
            self._coalesce.observe_flush(tier, len(live) / self.max_batch)
        if trace.enabled():
            # Coalesce: admission -> flush, per surviving request, each
            # carrying the wait it actually paid (eff_wait_ms — the
            # adaptive win is visible per request in traces); the
            # dropped ones get instants so a trace explains the gap.
            for r in live:
                trace.record_span(
                    "coalesce", "serving", r.t_admit, now,
                    args={"request_id": r.req_id, "tier": tier,
                          "bucket": str(bucket),
                          "eff_wait_ms": round((now - r.t_admit) * 1e3, 3)},
                )
            for r in reqs:
                if r not in live and r.future.done():
                    trace.record_instant(
                        "request_dropped", "serving", t=now,
                        args={"request_id": r.req_id, "tier": tier},
                    )
        if not live:
            return
        try:
            self._pools[tier].dispatch(
                bucket, live, queue_depth=self._requests.qsize()
            )
        except BaseException as err:
            for r in live:
                if not r.future.done():
                    r.future.set_exception(err)


class ExactShapeBatcher:
    """The pre-serving shape-aware grouping, lifted verbatim from
    ``inference.run_images_batched``: consecutive same-shaped images
    stack into device batches of up to ``batch_size``; a shape change
    flushes the pending batch; forwards go through the engine's jit
    cache, compiling once per unique shape. This is the CLI's
    ``--exact-shapes`` path — byte-for-byte the historical behavior —
    and the A/B baseline the bench line measures bucketing against.
    """

    def __init__(self, engine, batch_size: int, stats: Optional[ServingStats] = None):
        self.engine = engine
        self.batch_size = int(batch_size)
        self.stats = stats if stats is not None else ServingStats()
        self._pending: List[Tuple[object, np.ndarray, float]] = []

    def push(self, key, image: np.ndarray) -> List[Tuple[object, np.ndarray]]:
        """Add one image; returns any (key, enhanced) results this push
        flushed, in submission order (possibly two groups: the
        shape-change flush then the size-cap flush)."""
        flushed: List[Tuple[object, np.ndarray]] = []
        if self._pending and image.shape != self._pending[0][1].shape:
            flushed.extend(self.flush())
        self._pending.append((key, image, time.perf_counter()))
        if len(self._pending) >= self.batch_size:
            flushed.extend(self.flush())
        return flushed

    def flush(self) -> List[Tuple[object, np.ndarray]]:
        if not self._pending:
            return []
        images = [im for _, im, _ in self._pending]
        before = engine_jit_cache_size(self.engine)
        outs = self.engine.enhance(np.stack(images))
        grew = engine_jit_cache_size(self.engine) - before
        if grew > 0:
            self.stats.record_compile(grew)
        h, w = images[0].shape[:2]
        self.stats.record_batch(
            n_real=len(images),
            n_slots=self.batch_size,
            real_px=len(images) * h * w,
            padded_px=len(images) * h * w,  # exact shapes: zero padding
        )
        t_done = time.perf_counter()
        results = [(k, out) for (k, _, _), out in zip(self._pending, outs)]
        # Latency is push -> result ready, the same submit-anchored metric
        # DynamicBatcher records — the two batchers' stats are comparable.
        for _, _, t_push in self._pending:
            self.stats.record_latency(t_done - t_push)
        self._pending.clear()
        return results


def fit_ladder_to_engine(ladder: BucketLadder, engine) -> BucketLadder:
    """Round a ladder's bucket heights up to what the engine can lower.

    Spatially-sharded engines split H over ``spatial_shards`` devices and
    need every slab to hold at least ``2 * HALO`` rows, so each bucket
    height rounds up to the next multiple of the shard count with a
    ``2 * HALO * shards`` floor — rounding *up* keeps every shape the
    original ladder covered. Unsharded engines (and batch-sharded
    ones, whose constraint is on the slot count, not the canvas) pass
    through untouched.
    """
    shards = getattr(engine, "spatial_shards", 1)
    if shards <= 1:
        return ladder
    from waternet_tpu.parallel.spatial import HALO

    min_h = 2 * HALO * shards
    return BucketLadder(
        {(max(-(-bh // shards) * shards, min_h), bw) for bh, bw in ladder}
    )


def resolve_ladder(
    spec: str,
    shapes: Optional[Sequence[Tuple[int, int]]] = None,
    max_buckets: int = 3,
) -> BucketLadder:
    """CLI-facing ladder resolution: ``"auto"`` derives from the scanned
    ``shapes`` (falling back to the default square ladder when no shapes
    are known), anything else parses as an explicit bucket list."""
    from waternet_tpu.serving.bucketing import derive_buckets, parse_buckets

    if spec.strip().lower() == "auto":
        if shapes:
            return derive_buckets(shapes, max_buckets=max_buckets)
        return parse_buckets("256,512,1080x1920")
    return parse_buckets(spec)
