"""Compute reuse: temporal frame-delta gating for streams and a
content-addressed response cache for ``/enhance`` (docs/SERVING.md
"Temporal reuse & response cache").

Real underwater feeds — ROV pilots holding station, moorings,
surveillance pans — are dominated by static or slow-panning scenes, yet
the serving stack recomputes the full network for every frame. Two
independent reuse layers turn that redundancy into throughput:

* :class:`FrameDeltaGate` — per-stream temporal gating, split into a
  read-time *decision* and a delivery-time *materialization*. The
  session keeps a decimated grayscale thumbnail of the last frame
  SUBMITTED for compute (the anchor); each incoming frame scores a
  cheap mean-absolute delta against that thumbnail (optionally the
  minimum over a coarse block-flow search, which recognises slow pans)
  and, at or below the threshold, is marked for reuse and never enters
  the batcher. Anchoring on submission rather than on delivery is what
  makes reuse work under backlog: an open-loop camera that outruns the
  server still gates frames 1..N against frame 0 while frame 0 is
  still computing. Because sessions deliver strictly in order, the
  anchor's enhanced output is recorded before any of its reuse
  children are materialized; if the anchor never delivered (dropped or
  errored), the children become honest ``anchor`` drops instead of
  replaying the wrong scene. Scores always compare against the last
  SUBMITTED frame, never the last reused one, so slow drift
  accumulates until it crosses the threshold and forces a recompute —
  reuse cannot creep away from the content. A ``max_reuse_run`` cap
  bounds staleness: after that many consecutive reuses the next frame
  recomputes no matter what the detector says, so a stuck detector can
  never freeze a stream.
* :class:`ResponseCache` — a bounded, thread-safe LRU over fully
  rendered ``/enhance`` answers, keyed on (payload digest, tier, bucket
  ladder identity, params generation). ``invalidate()`` (wired to
  ``POST /admin/reload``) bumps the generation and clears the table, so
  an answer computed under old weights can never serve after a reload.

Exactness: a delta-of-zero frame reuses the *identical* enhanced array,
and the PNG encoder is deterministic, so the reused record is
byte-identical to what a recompute would have produced; likewise a
cache hit replays the exact stored bytes. Both layers are off by
default and tests pin that the disabled paths are byte-identical to the
always-compute behavior (tests/test_reuse.py).

Numpy only — the whole point is that the gate never touches jax, so
reused frames compile nothing and cost no device time.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional, Tuple

import numpy as np

#: Delta scores are computed on an at-most this-many-cells-per-edge
#: grayscale thumbnail (strided sampling — no resize dependency). Small
#: enough to be free next to a decode, large enough that a scene cut is
#: unmistakable.
DECIMATED_EDGE = 64

#: Coarse block-flow search radius, in decimated-grid cells per axis.
#: With warp enabled the gate scores min over (2R+1)^2 integer offsets,
#: so a pan of up to R cells per frame still gates as "same scene".
FLOW_RADIUS = 2

#: Default staleness cap: consecutive reuses before a recompute is
#: forced regardless of the delta score.
DEFAULT_MAX_REUSE_RUN = 30


def decimate(rgb: np.ndarray) -> np.ndarray:
    """Grayscale thumbnail of ``rgb`` by strided sampling, float32 in
    the input's value range. O(cells) work, no interpolation — the gate
    needs a stable cheap signature, not a pretty preview."""
    h, w = rgb.shape[:2]
    sy = max(1, h // DECIMATED_EDGE)
    sx = max(1, w // DECIMATED_EDGE)
    small = np.asarray(rgb[::sy, ::sx], dtype=np.float32)
    if small.ndim == 3:
        small = small.mean(axis=-1)
    return small


def delta_score(a: np.ndarray, b: np.ndarray) -> float:
    """Mean absolute difference between two equal-shape thumbnails
    (uint8 scale for uint8 inputs). 0.0 for identical frames."""
    return float(np.mean(np.abs(a - b)))


def block_flow(
    prev: np.ndarray, cur: np.ndarray, radius: int = FLOW_RADIUS
) -> Tuple[float, Tuple[int, int]]:
    """Coarse translational flow on the decimated grid: the integer
    offset ``(dx, dy)`` within ``radius`` minimizing the overlap MAE,
    with the backward-mapping convention of metrics/flicker.py —
    content at ``(x, y)`` in ``cur`` came from ``(x + dx, y + dy)`` in
    ``prev``. Returns ``(best_score, (dx, dy))``; ``(0, 0)`` wins ties,
    so a truly static frame never reports spurious motion."""
    h, w = cur.shape
    best = (delta_score(prev, cur), (0, 0))
    for dy in range(-radius, radius + 1):
        for dx in range(-radius, radius + 1):
            if dx == 0 and dy == 0:
                continue
            cy = slice(max(0, -dy), h - max(0, dy))
            cx = slice(max(0, -dx), w - max(0, dx))
            py = slice(max(0, dy), h - max(0, -dy))
            px = slice(max(0, dx), w - max(0, -dx))
            if cy.start >= cy.stop or cx.start >= cx.stop:
                continue
            score = delta_score(prev[py, px], cur[cy, cx])
            if score < best[0]:
                best = (score, (dx, dy))
    return best


def shift_frame(frame: np.ndarray, dx: float, dy: float) -> np.ndarray:  # loop-blocking: full-resolution numpy warp, milliseconds per frame
    """Motion-compensate ``frame`` by a constant backward flow
    ``(dx, dy)`` pixels (metrics/flicker.py warp semantics). Pixels
    whose source falls outside the frame keep their un-warped value —
    the cached content is a better guess at the newly exposed edge than
    clamped-border smear."""
    from waternet_tpu.metrics.flicker import warp

    h, w = frame.shape[:2]
    flow = np.empty((h, w, 2), dtype=np.float32)
    flow[..., 0] = dx
    flow[..., 1] = dy
    warped, valid = warp(frame, flow)
    out = frame.astype(np.float32).copy()
    out[valid] = warped[valid]
    if np.issubdtype(frame.dtype, np.integer):
        info = np.iinfo(frame.dtype)
        out = np.clip(np.rint(out), info.min, info.max)
    return out.astype(frame.dtype)


class FrameDeltaGate:
    """Per-session temporal gating state (one per :class:`StreamSession`).

    Single-task confinement, not locks: ``check``/``note_submitted``
    run on the session's reader task and ``note_computed``/
    ``materialize`` on its writer task, both on the same asyncio event
    loop thread — no concurrent access is possible, so the state below
    is deliberately unlocked. ``materialize`` may additionally run on
    an executor thread *on the writer task's behalf* (the full-frame
    warp is too heavy for the event loop — asynclint R201): that stays
    race-free because it only reads the writer-confined fields
    (``_enhanced``/``_flags``/``_computed_seq``) and the writer task is
    suspended awaiting it, while the reader task touches only its own
    fields (``_small``/``_shape``/``_anchor_seq``/``_run``).

    Protocol (see module docstring for why decision and answer are
    split): the reader calls ``check(rgb)`` per frame — ``None`` means
    compute (and, once the frame is actually submitted to the batcher,
    ``note_submitted(rgb, seq)`` makes it the new anchor); a decision
    tuple means reuse. The writer calls ``note_computed(seq, enhanced,
    flags)`` when it delivers a computed frame and
    ``materialize(decision)`` when it reaches a reuse child —
    ``(enhanced, flags)`` to replay, or ``None`` when the child's
    anchor never delivered.
    """

    def __init__(
        self,
        threshold: float,
        max_reuse_run: int = DEFAULT_MAX_REUSE_RUN,
        warp: bool = False,
    ):
        if threshold < 0:
            raise ValueError(f"reuse threshold must be >= 0, got {threshold}")
        if max_reuse_run < 1:
            raise ValueError(
                f"max_reuse_run must be >= 1, got {max_reuse_run}"
            )
        self.threshold = float(threshold)
        self.max_reuse_run = int(max_reuse_run)
        self.warp = bool(warp)
        self._small: Optional[np.ndarray] = None  # decimated anchor
        self._shape = None  # raw shape of the anchor frame
        self._anchor_seq: Optional[int] = None  # last submitted frame
        self._run = 0  # consecutive reuse decisions since the anchor
        self._enhanced: Optional[np.ndarray] = None  # last delivered
        self._flags = 0  # record flags the delivered frame carried
        self._computed_seq: Optional[int] = None  # its sequence number

    def check(
        self, rgb: np.ndarray
    ) -> Optional[Tuple[float, float, int]]:
        """Gate one incoming frame: a ``(dx, dy, anchor_seq)`` reuse
        decision (full-resolution backward flow, ``(0, 0)`` for a
        static scene) when it may be answered from the anchor's output,
        ``None`` when it must be computed (no anchor yet, resolution
        change, scene change, or the staleness cap)."""
        if self._small is None or rgb.shape != self._shape:
            return None
        if self._run >= self.max_reuse_run:
            return None
        small = decimate(rgb)
        if self.warp:
            score, (dx, dy) = block_flow(self._small, small)
        else:
            score, (dx, dy) = delta_score(self._small, small), (0, 0)
        if score > self.threshold:
            return None
        self._run += 1
        # Decimated-grid offset -> full-resolution pixels: the stride
        # the thumbnail was sampled with scales the motion.
        h, w = self._shape[:2]
        return (
            float(dx * max(1, w // DECIMATED_EDGE)),
            float(dy * max(1, h // DECIMATED_EDGE)),
            self._anchor_seq,
        )

    def note_submitted(self, rgb: np.ndarray, seq: int) -> None:
        """Record a frame submitted for compute as the new anchor."""
        self._small = decimate(rgb)
        self._shape = rgb.shape
        self._anchor_seq = int(seq)
        self._run = 0

    def note_computed(
        self, seq: int, enhanced: np.ndarray, flags: int = 0
    ) -> None:
        """Record a delivered computed frame's output (writer side)."""
        self._enhanced = enhanced
        self._flags = int(flags)
        self._computed_seq = int(seq)

    def materialize(
        self, decision: Tuple[float, float, int]
    ) -> Optional[Tuple[np.ndarray, int]]:
        """The cached ``(enhanced, flags)`` answer for a reuse decision
        (warped when the decision carries motion), or ``None`` when the
        decision's anchor never delivered — it was dropped or errored
        before its turn, so the cached output belongs to an older scene
        and replaying it would show the wrong content."""
        dx, dy, anchor_seq = decision
        if self._enhanced is None or self._computed_seq != anchor_seq:
            return None
        out = self._enhanced
        if dx or dy:
            out = shift_frame(out, dx, dy)
        return out, self._flags


class ResponseCache:
    """Bounded LRU over fully rendered ``/enhance`` answers.

    Keys are built by :meth:`key` from (payload digest, tier, the
    ladder identity fixed at construction, the current params
    generation); values are whatever the owner stores (the worker
    stores the response PNG, the fleet router a (ctype, headers, body)
    triple). ``invalidate()`` bumps the generation and clears the
    table — a ``put`` that raced a reload carries the old generation in
    its key and is refused, so stale-weights answers can never enter.

    Thread-safe: the front door's executor threads and the reload
    thread all touch it.
    """

    def __init__(self, capacity: int, ladder_id: str = ""):
        if capacity < 1:
            raise ValueError(f"cache capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.ladder_id = str(ladder_id)
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # guarded-by: self._lock
        self._generation = 0  # guarded-by: self._lock
        self._hits = 0  # guarded-by: self._lock
        self._misses = 0  # guarded-by: self._lock
        self._evictions = 0  # guarded-by: self._lock

    @staticmethod
    def digest(payload: bytes) -> str:
        return hashlib.sha256(payload).hexdigest()

    def key(self, payload: bytes, tier: str) -> tuple:
        with self._lock:
            gen = self._generation
        return (self.digest(payload), str(tier), self.ladder_id, gen)

    def get(self, key: tuple):
        """Stored value for ``key`` (bumped to most-recently-used), or
        None. Every call counts as a hit or a miss."""
        with self._lock:
            val = self._entries.get(key)
            if val is None:
                self._misses += 1
                return None
            self._entries.move_to_end(key)
            self._hits += 1
            return val

    def put(self, key: tuple, value) -> None:
        with self._lock:
            if key[-1] != self._generation:
                return  # computed under pre-reload params: refuse
            self._entries[key] = value
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self._evictions += 1

    def invalidate(self) -> int:
        """Drop everything and bump the params generation (the
        ``/admin/reload`` hook). Returns the new generation."""
        with self._lock:
            self._generation += 1
            self._entries.clear()
            return self._generation

    def counters(self) -> dict:
        """The ``cache`` block of ``/stats`` (docs/SERVING.md)."""
        with self._lock:
            return {
                "enabled": True,
                "hits": self._hits,
                "misses": self._misses,
                "evictions": self._evictions,
                "entries": len(self._entries),
                "capacity": self.capacity,
                "generation": self._generation,
            }


def empty_cache_block() -> dict:
    """The ``cache`` stats block for a server with no cache configured —
    same keys as :meth:`ResponseCache.counters`, all zeros."""
    return {
        "enabled": False,
        "hits": 0,
        "misses": 0,
        "evictions": 0,
        "entries": 0,
        "capacity": 0,
        "generation": 0,
    }
