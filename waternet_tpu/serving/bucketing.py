"""Shape bucketing: serve arbitrary-resolution streams with a fixed,
small set of compiled executables.

``run_images_batched`` historically flushed its pending batch on every
shape change and paid a fresh XLA compile per unique resolution — on a
mixed-resolution stream (UIEB challenge-60, any user-upload workload)
that degrades to fragment batches with compile stalls on the critical
path. Fast FCN operators are only fast when the executable is reused and
batches stay full (Chen et al. 2017, arXiv:1709.00643; Johnson et al.
2016, arXiv:1603.08155). The fix is a small ladder of compile *buckets*:
every input is padded up to the smallest bucket that covers it, the whole
stream is served by at most ``len(buckets)`` executables, and the output
is cropped back to the native shape.

Exactness policy (pinned in tests/test_serving.py, argued in
docs/SERVING.md): padding is applied on the bottom/right edges only, so
the original image occupies the top-left corner of the padded canvas and
its top/left borders see the exact same SAME-conv zero padding as the
native forward. WaterNet's receptive-field radius is
:data:`RECEPTIVE_RADIUS` = 13 pixels (the confidence-map trunk's
7/5/3/1/7/5/3 convs plus the final 3x3 — the same number the spatial
halo exchange uses, ``waternet_tpu.parallel.spatial.HALO``). A pixel
farther than that from the pad seam has a receptive field that lies
entirely inside original content, so its output is **bit-identical** to
the native-shape forward; only the bottom/right seam band of width 13
can differ, and there the reflect-pad content keeps the error
PSNR-bounded rather than the hard discontinuity zero-padding would give.

Inputs are reflect-padded (mirror without repeating the seam row) when
the pad fits in one reflection, falling back to edge-replication for
pads wider than the image — the interior exactness argument does not
depend on what the pad contains.
"""

from __future__ import annotations

from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Tuple

import numpy as np

from waternet_tpu.models.waternet import _CMG_SPEC

#: WaterNet's receptive-field radius in pixels: the confidence-map trunk
#: (kernels 7/5/3/1/7/5/3) plus its final 3x3 conv. The refiner branches'
#: radius (7/5/3 -> 6) is strictly smaller, so the fused output's radius
#: is the trunk's. Must equal waternet_tpu.parallel.spatial.HALO (tested).
RECEPTIVE_RADIUS = sum((k - 1) // 2 for _, k in _CMG_SPEC) + 1

Bucket = Tuple[int, int]  # (height, width)


class BucketLadder:
    """An ordered ladder of (H, W) compile buckets.

    :meth:`bucket_for` maps a native shape to the *smallest-area* bucket
    that covers it in both dimensions, or ``None`` when the shape
    overflows every bucket (the caller falls back to a native-shape
    forward and counts it).
    """

    def __init__(self, buckets: Iterable[Bucket]):
        seen = sorted({(int(h), int(w)) for h, w in buckets})
        if not seen:
            raise ValueError("bucket ladder needs at least one (H, W) bucket")
        for h, w in seen:
            if h <= 0 or w <= 0:
                raise ValueError(f"bucket {h}x{w} is not a valid shape")
        # Smallest-area-first so bucket_for's first hit is the cheapest.
        self.buckets: List[Bucket] = sorted(seen, key=lambda b: (b[0] * b[1], b))

    def bucket_for(self, h: int, w: int) -> Optional[Bucket]:
        for bh, bw in self.buckets:
            if bh >= h and bw >= w:
                return (bh, bw)
        return None

    def __len__(self) -> int:
        return len(self.buckets)

    def __iter__(self):
        return iter(self.buckets)

    def __repr__(self) -> str:
        return "BucketLadder(%s)" % ", ".join(f"{h}x{w}" for h, w in self.buckets)

    def describe(self) -> List[str]:
        return [f"{h}x{w}" for h, w in self.buckets]


def parse_buckets(spec: str) -> BucketLadder:
    """``"256,512,1080x1920"`` -> ladder of (256,256), (512,512),
    (1080,1920). A bare integer is a square bucket; ``HxW`` is explicit."""
    buckets = []
    for tok in spec.split(","):
        tok = tok.strip().lower()
        if not tok:
            continue
        try:
            if "x" in tok:
                h, w = tok.split("x")
                buckets.append((int(h), int(w)))
            else:
                buckets.append((int(tok), int(tok)))
        except ValueError:
            raise ValueError(
                f"bad bucket {tok!r} in {spec!r}: use N (square) or HxW"
            ) from None
    return BucketLadder(buckets)


def derive_buckets(
    shapes: Sequence[Tuple[int, int]], max_buckets: int = 3
) -> BucketLadder:
    """Auto-derive a ladder of at most ``max_buckets`` buckets from the
    native shapes of a scanned directory, minimizing total padded pixels.

    Shapes are sorted by height and partitioned into contiguous groups;
    each group's bucket is its elementwise (max H, max W), which always
    covers every member. The partition minimizing total padded area is
    found by O(n^2 * k) dynamic programming — a directory scan is a few
    hundred shapes, so exact beats clever here.
    """
    uniq = sorted({(int(h), int(w)) for h, w in shapes})
    if not uniq:
        raise ValueError("derive_buckets needs at least one shape")
    k = min(max_buckets, len(uniq))
    n = len(uniq)

    # cost(i, j): padded area of covering uniq[i..j] with one bucket
    # (max H over the slice is uniq[j][0] since sorted by H; W needs a
    # max). A prefix sum of native areas keeps each evaluation O(1), so
    # the DP stays O(n^2 k) as claimed.
    maxw_from = [[0] * n for _ in range(n)]
    for i in range(n):
        mw = 0
        for j in range(i, n):
            mw = max(mw, uniq[j][1])
            maxw_from[i][j] = mw
    area_pref = [0] * (n + 1)
    for i, (h, w) in enumerate(uniq):
        area_pref[i + 1] = area_pref[i] + h * w

    def cost(i: int, j: int) -> int:
        bh, bw = uniq[j][0], maxw_from[i][j]
        return (j - i + 1) * bh * bw - (area_pref[j + 1] - area_pref[i])

    INF = float("inf")
    best = [[INF] * (k + 1) for _ in range(n + 1)]
    back = [[0] * (k + 1) for _ in range(n + 1)]
    best[0][0] = 0
    for j in range(1, n + 1):
        for g in range(1, k + 1):
            for i in range(j):
                if best[i][g - 1] == INF:
                    continue
                c = best[i][g - 1] + cost(i, j - 1)
                if c < best[j][g]:
                    best[j][g] = c
                    back[j][g] = i
    g = min(range(1, k + 1), key=lambda gg: best[n][gg])
    cuts = []
    j = n
    while g:
        i = back[j][g]
        cuts.append((i, j))
        j, g = i, g - 1
    buckets = [
        (uniq[j - 1][0], maxw_from[i][j - 1]) for i, j in reversed(cuts)
    ]
    return BucketLadder(buckets)


def scan_shapes(
    paths: Iterable[Path], decode_budget: int = 16
) -> List[Tuple[int, int]]:
    """Native (H, W) of each readable image, header-only where possible.

    Uses the shared container-header parser
    (:func:`waternet_tpu.utils.imagemeta.image_shape` — the same pass-1
    trick score.py's no-reference path uses). Containers it can't parse
    (e.g. GIF) fall back to a full ``cv2.imread`` decode, but only for
    the first ``decode_budget`` such files: the ladder only needs a
    shape *sample*, and decoding an entire unparseable directory twice
    per run (once here, once in the serving pipeline) is exactly the
    cost the header-only scan exists to avoid. Unreadable files are
    skipped; the batcher skips them again at decode time.
    """
    from waternet_tpu.utils.imagemeta import image_shape

    shapes = []
    for p in paths:
        shape = image_shape(p)
        if shape is None and decode_budget > 0:
            import cv2

            decode_budget -= 1
            im = cv2.imread(str(p))
            shape = None if im is None else im.shape
        if shape is not None:
            shapes.append((int(shape[0]), int(shape[1])))
    return shapes


def pad_to_bucket(img: np.ndarray, bh: int, bw: int) -> np.ndarray:
    """Pad an (H, W, C) array to (bh, bw, C) on the bottom/right edges.

    Reflect (mirror, seam row not repeated) keeps the seam band smooth;
    np.pad's reflect cannot exceed ``dim - 1``, so wider pads fall back to
    edge replication per axis. Top/left are never padded — the exactness
    policy requires the original content to keep its top-left corner.
    """
    h, w = img.shape[:2]
    if bh < h or bw < w:
        raise ValueError(f"image {h}x{w} does not fit bucket {bh}x{bw}")
    if bh == h and bw == w:
        return img
    out = img
    pad_h, pad_w = bh - h, bw - w
    if pad_h:
        mode = "reflect" if pad_h <= h - 1 else "edge"
        out = np.pad(out, ((0, pad_h), (0, 0), (0, 0)), mode=mode)
    if pad_w:
        mode = "reflect" if pad_w <= w - 1 else "edge"
        out = np.pad(out, ((0, 0), (0, pad_w), (0, 0)), mode=mode)
    return out


def padding_overhead(
    shapes: Sequence[Tuple[int, int]], ladder: BucketLadder
) -> float:
    """Fraction of padded-canvas pixels that are padding, over a shape
    population served by ``ladder`` (oversize shapes serve at native
    resolution and contribute zero padding)."""
    real = padded = 0
    for h, w in shapes:
        b = ladder.bucket_for(h, w)
        bh, bw = b if b is not None else (h, w)
        real += h * w
        padded += bh * bw
    return 0.0 if padded == 0 else 1.0 - real / padded
