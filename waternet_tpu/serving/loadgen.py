"""Closed-loop load generator for the HTTP front door (serving/server.py).

Closed-loop means each of ``concurrency`` workers keeps exactly one
request outstanding on its own persistent connection — offered load
tracks the server's actual capacity times the concurrency, which is the
honest way to find a saturation point (an open-loop generator measures
its own timer, not the server). Doubling ``concurrency`` past saturation
is therefore "2x sustainable offered load": the regime where admission
control must shed rather than queue (the bench.py ``serve_http`` config
runs exactly that A/B).

Accounting is total: every request ends in exactly one of ``ok`` /
``shed`` (429) / ``deadline_expired`` (504) / ``rejected`` (other 4xx/
5xx, e.g. 503 while draining) / ``conn_reset`` (the peer closed the
connection mid-exchange — the signature of a graceful drain racing a
pooled client, NOT a crash) / ``errors`` (hard transport failures:
refused, timed out, unroutable), so the overload acceptance criterion —
no silent drops — is checkable from the report alone, and a drain test
can tell a graceful close from a dead server. ``downgraded`` counts the
subset of ``ok`` responses served by a different tier than requested
(the server's ``X-Tier-Served`` header under brown-out,
docs/SERVING.md "Fault isolation") — drive opt-in traffic with
``allow_downgrade=True`` / ``--allow-downgrade``. Stdlib-only
(http.client + threads); worker threads carry the pipeline
``THREAD_PREFIX`` so the test suite's leak guard covers them.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
import threading
import time
from typing import Dict, List, Optional
from urllib.parse import urlparse

from waternet_tpu.data.pipeline import THREAD_PREFIX
from waternet_tpu.serving.stats import _percentile


def run_load(
    url: str,
    payloads: List[bytes],
    concurrency: int = 4,
    total: int = 64,
    deadline_ms: Optional[float] = None,
    path: str = "/enhance",
    timeout: float = 120.0,
    keep_bodies: bool = False,
    tier: Optional[str] = None,
    allow_downgrade: bool = False,
) -> Dict:
    """Drive ``total`` POSTs at ``path`` with ``concurrency`` closed-loop
    workers cycling through ``payloads``; returns the accounting report.

    ``keep_bodies=True`` additionally returns ``bodies`` — a list of
    ``(request_index, status, body_bytes)`` — so byte-identity tests can
    check every response against the offline path. ``tier`` is forwarded
    as ``X-Tier``; ``allow_downgrade=True`` sets
    ``X-Tier-Allow-Downgrade: 1`` (the brown-out opt-in) and the report's
    ``downgraded`` counts 200s whose ``X-Tier-Served`` differs from the
    requested tier.
    """
    u = urlparse(url)
    host, port = u.hostname, u.port or 80
    lock = threading.Lock()
    counts = {
        "ok": 0, "shed": 0, "deadline_expired": 0, "rejected": 0,
        "conn_reset": 0, "errors": 0, "downgraded": 0,
    }
    latencies: List[float] = []
    bodies: List = []
    indices = itertools.count()

    def worker():
        import http.client

        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            while True:
                with lock:
                    i = next(indices)
                if i >= total:
                    break
                payload = payloads[i % len(payloads)]
                headers = {"Content-Type": "application/octet-stream"}
                if deadline_ms is not None:
                    headers["X-Deadline-Ms"] = str(deadline_ms)
                if tier is not None:
                    headers["X-Tier"] = tier
                if allow_downgrade:
                    headers["X-Tier-Allow-Downgrade"] = "1"
                t0 = time.perf_counter()
                try:
                    conn.request("POST", path, body=payload, headers=headers)
                    resp = conn.getresponse()
                    body = resp.read()
                    status = resp.status
                    served = resp.getheader("X-Tier-Served", "")
                    closed = (
                        resp.getheader("Connection", "").lower() == "close"
                    )
                except Exception as err:
                    # A peer closing mid-exchange (ConnectionResetError,
                    # incl. http.client.RemoteDisconnected, BrokenPipeError)
                    # is what a graceful drain looks like to a pooled
                    # client — counted apart from hard transport errors
                    # (refused, timed out): a drain is not a crash.
                    key = (
                        "conn_reset"
                        if isinstance(
                            err, (ConnectionResetError, BrokenPipeError)
                        )
                        else "errors"
                    )
                    with lock:
                        counts[key] += 1
                    conn.close()
                    conn = http.client.HTTPConnection(
                        host, port, timeout=timeout
                    )
                    continue
                dt = time.perf_counter() - t0
                with lock:
                    if status == 200:
                        counts["ok"] += 1
                        latencies.append(dt)
                        # Only meaningful when a tier was REQUESTED: a
                        # fast-default server answering tier-less traffic
                        # with X-Tier-Served: fast is not a downgrade.
                        if tier is not None and served and served != tier:
                            counts["downgraded"] += 1
                    elif status == 429:
                        counts["shed"] += 1
                    elif status == 504:
                        counts["deadline_expired"] += 1
                    else:
                        counts["rejected"] += 1
                    if keep_bodies:
                        bodies.append((i, status, body))
                if closed:
                    conn.close()
                    conn = http.client.HTTPConnection(
                        host, port, timeout=timeout
                    )
        finally:
            conn.close()

    threads = [
        threading.Thread(
            target=worker, name=f"{THREAD_PREFIX}-loadgen-{i}", daemon=True
        )
        for i in range(max(1, int(concurrency)))
    ]
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t0

    lat_sorted = sorted(latencies)
    report = {
        "sent": total,
        **counts,
        "images_per_sec": round(counts["ok"] / elapsed, 2) if elapsed else 0.0,
        "elapsed_sec": round(elapsed, 3),
        "concurrency": int(concurrency),
        "latency_ms": {
            "p50": round(_percentile(lat_sorted, 0.50) * 1e3, 3),
            "p99": round(_percentile(lat_sorted, 0.99) * 1e3, 3),
        },
    }
    if keep_bodies:
        report["bodies"] = bodies
    return report


def _synthetic_payloads(spec: str, n: int = 8) -> List[bytes]:
    """``HxW`` -> n deterministic PNG payloads (no dataset needed)."""
    import cv2
    import numpy as np

    h, w = (int(x) for x in spec.lower().split("x"))
    rng = np.random.default_rng(0)
    out = []
    for _ in range(n):
        img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".png", img)
        assert ok
        out.append(buf.tobytes())
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="waternet-loadgen", description=__doc__
    )
    parser.add_argument("--url", type=str, required=True)
    parser.add_argument(
        "--source", type=str, default=None,
        help="Directory of images to POST (defaults to synthetic frames).",
    )
    parser.add_argument(
        "--synthetic", type=str, default="112x150",
        help="HxW of synthetic payloads when --source is not given.",
    )
    parser.add_argument("--concurrency", type=int, default=4)
    parser.add_argument("--requests", type=int, default=64)
    parser.add_argument("--deadline-ms", type=float, default=None)
    parser.add_argument(
        "--tier", type=str, default=None,
        choices=["quality", "fast"],
        help="Forwarded as X-Tier (default: no header, the server's "
        "default tier).",
    )
    parser.add_argument(
        "--allow-downgrade", action="store_true", default=False,
        help="Opt this traffic into brown-out downgrades "
        "(X-Tier-Allow-Downgrade: 1): under saturation the server may "
        "serve quality requests from the fast tier instead of shedding "
        "— the report's 'downgraded' counts how often it did.",
    )
    args = parser.parse_args(argv)

    if args.source:
        from pathlib import Path

        payloads = [
            p.read_bytes()
            for p in sorted(Path(args.source).glob("*"))
            if p.suffix.lower() in (".png", ".jpg", ".jpeg", ".bmp")
        ]
        if not payloads:
            print(f"no images under {args.source}", file=sys.stderr)
            return 2
    else:
        payloads = _synthetic_payloads(args.synthetic)
    report = run_load(
        args.url,
        payloads,
        concurrency=args.concurrency,
        total=args.requests,
        deadline_ms=args.deadline_ms,
        tier=args.tier,
        allow_downgrade=args.allow_downgrade,
    )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
