"""Closed-loop load generator for the HTTP front door (serving/server.py).

Closed-loop means each of ``concurrency`` workers keeps exactly one
request outstanding on its own persistent connection — offered load
tracks the server's actual capacity times the concurrency, which is the
honest way to find a saturation point (an open-loop generator measures
its own timer, not the server). Doubling ``concurrency`` past saturation
is therefore "2x sustainable offered load": the regime where admission
control must shed rather than queue (the bench.py ``serve_http`` config
runs exactly that A/B).

Accounting is total: every request ends in exactly one of ``ok`` /
``shed`` (429) / ``deadline_expired`` (504) / ``rejected`` (other 4xx/
5xx, e.g. 503 while draining) / ``conn_reset`` (the peer closed the
connection mid-exchange — the signature of a graceful drain racing a
pooled client, NOT a crash) / ``errors`` (hard transport failures:
refused, timed out, unroutable), so the overload acceptance criterion —
no silent drops — is checkable from the report alone, and a drain test
can tell a graceful close from a dead server. ``downgraded`` counts the
subset of ``ok`` responses served by a different tier than requested
(the server's ``X-Tier-Served`` header under brown-out,
docs/SERVING.md "Fault isolation") — drive opt-in traffic with
``allow_downgrade=True`` / ``--allow-downgrade``. Stdlib-only
(http.client + threads); worker threads carry the pipeline
``THREAD_PREFIX`` so the test suite's leak guard covers them.

``--arrival-rate R`` switches request mode to **open-loop Poisson**
arrivals: request ``i`` is launched at a pre-drawn schedule time
(seeded exponential inter-arrival gaps at R req/s) regardless of what
came back — the regime where adaptive coalescing earns its keep,
because a closed-loop generator's arrival rate collapses to the
server's service rate and never exercises a queue-empty wait.
``--rate-ramp "0:20,10:200,20:20"`` drives a piecewise-constant rate
profile (seconds:rate pairs) for surge/decay drills; ``concurrency``
then bounds only the in-flight parallelism, not the offered rate.

``--stream`` switches to :func:`run_stream_load`: N paced concurrent
``POST /stream`` sessions (open-loop — live cameras do not slow down for
a busy server) with per-frame latency / drop / downgrade accounting and
the same conn_reset-vs-errors split.

Both modes add a ``window`` block — throughput and p50/p99 over only
the trailing ``--window-sec`` (default 10 s) of completions, the figure
that survives a run long enough to degrade (a lifetime average lets the
fast first minute pay for the saturated last one). ``--ledger PATH``
(request mode) additionally records every request as
``{"t", "latency_ms", "outcome"}`` for offline SLO replay:
``waternet-trace slo PATH --slo "p99_ms<=250,..."``.
"""

from __future__ import annotations

import argparse
import itertools
import json
import random
import struct
import sys
import threading
import time
from typing import Dict, List, Optional, Tuple
from urllib.parse import urlparse

from waternet_tpu.data.pipeline import THREAD_PREFIX
from waternet_tpu.obs.trace import new_request_id
from waternet_tpu.serving.stats import _percentile

#: Cap on the per-request failure ledger in a report: enough to chase
#: every id in a test run, bounded so a saturation run's report stays a
#: report (the full counts are always exact; only the ledger truncates,
#: and ``failures_truncated`` says by how much).
MAX_FAILURE_RECORDS = 128

#: Default trailing span for the report's ``window`` block.
DEFAULT_WINDOW_SEC = 10.0


def _window_block(
    samples: List, window_sec: float, now: Optional[float] = None
) -> Dict:
    """Trailing-``window_sec`` throughput/latency from completion
    ``(t, latency_sec)`` samples (``t`` relative to run start).

    Lifetime averages hide the end state of a run that degrades —
    the first fast minute pays for the last saturated one. This block
    reports only completions with ``t`` in ``(now - window_sec, now]``;
    the rate divisor is ``min(window_sec, now)`` so a run shorter than
    the window is not under-reported. Pure so tests can pin it without
    a server.
    """
    if now is None:
        now = max((t for t, _ in samples), default=0.0)
    recent = sorted(lat for t, lat in samples if t > now - window_sec)
    span = max(min(window_sec, now), 1e-9)
    return {
        "window_sec": float(window_sec),
        "count": len(recent),
        "requests_per_sec": round(len(recent) / span, 2),
        "latency_ms": {
            "p50": round(_percentile(recent, 0.50) * 1e3, 3),
            "p99": round(_percentile(recent, 0.99) * 1e3, 3),
        },
    }


def parse_rate_ramp(spec: str) -> List[Tuple[float, float]]:
    """``"0:20,10:200,20:20"`` -> ``[(0.0, 20.0), (10.0, 200.0),
    (20.0, 20.0)]``: piecewise-constant offered rate, each pair giving
    the req/s that holds from that second onward. Segments must start
    at 0 and be strictly increasing in time; every rate must be > 0."""
    segments: List[Tuple[float, float]] = []
    for raw in spec.split(","):
        clause = raw.strip()
        if not clause:
            continue
        t_s, sep, r_s = clause.partition(":")
        if not sep:
            raise ValueError(
                f"rate ramp wants SEC:RATE pairs, got {clause!r}"
            )
        t, r = float(t_s), float(r_s)
        if r <= 0:
            raise ValueError(f"ramp rate must be > 0, got {clause!r}")
        if segments and t <= segments[-1][0]:
            raise ValueError(
                f"ramp times must be strictly increasing at {clause!r}"
            )
        segments.append((t, r))
    if not segments or segments[0][0] != 0.0:
        raise ValueError(f"rate ramp must start at second 0: {spec!r}")
    return segments


def arrival_schedule(
    total: int,
    arrival_rate: Optional[float] = None,
    rate_ramp: Optional[List[Tuple[float, float]]] = None,
    seed: int = 0,
) -> List[float]:
    """Pre-drawn open-loop Poisson send times (seconds from run start).

    Exponential inter-arrival gaps from a seeded PRNG, so the same
    (total, rate, seed) always offers the same trace — a bench A/B run
    (fixed vs adaptive coalescing) sees literally identical arrivals.
    With ``rate_ramp``, the gap after time ``t`` is drawn at the
    segment rate active at ``t`` (piecewise-constant intensity).
    """
    if (arrival_rate is None) == (rate_ramp is None):
        raise ValueError("need exactly one of arrival_rate / rate_ramp")
    if arrival_rate is not None:
        if arrival_rate <= 0:
            raise ValueError(f"arrival_rate must be > 0, got {arrival_rate}")
        rate_ramp = [(0.0, float(arrival_rate))]
    rng = random.Random(seed)
    times: List[float] = []
    t = 0.0
    for _ in range(int(total)):
        rate = rate_ramp[0][1]
        for t_seg, r_seg in rate_ramp:
            if t >= t_seg:
                rate = r_seg
        t += rng.expovariate(rate)
        times.append(t)
    return times


def run_load(
    url: str,
    payloads: List[bytes],
    concurrency: int = 4,
    total: int = 64,
    deadline_ms: Optional[float] = None,
    path: str = "/enhance",
    timeout: float = 120.0,
    keep_bodies: bool = False,
    tier: Optional[str] = None,
    allow_downgrade: bool = False,
    window_sec: float = DEFAULT_WINDOW_SEC,
    collect_ledger: bool = False,
    per_worker: bool = False,
    arrival_rate: Optional[float] = None,
    rate_ramp: Optional[List[Tuple[float, float]]] = None,
    arrival_seed: int = 0,
) -> Dict:
    """Drive ``total`` POSTs at ``path`` with ``concurrency`` closed-loop
    workers cycling through ``payloads``; returns the accounting report.

    ``arrival_rate`` (req/s) or ``rate_ramp`` (see
    :func:`parse_rate_ramp`) switches to open-loop Poisson arrivals:
    request ``i`` launches at a pre-drawn schedule time (seeded by
    ``arrival_seed``, see :func:`arrival_schedule`) independent of
    responses, and ``concurrency`` bounds only the in-flight
    parallelism. The report then carries an ``offered`` block with the
    schedule's realized span and any launch lag (a worker pool too
    small to keep up shows up as lag, not as a silently slower rate).

    ``keep_bodies=True`` additionally returns ``bodies`` — a list of
    ``(request_index, status, body_bytes)`` — so byte-identity tests can
    check every response against the offline path. ``tier`` is forwarded
    as ``X-Tier``; ``allow_downgrade=True`` sets
    ``X-Tier-Allow-Downgrade: 1`` (the brown-out opt-in) and the report's
    ``downgraded`` counts 200s whose ``X-Tier-Served`` differs from the
    requested tier. ``cache_hits`` counts 200s stamped ``X-Cache: hit``
    — answers replayed from a content-addressed response cache
    (docs/SERVING.md "Temporal reuse & response cache"); always 0
    against a cache-less server.

    Every request carries a unique ``X-Request-Id`` (``lg-<run>-<i>``),
    which the server echoes and stamps on its trace spans
    (docs/OBSERVABILITY.md): the report's ``failures`` ledger lists each
    non-ok request's id and outcome, so a shed/reset/error in a load run
    is findable in the server-side trace by the same id.

    The report's ``window`` block restates throughput and p50/p99 over
    only the trailing ``window_sec`` of completions (see
    :func:`_window_block`) — the figure to read on a run long enough to
    degrade. ``collect_ledger=True`` additionally returns ``ledger``:
    one ``{"t", "latency_ms", "outcome", "worker"}`` entry per request
    (``t`` seconds from run start), the input format of
    ``waternet-trace slo`` offline replay (docs/OBSERVABILITY.md).

    ``per_worker=True`` adds a ``per_worker`` block splitting the same
    total accounting by the ``X-Worker-Id`` the answering serving
    worker stamped (docs/SERVING.md "Fleet") — the client half of the
    fleet bench's ledger-vs-router reconciliation. Answers without the
    header (single-worker servers, router-originated errors) and
    transport failures (nobody answered) land under ``"unattributed"``.
    """
    u = urlparse(url)
    host, port = u.hostname, u.port or 80
    run_tag = new_request_id()[:8]
    sched: Optional[List[float]] = None
    if arrival_rate is not None or rate_ramp is not None:
        sched = arrival_schedule(
            total, arrival_rate=arrival_rate, rate_ramp=rate_ramp,
            seed=arrival_seed,
        )
    lock = threading.Lock()
    counts = {
        "ok": 0, "shed": 0, "deadline_expired": 0, "rejected": 0,
        "conn_reset": 0, "errors": 0, "downgraded": 0, "cache_hits": 0,
    }
    launch_lag = [0.0]  # worst (actual - scheduled) launch time, open-loop
    latencies: List[float] = []
    samples: List = []  # (t_done - t0, latency_sec) for ok requests
    ledger_entries: List[Dict] = []
    bodies: List = []
    failures: List[Dict] = []
    per_worker_counts: Dict[str, Dict[str, int]] = {}
    truncated = [0]
    indices = itertools.count()

    def record_failure(rec: Dict) -> None:
        # Caller holds `lock`.
        if len(failures) < MAX_FAILURE_RECORDS:
            failures.append(rec)
        else:
            truncated[0] += 1

    def record_ledger(rel_t: float, outcome: str,
                      latency_s: Optional[float],
                      worker: Optional[str] = None) -> None:
        # Caller holds `lock`.
        if collect_ledger:
            ledger_entries.append({
                "t": round(rel_t, 6),
                "latency_ms": (
                    None if latency_s is None else round(latency_s * 1e3, 3)
                ),
                "outcome": outcome,
                "worker": worker,
            })

    def record_worker(worker: Optional[str], outcome: str) -> None:
        # Caller holds `lock`. Split the same total accounting by the
        # serving worker that stamped X-Worker-Id on the answer.
        if not per_worker:
            return
        bucket = per_worker_counts.setdefault(
            worker or "unattributed",
            {"ok": 0, "shed": 0, "deadline_expired": 0, "rejected": 0,
             "conn_reset": 0, "errors": 0, "downgraded": 0},
        )
        bucket[outcome] += 1

    def worker():
        import http.client

        conn = http.client.HTTPConnection(host, port, timeout=timeout)
        try:
            while True:
                with lock:
                    i = next(indices)
                if i >= total:
                    break
                if sched is not None:
                    # Open-loop: fire at the pre-drawn Poisson time, not
                    # when the last answer lands. A starved worker pool
                    # fires late; the worst lag is reported, never hidden.
                    lag = t_run0 + sched[i] - time.perf_counter()
                    if lag > 0:
                        time.sleep(lag)
                    else:
                        with lock:
                            launch_lag[0] = max(launch_lag[0], -lag)
                payload = payloads[i % len(payloads)]
                rid = f"lg-{run_tag}-{i:05d}"
                headers = {
                    "Content-Type": "application/octet-stream",
                    "X-Request-Id": rid,
                }
                if deadline_ms is not None:
                    headers["X-Deadline-Ms"] = str(deadline_ms)
                if tier is not None:
                    headers["X-Tier"] = tier
                if allow_downgrade:
                    headers["X-Tier-Allow-Downgrade"] = "1"
                t0 = time.perf_counter()
                try:
                    conn.request("POST", path, body=payload, headers=headers)
                    resp = conn.getresponse()
                    body = resp.read()
                    status = resp.status
                    served = resp.getheader("X-Tier-Served", "")
                    wid = resp.getheader("X-Worker-Id", "") or None
                    cache_hit = resp.getheader("X-Cache", "") == "hit"
                    closed = (
                        resp.getheader("Connection", "").lower() == "close"
                    )
                except Exception as err:
                    # A peer closing mid-exchange (ConnectionResetError,
                    # incl. http.client.RemoteDisconnected, BrokenPipeError)
                    # is what a graceful drain looks like to a pooled
                    # client — counted apart from hard transport errors
                    # (refused, timed out): a drain is not a crash.
                    key = (
                        "conn_reset"
                        if isinstance(
                            err, (ConnectionResetError, BrokenPipeError)
                        )
                        else "errors"
                    )
                    with lock:
                        counts[key] += 1
                        record_worker(None, key)
                        record_failure({
                            "request_id": rid,
                            "outcome": key,
                            "error": type(err).__name__,
                        })
                        record_ledger(
                            time.perf_counter() - t_run0, key, None
                        )
                    conn.close()
                    conn = http.client.HTTPConnection(
                        host, port, timeout=timeout
                    )
                    continue
                t1 = time.perf_counter()
                dt = t1 - t0
                with lock:
                    if status == 200:
                        counts["ok"] += 1
                        if cache_hit:
                            # Content-addressed response cache answered
                            # (X-Cache: hit) — still an ok, also tallied
                            # so closed-loop runs can report hit rate.
                            counts["cache_hits"] += 1
                        record_worker(wid, "ok")
                        latencies.append(dt)
                        samples.append((t1 - t_run0, dt))
                        record_ledger(t1 - t_run0, "ok", dt, worker=wid)
                        # Only meaningful when a tier was REQUESTED: a
                        # fast-default server answering tier-less traffic
                        # with X-Tier-Served: fast is not a downgrade.
                        if tier is not None and served and served != tier:
                            counts["downgraded"] += 1
                            record_worker(wid, "downgraded")
                    else:
                        if status == 429:
                            outcome = "shed"
                        elif status == 504:
                            outcome = "deadline_expired"
                        else:
                            outcome = "rejected"
                        counts[outcome] += 1
                        record_worker(wid, outcome)
                        record_failure({
                            "request_id": rid,
                            "outcome": outcome,
                            "status": status,
                        })
                        record_ledger(t1 - t_run0, outcome, None, worker=wid)
                    if keep_bodies:
                        bodies.append((i, status, body))
                if closed:
                    conn.close()
                    conn = http.client.HTTPConnection(
                        host, port, timeout=timeout
                    )
        finally:
            conn.close()

    threads = [
        threading.Thread(
            target=worker, name=f"{THREAD_PREFIX}-loadgen-{i}", daemon=True
        )
        for i in range(max(1, int(concurrency)))
    ]
    t_run0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_run0

    lat_sorted = sorted(latencies)
    report = {
        "sent": total,
        **counts,
        "images_per_sec": round(counts["ok"] / elapsed, 2) if elapsed else 0.0,
        "elapsed_sec": round(elapsed, 3),
        "concurrency": int(concurrency),
        "latency_ms": {
            "p50": round(_percentile(lat_sorted, 0.50) * 1e3, 3),
            "p99": round(_percentile(lat_sorted, 0.99) * 1e3, 3),
        },
        "window": _window_block(samples, window_sec, now=elapsed),
        "request_id_prefix": f"lg-{run_tag}",
        "failures": failures,
    }
    if sched is not None:
        report["offered"] = {
            "mode": "poisson",
            "rate": arrival_rate,
            "ramp": rate_ramp,
            "span_sec": round(sched[-1], 3) if sched else 0.0,
            "max_launch_lag_ms": round(launch_lag[0] * 1e3, 3),
        }
    if truncated[0]:
        report["failures_truncated"] = truncated[0]
    if keep_bodies:
        report["bodies"] = bodies
    if collect_ledger:
        report["ledger"] = sorted(ledger_entries, key=lambda e: e["t"])
    if per_worker:
        report["per_worker"] = per_worker_counts
    return report


# ----------------------------------------------------------------------
# Stream mode: N paced concurrent POST /stream sessions
# ----------------------------------------------------------------------

# Client-side copies of the stream wire framing
# (waternet_tpu/serving/streams.py — kept import-free here so the load
# generator stays stdlib-only; the protocol-compat tests drive this
# client against a live server, so drift cannot go unnoticed).
_FRAME_LEN = struct.Struct("!I")
_REC_HEAD = struct.Struct("!cBII")
_FLAG_DOWNGRADED = 1
_FLAG_REUSED = 2


def _read_exact(f, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes from a socket file, None on EOF."""
    chunks = []
    while n:
        chunk = f.read(n)
        if not chunk:
            return None
        chunks.append(chunk)
        n -= len(chunk)
    return b"".join(chunks)


def run_stream_load(
    url: str,
    payloads: List[bytes],
    streams: int = 4,
    frames: int = 16,
    fps: float = 10.0,
    budget_ms: Optional[float] = None,
    window: Optional[int] = None,
    tier: Optional[str] = None,
    allow_downgrade: bool = False,
    timeout: float = 120.0,
    window_sec: float = DEFAULT_WINDOW_SEC,
    per_worker: bool = False,
    reuse_threshold: Optional[float] = None,
    max_reuse_run: Optional[int] = None,
    reuse_warp: bool = False,
    keep_frames: bool = False,
) -> Dict:
    """Replay ``payloads`` as ``streams`` paced concurrent POST /stream
    sessions (``frames`` frames each at ``fps``); returns the aggregate
    per-frame accounting report.

    Open-loop per stream ON PURPOSE — a live camera does not slow down
    because the server is busy, so frame ``i`` is sent at
    ``t0 + i/fps`` regardless of what came back. Every sent frame ends
    in exactly one bucket: ``ok`` (enhanced frame delivered, its
    end-to-end latency sampled), ``dropped`` (explicit drop record:
    window overflow / queue shed / disconnect cleanup),
    ``out_of_budget`` (drop record with reason ``budget``),
    ``frame_errors`` (per-frame error record), or — when the connection
    died under the session — ``conn_reset`` / ``errors`` absorb the
    unaccounted remainder, split exactly as in :func:`run_load` (a
    graceful close is not a crash). ``refused`` counts sessions the
    server turned away at admission (503, degradation rung 3);
    ``downgraded`` counts delivered frames served by the fast tier
    under brown-out (the record's downgrade flag). ``per_worker=True``
    adds ``per_worker_sessions`` — accepted sessions counted by the
    ``X-Worker-Id`` on the response head, pinning which fleet worker
    each session landed on (docs/SERVING.md "Fleet").

    ``reuse_threshold`` opts the sessions into server-side temporal
    reuse (``X-Stream-Reuse``, docs/SERVING.md "Temporal reuse"):
    near-static frames come back as reuse records (wire kind ``R``),
    counted in ``reused`` — delivered answers that skipped compute, so
    the effective rate is ``(ok + reused)`` per stream-second and
    ``fps_per_stream`` counts both. ``max_reuse_run`` forwards the
    staleness cap (``X-Stream-Max-Reuse-Run``) and ``reuse_warp``
    enables coarse motion-compensated reuse (``X-Stream-Reuse-Warp``).
    ``keep_frames=True`` additionally returns ``frames`` — per stream
    index, the ordered ``(seq, kind, payload_bytes)`` of every
    delivered frame — so a bench can measure flicker on exactly what a
    viewer would see.
    """
    import socket

    u = urlparse(url)
    host, port = u.hostname, u.port or 80
    run_tag = new_request_id()[:8]
    lock = threading.Lock()
    counts = {
        "ok": 0, "reused": 0, "dropped": 0, "out_of_budget": 0,
        "frame_errors": 0, "downgraded": 0, "refused": 0, "conn_reset": 0,
        "errors": 0,
    }
    totals = {"frames_sent": 0}
    latencies: List[float] = []
    samples: List = []  # (t_recv - t_run0, latency_sec) delivered frames
    failures: List[Dict] = []
    session_workers: Dict[str, int] = {}  # X-Worker-Id -> sessions
    frames_out: Dict[int, List] = {}  # stream idx -> [(seq, kind, bytes)]

    def record_failure(rec: Dict) -> None:
        # Caller holds `lock`.
        if len(failures) < MAX_FAILURE_RECORDS:
            failures.append(rec)

    def stream_worker(si: int):
        rid = f"lg-{run_tag}-s{si}"
        t_sent: Dict[int, float] = {}
        accounted = 0  # frames that got a record (or a refusal)
        sent = 0
        reset = False
        sock = None
        try:
            sock = socket.create_connection((host, port), timeout=timeout)
            head = (
                "POST /stream HTTP/1.1\r\n"
                f"Host: {host}:{port}\r\n"
                f"X-Request-Id: {rid}\r\n"
                f"X-Stream-Fps: {fps}\r\n"
            )
            if budget_ms is not None:
                head += f"X-Stream-Budget-Ms: {budget_ms}\r\n"
            if window is not None:
                head += f"X-Stream-Window: {window}\r\n"
            if tier is not None:
                head += f"X-Tier: {tier}\r\n"
            if allow_downgrade:
                head += "X-Tier-Allow-Downgrade: 1\r\n"
            if reuse_threshold is not None:
                head += f"X-Stream-Reuse: {reuse_threshold}\r\n"
            if max_reuse_run is not None:
                head += f"X-Stream-Max-Reuse-Run: {max_reuse_run}\r\n"
            if reuse_warp:
                head += "X-Stream-Reuse-Warp: 1\r\n"
            head += "\r\n"
            sock.sendall(head.encode("latin-1"))
            f = sock.makefile("rb")
            status_line = f.readline()
            status = int(status_line.split()[1]) if status_line else 0
            wid = None
            while True:  # response headers: keep the worker stamp only
                line = f.readline()
                if not line or line in (b"\r\n", b"\n"):
                    break
                name, _, value = line.decode("latin-1").partition(":")
                if name.strip().lower() == "x-worker-id":
                    wid = value.strip() or None
            if status == 200 and per_worker:
                with lock:
                    key = wid or "unattributed"
                    session_workers[key] = session_workers.get(key, 0) + 1
            if status != 200:
                with lock:
                    outcome = "refused" if status == 503 else "errors"
                    counts[outcome] += 1
                    record_failure({
                        "request_id": rid,
                        "outcome": outcome,
                        "status": status,
                    })
                return

            done = threading.Event()

            def sender():
                nonlocal sent
                t0 = time.perf_counter()
                try:
                    for i in range(frames):
                        lag = t0 + i / fps - time.perf_counter()
                        if lag > 0:
                            time.sleep(lag)
                        payload = payloads[i % len(payloads)]
                        with lock:
                            t_sent[i] = time.perf_counter()
                        sock.sendall(
                            _FRAME_LEN.pack(len(payload)) + payload
                        )
                        sent += 1
                    sock.sendall(_FRAME_LEN.pack(0))  # clean end
                except OSError:
                    pass  # server closed mid-upload; reader accounts
                finally:
                    done.set()

            tx = threading.Thread(
                target=sender,
                name=f"{THREAD_PREFIX}-stream-tx-{si}",
                daemon=True,
            )
            tx.start()
            try:
                while True:
                    raw = _read_exact(f, _REC_HEAD.size)
                    if raw is None:
                        reset = True  # session ended without a Z record
                        break
                    kind, flags, seq, n = _REC_HEAD.unpack(raw)
                    payload = _read_exact(f, n) if n else b""
                    if n and payload is None:
                        reset = True
                        break
                    t_recv = time.perf_counter()
                    if kind == b"Z":
                        break
                    with lock:
                        accounted += 1
                        if kind in (b"F", b"R"):
                            counts["ok" if kind == b"F" else "reused"] += 1
                            if flags & _FLAG_DOWNGRADED:
                                counts["downgraded"] += 1
                            if kind == b"F" and seq in t_sent:
                                latencies.append(t_recv - t_sent[seq])
                                samples.append(
                                    (t_recv - t_run0, t_recv - t_sent[seq])
                                )
                            if keep_frames:
                                frames_out.setdefault(si, []).append(
                                    (seq, kind.decode("latin-1"), payload)
                                )
                        elif kind == b"D":
                            reason = json.loads(payload).get("reason")
                            counts[
                                "out_of_budget"
                                if reason == "budget"
                                else "dropped"
                            ] += 1
                        else:  # b"E"
                            counts["frame_errors"] += 1
            except OSError:
                reset = True
            done.wait(timeout)
        except OSError as err:
            with lock:
                key = (
                    "conn_reset"
                    if isinstance(
                        err, (ConnectionResetError, BrokenPipeError)
                    )
                    else "errors"
                )
                counts[key] += 1
                record_failure({
                    "request_id": rid,
                    "outcome": key,
                    "error": type(err).__name__,
                })
        finally:
            if sock is not None:
                try:
                    sock.close()
                except OSError:
                    pass
            with lock:
                totals["frames_sent"] += sent
                # Frames sent but never answered by any record: the
                # connection died under them. conn_reset, not silence.
                if reset and sent > accounted:
                    counts["conn_reset"] += sent - accounted
                    record_failure({
                        "request_id": rid,
                        "outcome": "conn_reset",
                        "frames_unaccounted": sent - accounted,
                    })

    threads = [
        threading.Thread(
            target=stream_worker,
            args=(i,),
            name=f"{THREAD_PREFIX}-stream-{i}",
            daemon=True,
        )
        for i in range(max(1, int(streams)))
    ]
    t_run0 = time.perf_counter()
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    elapsed = time.perf_counter() - t_run0

    lat_sorted = sorted(latencies)
    # Delivered = computed + reused: a reuse record is a real answer on
    # the wire, it just skipped the device. With reuse off (the
    # default) reused is 0 and this is the old ok-only figure.
    delivered = counts["ok"] + counts["reused"]
    per_worker_block = (
        {"per_worker_sessions": session_workers} if per_worker else {}
    )
    frames_block = {"frames": frames_out} if keep_frames else {}
    return {
        **per_worker_block,
        **frames_block,
        "streams": int(streams),
        "frames_per_stream": int(frames),
        "offered_fps": float(fps),
        **totals,
        **counts,
        "fps_per_stream": (
            round(delivered / max(1, int(streams)) / elapsed, 2)
            if elapsed else 0.0
        ),
        "elapsed_sec": round(elapsed, 3),
        "frame_latency_ms": {
            "p50": round(_percentile(lat_sorted, 0.50) * 1e3, 3),
            "p99": round(_percentile(lat_sorted, 0.99) * 1e3, 3),
        },
        "window": _window_block(samples, window_sec, now=elapsed),
        "request_id_prefix": f"lg-{run_tag}",
        "failures": failures,
    }


def _synthetic_payloads(spec: str, n: int = 8) -> List[bytes]:
    """``HxW`` -> n deterministic PNG payloads (no dataset needed)."""
    import cv2
    import numpy as np

    h, w = (int(x) for x in spec.lower().split("x"))
    rng = np.random.default_rng(0)
    out = []
    for _ in range(n):
        img = rng.integers(0, 256, (h, w, 3), dtype=np.uint8)
        ok, buf = cv2.imencode(".png", img)
        assert ok
        out.append(buf.tobytes())
    return out


def _stream_payloads(
    spec: str, n: int = 16, static_pct: int = 0, pan_px: int = 0,
) -> List[bytes]:
    """``HxW`` -> n deterministic PNG frames with a controlled
    redundancy mix, the input for temporal-reuse benchmarking.

    ``static_pct`` of the frames repeat their predecessor exactly (the
    pattern is deterministic: frame ``i`` changes content only when
    ``i * (100 - static_pct) // 100`` advances, so a 75%-static run is
    the same frames every time). When the content does change it is a
    fresh scene unless ``pan_px`` is set, in which case the scene pans
    — ``np.roll`` by ``pan_px`` columns per change — which a
    block-flow-warping gate can still reuse but a plain delta gate
    treats as motion. With ``static_pct=0, pan_px=0`` every frame is an
    independent scene (the always-compute control mix).
    """
    import cv2
    import numpy as np

    if not 0 <= int(static_pct) <= 100:
        raise ValueError("static_pct must be in [0, 100]")
    h, w = (int(x) for x in spec.lower().split("x"))
    rng = np.random.default_rng(0)
    # Structured base scene (smooth gradients + texture) rather than
    # pure noise: block matching on noise is meaningless, and real
    # camera frames are compressible structure, not static snow.
    yy, xx = np.mgrid[0:h, 0:w].astype(np.float32)
    base = np.stack(
        [
            127 + 90 * np.sin(xx / 11.0) * np.cos(yy / 7.0),
            127 + 90 * np.cos(xx / 5.0 + yy / 13.0),
            rng.integers(0, 256, (h, w)).astype(np.float32),
        ],
        axis=-1,
    ).clip(0, 255).astype(np.uint8)
    out = []
    img = base
    fresh = (100 - int(static_pct))
    for i in range(n):
        changed = i == 0 or (i * fresh) // 100 != ((i - 1) * fresh) // 100
        if changed and i > 0:
            if pan_px:
                img = np.roll(img, int(pan_px), axis=1)
            else:
                img = np.asarray(
                    rng.integers(0, 256, (h, w, 3)), dtype=np.uint8
                )
        ok, buf = cv2.imencode(".png", img)
        assert ok
        out.append(buf.tobytes())
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="waternet-loadgen", description=__doc__
    )
    parser.add_argument("--url", type=str, required=True)
    parser.add_argument(
        "--source", type=str, default=None,
        help="Directory of images to POST (defaults to synthetic frames).",
    )
    parser.add_argument(
        "--synthetic", type=str, default="112x150",
        help="HxW of synthetic payloads when --source is not given.",
    )
    parser.add_argument("--concurrency", type=int, default=4)
    parser.add_argument("--requests", type=int, default=64)
    parser.add_argument("--deadline-ms", type=float, default=None)
    parser.add_argument(
        "--arrival-rate", type=float, default=None, metavar="RPS",
        help="Open-loop Poisson arrivals at this rate (req/s) instead "
        "of closed-loop pacing: requests fire on a seeded exponential "
        "schedule regardless of responses; --concurrency then bounds "
        "only in-flight parallelism (request mode only).",
    )
    parser.add_argument(
        "--rate-ramp", type=str, default=None, metavar="SEC:RPS,...",
        help="Piecewise-constant open-loop rate profile, e.g. "
        "'0:20,10:200,20:20' — 20 req/s, surge to 200 at t=10 s, back "
        "at t=20 s (request mode only; excludes --arrival-rate).",
    )
    parser.add_argument(
        "--arrival-seed", type=int, default=0,
        help="PRNG seed for the Poisson schedule (same seed + rate = "
        "identical offered trace, for A/B runs).",
    )
    parser.add_argument(
        "--window-sec", type=float, default=DEFAULT_WINDOW_SEC,
        help="Trailing span of the report's 'window' block "
        "(throughput + p50/p99 over only the last N seconds of "
        "completions — the figure to read on a long degrading run).",
    )
    parser.add_argument(
        "--ledger", type=str, default=None,
        help="Write every request's {t, latency_ms, outcome} to this "
        "JSON file — replayable offline against an SLO spec with "
        "'waternet-trace slo LEDGER --slo ...' (request mode only).",
    )
    parser.add_argument(
        "--tier", type=str, default=None,
        choices=["quality", "fast"],
        help="Forwarded as X-Tier (default: no header, the server's "
        "default tier).",
    )
    parser.add_argument(
        "--allow-downgrade", action="store_true", default=False,
        help="Opt this traffic into brown-out downgrades "
        "(X-Tier-Allow-Downgrade: 1): under saturation the server may "
        "serve quality requests from the fast tier instead of shedding "
        "— the report's 'downgraded' counts how often it did.",
    )
    parser.add_argument(
        "--per-worker", action="store_true", default=False,
        help="Split the accounting by the X-Worker-Id each answer was "
        "stamped with (docs/SERVING.md 'Fleet'): request mode adds a "
        "'per_worker' counts block (and worker ids to --ledger "
        "entries), stream mode adds 'per_worker_sessions'. Answers "
        "without the header land under 'unattributed'.",
    )
    parser.add_argument(
        "--stream", action="store_true", default=False,
        help="Stream mode: replay the payloads as N paced concurrent "
        "POST /stream sessions (open-loop, like live cameras) with "
        "per-frame latency/drop/downgrade accounting instead of "
        "closed-loop /enhance requests.",
    )
    parser.add_argument(
        "--streams", type=int, default=4,
        help="Concurrent stream sessions (--stream mode).",
    )
    parser.add_argument(
        "--frames", type=int, default=16,
        help="Frames per stream (--stream mode).",
    )
    parser.add_argument(
        "--fps", type=float, default=10.0,
        help="Paced frame rate per stream, declared to the server as "
        "X-Stream-Fps (--stream mode).",
    )
    parser.add_argument(
        "--budget-ms", type=float, default=None,
        help="Per-frame freshness budget (X-Stream-Budget-Ms); default: "
        "the server derives 3000/fps (--stream mode).",
    )
    parser.add_argument(
        "--window", type=int, default=None,
        help="Per-stream delivery window (X-Stream-Window); default: "
        "the server's --stream-window (--stream mode).",
    )
    parser.add_argument(
        "--static-pct", type=int, default=None, metavar="PCT",
        help="Generate stream payloads where PCT%% of frames repeat "
        "their predecessor exactly (deterministic redundancy mix for "
        "temporal-reuse runs; --stream mode, replaces --synthetic "
        "noise frames).",
    )
    parser.add_argument(
        "--pan-px", type=int, default=0,
        help="When the generated scene changes, pan it by this many "
        "pixels instead of cutting to a fresh scene (exercises the "
        "warp path; needs --static-pct).",
    )
    parser.add_argument(
        "--reuse-threshold", type=float, default=None,
        help="Opt into server-side temporal reuse at this frame-delta "
        "threshold (X-Stream-Reuse); the report's 'reused' counts "
        "answers served from the reuse gate (--stream mode).",
    )
    parser.add_argument(
        "--max-reuse-run", type=int, default=None,
        help="Staleness cap forwarded as X-Stream-Max-Reuse-Run: at "
        "most N consecutive reused frames before a forced recompute.",
    )
    parser.add_argument(
        "--reuse-warp", action="store_true", default=False,
        help="Enable coarse motion-compensated reuse "
        "(X-Stream-Reuse-Warp: 1) for slow pans.",
    )
    args = parser.parse_args(argv)
    if args.arrival_rate is not None and args.rate_ramp is not None:
        print("--arrival-rate and --rate-ramp are exclusive",
              file=sys.stderr)
        return 2

    if args.source:
        from pathlib import Path

        payloads = [
            p.read_bytes()
            for p in sorted(Path(args.source).glob("*"))
            if p.suffix.lower() in (".png", ".jpg", ".jpeg", ".bmp")
        ]
        if not payloads:
            print(f"no images under {args.source}", file=sys.stderr)
            return 2
    elif args.stream and args.static_pct is not None:
        payloads = _stream_payloads(
            args.synthetic, n=max(args.frames, 1),
            static_pct=args.static_pct, pan_px=args.pan_px,
        )
    else:
        payloads = _synthetic_payloads(args.synthetic)
    if args.stream:
        report = run_stream_load(
            args.url,
            payloads,
            streams=args.streams,
            frames=args.frames,
            fps=args.fps,
            budget_ms=args.budget_ms,
            window=args.window,
            tier=args.tier,
            allow_downgrade=args.allow_downgrade,
            window_sec=args.window_sec,
            per_worker=args.per_worker,
            reuse_threshold=args.reuse_threshold,
            max_reuse_run=args.max_reuse_run,
            reuse_warp=args.reuse_warp,
        )
        print(json.dumps(report))
        return 0
    report = run_load(
        args.url,
        payloads,
        concurrency=args.concurrency,
        total=args.requests,
        deadline_ms=args.deadline_ms,
        tier=args.tier,
        allow_downgrade=args.allow_downgrade,
        window_sec=args.window_sec,
        collect_ledger=args.ledger is not None,
        per_worker=args.per_worker,
        arrival_rate=args.arrival_rate,
        rate_ramp=(
            parse_rate_ramp(args.rate_ramp)
            if args.rate_ramp is not None else None
        ),
        arrival_seed=args.arrival_seed,
    )
    if args.ledger is not None:
        from pathlib import Path

        Path(args.ledger).write_text(
            json.dumps({"ledger": report.pop("ledger", [])})
        )
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
