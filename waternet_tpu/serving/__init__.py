"""Serving layer (L4.5): throughput-oriented inference over arbitrary
request streams — shape bucketing, dynamic micro-batching, AOT warmup,
and serving observability. See docs/SERVING.md.
"""

from waternet_tpu.serving.batcher import (
    DynamicBatcher,
    ExactShapeBatcher,
    resolve_ladder,
)
from waternet_tpu.serving.bucketing import (
    RECEPTIVE_RADIUS,
    BucketLadder,
    derive_buckets,
    pad_to_bucket,
    padding_overhead,
    parse_buckets,
    scan_shapes,
)
from waternet_tpu.serving.stats import ServingStats
from waternet_tpu.serving.warmup import warmup

__all__ = [
    "RECEPTIVE_RADIUS",
    "BucketLadder",
    "DynamicBatcher",
    "ExactShapeBatcher",
    "ServingStats",
    "derive_buckets",
    "pad_to_bucket",
    "padding_overhead",
    "parse_buckets",
    "resolve_ladder",
    "scan_shapes",
    "warmup",
]
