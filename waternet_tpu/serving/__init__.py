"""Serving layer (L4.5): throughput-oriented inference over arbitrary
request streams — shape bucketing, dynamic micro-batching, a multi-device
replica pool, AOT warmup, and serving observability. See docs/SERVING.md.
"""

from waternet_tpu.serving.batcher import (
    DynamicBatcher,
    ExactShapeBatcher,
    fit_ladder_to_engine,
    resolve_ladder,
)
from waternet_tpu.serving.replicas import (
    ReplicaPool,
    engine_jit_cache_size,
    resolve_replicas,
)
from waternet_tpu.serving.bucketing import (
    RECEPTIVE_RADIUS,
    BucketLadder,
    derive_buckets,
    pad_to_bucket,
    padding_overhead,
    parse_buckets,
    scan_shapes,
)
from waternet_tpu.serving.stats import ServingStats
from waternet_tpu.serving.warmup import warmup

__all__ = [
    "RECEPTIVE_RADIUS",
    "BucketLadder",
    "DynamicBatcher",
    "ExactShapeBatcher",
    "ReplicaPool",
    "ServingStats",
    "derive_buckets",
    "engine_jit_cache_size",
    "fit_ladder_to_engine",
    "pad_to_bucket",
    "padding_overhead",
    "parse_buckets",
    "resolve_ladder",
    "resolve_replicas",
    "scan_shapes",
    "warmup",
]
