"""Serving layer (L4.5): throughput-oriented inference over arbitrary
request streams — shape bucketing, dynamic micro-batching, a multi-device
replica pool, AOT warmup, serving observability, and the HTTP front door
(``waternet_tpu.serving.server`` — imported explicitly, not re-exported,
so library users of the batcher never touch the gateway stack). See
docs/SERVING.md.
"""

from waternet_tpu.serving.batcher import (
    DeadlineExpired,
    DynamicBatcher,
    ExactShapeBatcher,
    QueueFull,
    RequestCancelled,
    UnknownTier,
    fit_ladder_to_engine,
    resolve_ladder,
)
from waternet_tpu.serving.replicas import (
    ReplicaPool,
    ReplicaUnavailable,
    SupervisionConfig,
    engine_jit_cache_size,
    resolve_replicas,
)
from waternet_tpu.serving.bucketing import (
    RECEPTIVE_RADIUS,
    BucketLadder,
    derive_buckets,
    pad_to_bucket,
    padding_overhead,
    parse_buckets,
    scan_shapes,
)
from waternet_tpu.serving.stats import ServingStats
from waternet_tpu.serving.warmup import warmup

__all__ = [
    "RECEPTIVE_RADIUS",
    "BucketLadder",
    "DeadlineExpired",
    "DynamicBatcher",
    "ExactShapeBatcher",
    "QueueFull",
    "ReplicaPool",
    "ReplicaUnavailable",
    "RequestCancelled",
    "ServingStats",
    "SupervisionConfig",
    "UnknownTier",
    "derive_buckets",
    "engine_jit_cache_size",
    "fit_ladder_to_engine",
    "pad_to_bucket",
    "padding_overhead",
    "parse_buckets",
    "resolve_ladder",
    "resolve_replicas",
    "scan_shapes",
    "warmup",
]
