"""Fleet front tier: a supervising router over N ``waternet-serve`` workers.

One ``waternet-serve`` process per host cannot carry the ROADMAP's
"millions of users" — and a crashed or wedged gateway must not be a
client-visible event. ``waternet-fleet`` composes the pieces built by
earlier PRs into a front tier (docs/SERVING.md "Fleet"):

* **Supervision** — the router spawns N serving workers on ephemeral
  ports and drives :class:`waternet_tpu.resilience.heartbeat.WorkerHealth`
  (``live_phase="serve"``) per worker off file heartbeats plus
  ``/healthz`` polls, exactly as resilience/supervisor.py does for train
  gangs. Crashed or hung workers are drained (SIGTERM), SIGKILLed past
  the grace window, and relaunched as fresh generations on new ports.
* **Routing** — ``/enhance`` goes to the least-loaded ready worker,
  skipping workers whose queue gauge projects past the request's
  ``X-Deadline-Ms`` budget; ``/stream`` sessions pin to a worker by
  consistent hashing on the session id (:class:`HashRing`), so a
  membership change remaps ONLY the dead worker's arc and every other
  pinned session stays put.
* **Failover** — a request in flight on a worker that dies mid-answer is
  transparently re-dispatched to another ready worker (bounded by
  ``route_retries``), with ``X-Request-Id`` preserved across the hop;
  responses are byte-identical by replica invariance (the workers run
  the same weights through the same compiled buckets). Worker verdicts
  (429/503/504) relay verbatim — ``Retry-After`` and ``X-Request-Id``
  pass through untouched, they are answers, not failures.
* **SLO closed loop** — the router feeds its own sliding windows of
  relayed outcomes to a :class:`waternet_tpu.obs.slo.SloEngine`;
  sustained ``page`` burn triggers a worker scale-up (to
  ``--max-workers``) plus a fleet-wide brown-out (every worker's
  downgrade watermark lowered via ``POST /admin/policy``), and sustained
  ``ok`` scales back down and restores the baseline policy. Every
  transition is logged with its triggering objective and surfaced on the
  router's ``/stats``, ``/healthz`` (per-worker health map), and
  ``/metrics``.
* **Forecast-driven autoscaling** — when the armed SLO has a latency
  objective, a :class:`waternet_tpu.serving.adaptive.QueueForecaster`
  tracks aggregate worker queue depth each control tick and scales the
  fleet up on a *predicted* objective breach — BEFORE the burn-rate
  engine pages, so capacity lands ahead of the brown-out rung — and
  down on a sustained low forecast. Forecast actions share the burn
  loop's scale cooldown (one scaler, two triggers) and never touch the
  brown-out policy; they log as ``forecast_scale_up`` /
  ``forecast_scale_down``.
* **Copy-lean relay** — ``/enhance`` worker answers stream through the
  router in 64 KiB chunks once the response head has parsed, instead of
  being rebuffered whole; the full body is tee-accumulated only when
  the router response cache will store it. A worker that dies before
  the head commits still re-dispatches exactly as before.

The router itself is stdlib-only — hand-rolled asyncio HTTP, no model,
no jax — so it stays cheap to run next to the workers and trivially
testable with stub workers (tests/test_fleet.py). Fault kinds
``gateway_crash@K`` / ``gateway_hang@K`` (resilience/faults.py) drill
the failover deterministically, and ``bench.py --config serve_fleet``
pins the chaos contract.
"""

from __future__ import annotations

import argparse
import asyncio
import bisect
import hashlib
import http.client
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from waternet_tpu.data.pipeline import THREAD_PREFIX
from waternet_tpu.obs import trace
from waternet_tpu.obs import window as obswin
from waternet_tpu.obs.slo import SloEngine, WindowSample, parse_slo
from waternet_tpu.serving.adaptive import (
    QueueForecaster,
    empty_forecast_block,
)
from waternet_tpu.resilience.heartbeat import (
    ENV_HEARTBEAT_DIR,
    ENV_HEARTBEAT_SEC,
    ENV_WORKER_GENERATION,
    ENV_WORKER_ID,
    ENV_WORKER_SLOT,
    HeartbeatWriter,  # noqa: F401  (re-exported for worker-side users)
    WorkerHealth,
    heartbeat_path,
    read_heartbeat,
)
from waternet_tpu.serving.reuse import ResponseCache, empty_cache_block

__all__ = [
    "FleetPolicy",
    "FleetRouter",
    "HashRing",
    "worker_id",
    "main",
]

MAX_BODY_BYTES = 64 << 20

_REASONS = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 413: "Payload Too Large",
    429: "Too Many Requests", 500: "Internal Server Error",
    503: "Service Unavailable", 504: "Gateway Timeout",
}

#: Response headers relayed verbatim from worker answers — the backoff
#: hint (Retry-After), the correlation id, and the serving facts a
#: client ledger splits on must all survive the extra hop.
_RELAY_HEADERS = (
    "content-type", "retry-after", "x-request-id", "x-tier-served",
    "x-worker-id", "x-cache",
)

#: Request headers forwarded to the chosen worker (everything the
#: serving contract reads; hop-by-hop headers are rebuilt, not copied).
_FORWARD_HEADERS = (
    "content-type", "x-request-id", "x-tier", "x-tier-allow-downgrade",
    "x-deadline-ms", "x-stream-window", "x-stream-fps",
    "x-stream-reuse", "x-stream-max-reuse-run", "x-stream-reuse-warp",
)


def worker_id(slot: int, generation: int) -> str:
    """The opaque id a worker stamps as ``X-Worker-Id``: slot identity
    plus restart generation, so a relaunched worker is distinguishable
    in client ledgers from the generation it replaced."""
    return f"w{int(slot)}g{int(generation)}"


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _request_id(headers: dict) -> str:
    """Same contract as the worker front door: accept a sane client
    ``X-Request-Id`` token, replace anything that could smuggle CRLF."""
    raw = headers.get("x-request-id", "").strip()
    if (
        raw
        and len(raw) <= 128
        and all(c.isalnum() or c in "-_.:/" for c in raw)
    ):
        return raw
    return trace.new_request_id()


def _content_length(headers: dict) -> int:
    try:
        return max(0, int(headers.get("content-length", "0")))
    except ValueError:
        return 0


def backoff_sec(base: float, cap: float, restart_index: int) -> float:
    """Exponential relaunch backoff, same shape as the train supervisor's
    (a serving slot that dies at boot must not busy-loop Popen)."""
    if base <= 0:
        return 0.0
    return min(cap, base * (2.0 ** max(0, restart_index - 1)))


# ----------------------------------------------------------------------
# Consistent hashing
# ----------------------------------------------------------------------


class HashRing:
    """Deterministic consistent-hash ring over worker slots.

    Each member slot owns ``vnodes`` points on a 2^64 ring, placed by
    sha256 of ``"slot:vnode"`` — fully deterministic, no process seed,
    so the session→slot mapping is reproducible across router restarts
    and pinned in tests. Removing a slot deletes only its points:
    sessions hashing into the removed arcs fall to the next point
    clockwise, and every other session's mapping is untouched (the
    single-arc-remap property tests/test_fleet.py asserts).

    Not self-locked: the router owns membership under its own lock.
    """

    def __init__(self, vnodes: int = 64):
        if vnodes < 1:
            raise ValueError("vnodes must be >= 1")
        self.vnodes = int(vnodes)
        self._points: List[int] = []  # sorted ring positions
        self._owner: Dict[int, int] = {}  # point -> slot
        self._members: Dict[int, List[int]] = {}  # slot -> its points

    @staticmethod
    def _hash(key: str) -> int:
        return int.from_bytes(
            hashlib.sha256(key.encode("utf-8")).digest()[:8], "big"
        )

    def add(self, slot: int) -> None:
        if slot in self._members:
            return
        points = []
        for v in range(self.vnodes):
            p = self._hash(f"{int(slot)}:{v}")
            # sha256 collisions across distinct keys are not a practical
            # concern; first owner keeps a contested point so add/remove
            # stays an exact inverse.
            if p in self._owner:
                continue
            self._owner[p] = slot
            bisect.insort(self._points, p)
            points.append(p)
        self._members[slot] = points

    def remove(self, slot: int) -> None:
        for p in self._members.pop(slot, ()):
            del self._owner[p]
            i = bisect.bisect_left(self._points, p)
            del self._points[i]

    def members(self) -> List[int]:
        return sorted(self._members)

    def lookup(self, key: str) -> Optional[int]:
        """The slot owning ``key``, or None on an empty ring."""
        if not self._points:
            return None
        h = self._hash(key)
        i = bisect.bisect_right(self._points, h)
        if i == len(self._points):
            i = 0  # wrap: past the last point means the first owner
        return self._owner[self._points[i]]


# ----------------------------------------------------------------------
# Scale / brown-out policy
# ----------------------------------------------------------------------


class FleetPolicy:
    """Pure scale + brown-out decision engine over the SLO alert state.

    The *sustained* part lives in the SLO engine (multi-window burn
    rates escalate, ``hold_sec`` gates de-escalation), so this class
    only maps alert state to fleet actions, with a scale cooldown as the
    anti-flap term. Pure — ``step(now, ...)`` takes explicit time — so
    every decision is unit-testable without processes or sleeps.
    """

    def __init__(
        self,
        min_workers: int,
        max_workers: int,
        cooldown_sec: float = 30.0,
    ):
        if not 1 <= min_workers <= max_workers:
            raise ValueError(
                f"need 1 <= min_workers ({min_workers}) <= max_workers "
                f"({max_workers})"
            )
        self.min_workers = int(min_workers)
        self.max_workers = int(max_workers)
        self.cooldown_sec = float(cooldown_sec)
        self.brownout = False
        self._last_scale: Optional[float] = None

    def _cooled(self, now: float) -> bool:
        return (
            self._last_scale is None
            or now - self._last_scale >= self.cooldown_sec
        )

    # The forecast path scales through the SAME cooldown ledger: a burn
    # scale and a forecast scale are one fleet-level actuator, so one
    # anti-flap term must gate both.
    def cooled(self, now: float) -> bool:
        """True when the scale cooldown allows another action at ``now``."""
        return self._cooled(now)

    def note_scale(self, now: float) -> None:
        """Record an external (forecast-driven) scale action so the
        cooldown applies to the next decision from either trigger."""
        self._last_scale = now

    def step(self, now: float, slo_state: str, n_workers: int) -> List[str]:
        """Actions for one control tick: any of ``brownout`` /
        ``restore`` / ``scale_up`` / ``scale_down``, in apply order.
        Brown-out tracks the paging edge exactly; scaling additionally
        respects the cooldown and the worker bounds."""
        actions: List[str] = []
        if slo_state == "page":
            if not self.brownout:
                self.brownout = True
                actions.append("brownout")
            if n_workers < self.max_workers and self._cooled(now):
                self._last_scale = now
                actions.append("scale_up")
        elif slo_state == "ok":
            if self.brownout:
                self.brownout = False
                actions.append("restore")
            if n_workers > self.min_workers and self._cooled(now):
                self._last_scale = now
                actions.append("scale_down")
        # "warn" holds position: neither direction is justified yet.
        return actions


# ----------------------------------------------------------------------
# Router-side windows
# ----------------------------------------------------------------------


class RouterWindows:
    """Sliding windows over RELAYED outcomes — the fleet-level aggregate
    the SLO engine grades (a client cares about the answer it got, not
    which worker produced it). Same primitives as the worker's own
    windows (obs/window.py), same injectable clock, so tests drive burn
    rates without sleeping."""

    def __init__(self, clock=None):
        self._clock = clock if clock is not None else time.monotonic
        self.latency = obswin.WindowedHistogram(clock=self._clock)
        self.ok = obswin.WindowedCounter(clock=self._clock)
        self.errors = obswin.WindowedCounter(clock=self._clock)
        self.shed = obswin.WindowedCounter(clock=self._clock)

    def observe(self, status: int, latency_ms: float) -> None:
        self.latency.record(latency_ms)
        if status < 400:
            self.ok.add()
        elif status == 429:
            self.shed.add()
        else:
            self.errors.add()

    def sample(self, span_sec: float) -> WindowSample:
        return WindowSample(
            self.latency.merged(span_sec),
            ok=self.ok.total(span_sec),
            errors=self.errors.total(span_sec),
            shed=self.shed.total(span_sec),
        )

    def block(self, span_sec: float = obswin.DEFAULT_WINDOW_SEC) -> dict:
        hist = self.latency.merged(span_sec)
        return {
            "span_sec": span_sec,
            "ok": self.ok.total(span_sec),
            "errors": self.errors.total(span_sec),
            "shed": self.shed.total(span_sec),
            "latency_ms": {
                "count": hist.count,
                "p50": round(hist.quantile(0.50), 3),
                "p90": round(hist.quantile(0.90), 3),
                "p99": round(hist.quantile(0.99), 3),
            },
        }


# ----------------------------------------------------------------------
# One supervised worker
# ----------------------------------------------------------------------


class FleetWorker:
    """Router-side record of one serving worker process (slot +
    generation). Process lifecycle and health are owned by the monitor
    thread; the routing fields (``ready``/``failed``/``inflight``/
    gauges) are shared with the event loop under the router's lock."""

    def __init__(
        self,
        slot: int,
        generation: int,
        port: int,
        proc: "subprocess.Popen",
        health: WorkerHealth,
        hb_file: Path,
    ):
        self.slot = int(slot)
        self.generation = int(generation)
        self.worker_id = worker_id(slot, generation)
        self.port = int(port)
        self.proc = proc
        self.health = health
        self.hb_file = Path(hb_file)
        self.ready = False
        self.failed = False
        self.retiring = False
        self.inflight = 0
        self.queue_depth = 0
        self.latency_p50_ms: Optional[float] = None
        self.replicas = 1
        self.last_stats: Optional[dict] = None
        self.baseline_downgrade: Optional[int] = None
        self.kill_deadline: Optional[float] = None
        self.down_event: Optional[asyncio.Event] = None
        self._last_http_poll = 0.0

    def est_ms(self) -> float:
        """Projected time-to-answer from the last polled gauges: the
        backlog ahead of a new arrival, spread over the worker's
        replicas, at its windowed median latency. Zero (never skip)
        until the worker has served enough to have a median."""
        if not self.latency_p50_ms:
            return 0.0
        waiting = self.queue_depth + self.inflight
        return (waiting / max(1, self.replicas) + 1) * self.latency_p50_ms

    def summary(self) -> dict:
        return {
            "slot": self.slot,
            "generation": self.generation,
            "port": self.port,
            "state": self.health.state,
            "ready": self.ready,
            "inflight": self.inflight,
            "queue_depth": self.queue_depth,
        }


class _ClientSink:
    """Client-side target for the copy-lean /enhance relay.

    Once a worker response head parses, ``begin`` commits the head to
    the client and body chunks are pumped straight through — the router
    never rebuffers the whole answer. ``tee`` collects the chunks ONLY
    when the head callback decided the router cache will store the body;
    ``committed`` tells the dispatch loop a redispatch is no longer
    possible (bytes are on the wire). Event-loop-only state: no lock.
    """

    def __init__(self, writer, head_fn):
        self.writer = writer
        self._head_fn = head_fn
        self.committed = False
        self.tee: Optional[List[bytes]] = None

    def begin(self, status: int, ctype: str, relay, length: int) -> None:
        self.committed = True
        self.tee = self._head_fn(status, ctype, relay, length)


# ----------------------------------------------------------------------
# The router
# ----------------------------------------------------------------------


class FleetRouter:
    """Front router + worker supervisor + SLO control loop.

    Threading model (threadlint-audited): the asyncio event loop (one
    thread) owns client connections and request relays; ONE monitor
    thread owns worker processes, health, and the control loop; the two
    share the worker table and counters under ``self._lock``, with no
    blocking call ever made while holding it. Worker HTTP polls and
    policy pushes happen on the monitor thread between lock sections.
    """

    def __init__(
        self,
        worker_cmd: List[str],
        n_workers: int = 2,
        max_workers: Optional[int] = None,
        host: str = "127.0.0.1",
        port: int = 0,
        late_sec: float = 3.0,
        hang_sec: float = 6.0,
        startup_grace_sec: float = 180.0,
        drain_grace_sec: float = 10.0,
        poll_sec: float = 0.25,
        health_poll_sec: float = 0.5,
        heartbeat_sec: float = 0.5,
        route_retries: int = 2,
        proxy_timeout_sec: float = 120.0,
        grace_sec: float = 30.0,
        slo: Optional[str] = None,
        slo_short_sec: float = obswin.DEFAULT_WINDOW_SEC,
        slo_long_sec: float = obswin.DEFAULT_LONG_WINDOW_SEC,
        slo_hold_sec: float = 60.0,
        scale_cooldown_sec: float = 30.0,
        forecast: bool = True,
        forecast_horizon_sec: float = 30.0,
        forecast_up_sustain: int = 2,
        forecast_down_sustain: int = 6,
        brownout_watermark: int = 1,
        heartbeat_root=None,
        worker_faults: Optional[Dict[Tuple[int, int], str]] = None,
        worker_env: Optional[Dict[str, str]] = None,
        max_restarts: int = 5,
        backoff_base_sec: float = 0.25,
        backoff_cap_sec: float = 5.0,
        ring_vnodes: int = 64,
        response_cache: int = 0,
        clock=None,
    ):
        if n_workers < 1:
            raise ValueError("n_workers must be >= 1")
        self.worker_cmd = list(worker_cmd)
        self.n_workers = int(n_workers)
        self.max_workers = int(
            max_workers if max_workers is not None else n_workers
        )
        self.host = host
        self.port = int(port)
        self.late_sec = float(late_sec)
        self.hang_sec = float(hang_sec)
        self.startup_grace_sec = float(startup_grace_sec)
        self.drain_grace_sec = float(drain_grace_sec)
        self.poll_sec = float(poll_sec)
        self.health_poll_sec = float(health_poll_sec)
        self.heartbeat_sec = float(heartbeat_sec)
        self.route_retries = int(route_retries)
        self.proxy_timeout_sec = float(proxy_timeout_sec)
        self.grace_sec = float(grace_sec)
        self.brownout_watermark = int(brownout_watermark)
        self.worker_faults = dict(worker_faults or {})
        self.worker_env = dict(worker_env or {})
        self.max_restarts = int(max_restarts)
        self.backoff_base_sec = float(backoff_base_sec)
        self.backoff_cap_sec = float(backoff_cap_sec)
        # Control-plane clock (windows, SLO, policy cooldown) is
        # injectable so tests drive burn rates deterministically; health
        # freshness always uses wall time — heartbeat records carry
        # time.time() stamped by another process.
        self._control_clock = clock if clock is not None else time.monotonic
        self._windows = RouterWindows(clock=self._control_clock)
        self.slo_spec = slo
        self.slo_short_sec = float(slo_short_sec)
        self.slo_long_sec = float(slo_long_sec)
        self._slo = (
            SloEngine(
                parse_slo(slo), spec=slo,
                short_sec=slo_short_sec, long_sec=slo_long_sec,
                hold_sec=slo_hold_sec,
            )
            if slo
            else None
        )
        self._policy = FleetPolicy(
            self.n_workers, self.max_workers, cooldown_sec=scale_cooldown_sec
        )
        # Queue-depth forecaster: armed only when the SLO carries a
        # latency objective — its threshold IS the drain-time budget the
        # Little's-law breach depth is computed against. The burn engine
        # stays authoritative for paging and brown-out; the forecaster
        # only moves capacity earlier (monitor thread is the sole
        # caller, so the forecaster needs no lock).
        lat_ms = None
        if forecast and self._slo is not None:
            lats = [
                o.threshold for o in self._slo.objectives
                if o.kind == "latency"
            ]
            lat_ms = min(lats) if lats else None
        self._forecaster = (
            QueueForecaster(
                lat_ms,
                horizon_sec=forecast_horizon_sec,
                up_sustain=forecast_up_sustain,
                down_sustain=forecast_down_sustain,
            )
            if lat_ms is not None
            else None
        )
        # Router-level content-addressed /enhance cache. Keys include a
        # ladder identity of "fleet" rather than the bucket ladder (the
        # router never sees it); invalidated when /admin/reload is
        # broadcast through this front door. Only answers served at the
        # exact requested tier are stored, so a brown-out downgrade can
        # never be replayed to a non-opt-in client.
        self.response_cache = (
            ResponseCache(int(response_cache), ladder_id="fleet")
            if response_cache
            else None
        )
        self._hb_root = Path(
            heartbeat_root
            if heartbeat_root is not None
            else tempfile.mkdtemp(prefix="waternet-fleet-hb-")
        )

        self._lock = threading.Lock()
        self._workers: Dict[int, FleetWorker] = {}  # guarded-by: self._lock
        self._ring = HashRing(ring_vnodes)  # guarded-by: self._lock
        self._events: List[dict] = []  # guarded-by: self._lock
        self._worker_ledger: Dict[str, Dict[str, int]] = {}  # guarded-by: self._lock
        self._routed = {"enhance": 0, "stream": 0}  # guarded-by: self._lock
        self._redispatches = 0  # guarded-by: self._lock
        self._restarts = 0  # guarded-by: self._lock
        self._slot_restarts: Dict[int, int] = {}  # guarded-by: self._lock
        self._pending_spawn: Dict[int, Tuple[int, float]] = {}  # guarded-by: self._lock
        self._fail_at: Dict[int, float] = {}  # guarded-by: self._lock
        self._recovery_last: Optional[float] = None  # guarded-by: self._lock
        self._recovery_max = 0.0  # guarded-by: self._lock
        self._brownout = False  # guarded-by: self._lock
        self._slo_block: Optional[dict] = None  # guarded-by: self._lock
        self._next_slot = self.n_workers  # guarded-by: self._lock
        self._inflight = 0  # guarded-by: self._lock

        self.bound_port: Optional[int] = None
        self.draining = threading.Event()
        self._bound = threading.Event()
        self._drain_flag = False
        self._stop_monitor = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._thread: Optional[threading.Thread] = None
        self._exit_code: Optional[int] = None
        self._error: Optional[BaseException] = None

    # -- lifecycle -----------------------------------------------------

    def run(self, install_signal_handlers: bool = True) -> int:
        return asyncio.run(self._main(install_signal_handlers))

    def start_background(self, timeout: float = 30.0) -> "FleetRouter":
        def _target():
            try:
                self._exit_code = self.run(install_signal_handlers=False)
            except BaseException as err:  # surfaced by wait_ready/join
                self._error = err
                self._exit_code = 1
                self._bound.set()

        self._thread = threading.Thread(
            target=_target, name=f"{THREAD_PREFIX}-fleet-http", daemon=True
        )
        self._thread.start()
        if not self._bound.wait(timeout):
            raise RuntimeError("fleet router did not bind within the timeout")
        if self._error is not None:
            raise RuntimeError("fleet router failed to start") from self._error
        return self

    def wait_ready(
        self, timeout: float = 120.0, min_ready: Optional[int] = None
    ) -> None:
        """Block until ``min_ready`` workers (default: all initially
        requested) report ready on /healthz."""
        need = self.n_workers if min_ready is None else int(min_ready)
        deadline = time.monotonic() + timeout
        while True:
            if self._error is not None:
                raise RuntimeError(
                    "fleet router died during warmup"
                ) from self._error
            with self._lock:
                ready = sum(1 for w in self._workers.values() if w.ready)
            if ready >= need:
                return
            if time.monotonic() > deadline:
                raise RuntimeError(
                    f"only {ready}/{need} workers ready in time"
                )
            time.sleep(0.05)

    def request_drain(self) -> None:
        self._drain_flag = True

    def join(self, timeout: float = 120.0) -> int:
        assert self._thread is not None, "router was not started in background"
        self._thread.join(timeout)
        if self._thread.is_alive():
            raise RuntimeError("fleet router did not exit within the timeout")
        return int(self._exit_code)

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.bound_port}"

    # -- worker process management (monitor thread) --------------------

    def _worker_env(self, slot: int, generation: int, gen_dir: Path) -> dict:
        env = dict(os.environ)
        # Caller overlay first (e.g. the fleet bench forcing workers onto
        # the host platform): the supervisor contract keys below always
        # win — a worker whose heartbeat env was overridden would be
        # undetectable-by-design.
        env.update(self.worker_env)
        env[ENV_HEARTBEAT_DIR] = str(gen_dir)
        env[ENV_HEARTBEAT_SEC] = str(self.heartbeat_sec)
        env[ENV_WORKER_SLOT] = str(slot)
        env[ENV_WORKER_GENERATION] = str(generation)
        env[ENV_WORKER_ID] = worker_id(slot, generation)
        spec = self.worker_faults.get((slot, generation))
        if spec:
            # Deterministic fault targeting, supervisor-style: exactly
            # the named (slot, generation) gets a plan; everyone else
            # must NOT inherit one from the router's own environment.
            env["WATERNET_FAULTS"] = spec
        else:
            env.pop("WATERNET_FAULTS", None)
        return env

    def _spawn_worker(self, slot: int, generation: int) -> FleetWorker:
        port = _free_port()
        gen_dir = self._hb_root / f"slot-{slot:02d}" / f"gen-{generation:03d}"
        cmd = list(self.worker_cmd) + [
            "--host", "127.0.0.1", "--port", str(port),
        ]
        proc = subprocess.Popen(
            cmd, env=self._worker_env(slot, generation, gen_dir)
        )
        health = WorkerHealth(
            late_sec=self.late_sec,
            hang_sec=self.hang_sec,
            startup_grace_sec=self.startup_grace_sec,
            started_at=time.time(),
            live_phase="serve",
        )
        w = FleetWorker(
            slot, generation, port, proc, health,
            heartbeat_path(gen_dir, slot),
        )
        with self._lock:
            self._workers[slot] = w
            self._worker_ledger.setdefault(
                w.worker_id,
                {"ok": 0, "errors": 0, "shed": 0, "deadline_expired": 0,
                 "streams": 0},
            )
        print(
            f"waternet-fleet: spawned worker {w.worker_id} "
            f"(slot {slot} gen {generation}, pid {proc.pid}, port {port})",
            flush=True,
        )
        return w

    def _log_event(self, now: float, **fields) -> None:
        event = {"at": round(now, 3), **fields}
        with self._lock:
            self._events.append(event)
        print(f"waternet-fleet: {json.dumps(event)}", flush=True)

    def _set_down_event(self, w: FleetWorker) -> None:
        ev, loop = w.down_event, self._loop
        if ev is not None and loop is not None:
            try:
                loop.call_soon_threadsafe(ev.set)
            except RuntimeError:
                pass  # loop already closed (shutdown race)

    def _fail_worker(self, w: FleetWorker, now: float, reason: str) -> None:
        """Declare one worker failed: stop routing to it immediately
        (its ring arc remaps, in-flight relays abort and re-dispatch),
        then drain/SIGKILL on the monitor's schedule."""
        with self._lock:
            w.failed = True
            w.ready = False
            self._ring.remove(w.slot)
            self._fail_at.setdefault(w.slot, now)
        self._set_down_event(w)
        self._log_event(
            now, event="worker_failed", worker=w.worker_id,
            reason=reason, state=w.health.state,
        )
        if w.proc.poll() is None:
            # Drain first (SIGTERM = the worker's own graceful path);
            # the monitor SIGKILLs past the grace window. A wedged event
            # loop never acts on SIGTERM — that is what the window is for.
            try:
                w.proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            w.kill_deadline = now + self.drain_grace_sec

    def _reap_and_relaunch(self, w: FleetWorker, now: float) -> None:
        """Once a failed worker's process is gone, schedule the slot's
        next generation (with backoff; budget-bounded)."""
        with self._lock:
            restarts = self._slot_restarts.get(w.slot, 0) + 1
            self._slot_restarts[w.slot] = restarts
            self._restarts += 1
            if restarts > self.max_restarts:
                del self._workers[w.slot]
                abandoned = True
            else:
                delay = backoff_sec(
                    self.backoff_base_sec, self.backoff_cap_sec, restarts
                )
                self._pending_spawn[w.slot] = (w.generation + 1, now + delay)
                del self._workers[w.slot]
                abandoned = False
        if abandoned:
            self._log_event(
                now, event="slot_abandoned", slot=w.slot,
                restarts=restarts,
            )
        else:
            self._log_event(
                now, event="worker_relaunching", slot=w.slot,
                generation=w.generation + 1,
            )

    def _http_json(
        self, port: int, method: str, path: str, payload=None,
        timeout: float = 1.0,
    ) -> Tuple[Optional[int], Optional[dict]]:
        """Blocking worker-control HTTP from the monitor thread. A hung
        worker times out — never call this holding the lock."""
        body = b"" if payload is None else json.dumps(payload).encode()
        conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
        try:
            conn.request(
                method, path, body=body,
                headers={"Content-Type": "application/json"},
            )
            resp = conn.getresponse()
            return resp.status, json.loads(resp.read() or b"{}")
        except (OSError, ValueError, http.client.HTTPException):
            return None, None
        finally:
            conn.close()

    def _apply_policy(self, w: FleetWorker, watermark) -> None:
        self._http_json(
            w.port, "POST", "/admin/policy",
            {"downgrade_watermark": watermark},
        )

    def _note_ready(self, w: FleetWorker, now: float) -> None:
        # Baseline policy captured BEFORE the worker joins the ring, so
        # a brown-out restore always has a value to restore to.
        _, policy = self._http_json(w.port, "POST", "/admin/policy", {})
        if policy:
            w.baseline_downgrade = policy.get("policy", {}).get(
                "downgrade_watermark"
            )
        with self._lock:
            brownout = self._brownout
        if brownout:
            self._apply_policy(w, self.brownout_watermark)
        recovery = None
        with self._lock:
            w.ready = True
            self._ring.add(w.slot)
            fail_t = self._fail_at.pop(w.slot, None)
            if fail_t is not None:
                recovery = now - fail_t
                self._recovery_last = recovery
                self._recovery_max = max(self._recovery_max, recovery)
        event = {"event": "worker_ready", "worker": w.worker_id}
        if recovery is not None:
            event["recovery_sec"] = round(recovery, 3)
        self._log_event(now, **event)

    def _poll_worker_http(self, w: FleetWorker, now: float) -> None:
        if now - w._last_http_poll < self.health_poll_sec:
            return
        w._last_http_poll = now
        timeout = max(0.2, min(1.0, self.hang_sec / 2))
        status, health = self._http_json(
            w.port, "GET", "/healthz", timeout=timeout
        )
        if not w.ready and status is not None and health is not None:
            if health.get("ready"):
                self._note_ready(w, now)
        status, stats = self._http_json(
            w.port, "GET", "/stats", timeout=timeout
        )
        if status == 200 and stats is not None:
            lat = stats.get("latency_ms_window") or stats.get("latency_ms")
            with self._lock:
                w.last_stats = stats
                w.queue_depth = int(stats.get("queue_depth", 0))
                w.replicas = int(stats.get("replicas", 1))
                if isinstance(lat, dict) and lat.get("p50"):
                    w.latency_p50_ms = float(lat["p50"])

    def _supervise_tick(self, now: float) -> None:
        with self._lock:
            workers = list(self._workers.values())
            pending = dict(self._pending_spawn)
        # Deferred relaunches whose backoff expired.
        for slot, (generation, t_spawn) in pending.items():
            if now >= t_spawn:
                with self._lock:
                    self._pending_spawn.pop(slot, None)
                self._spawn_worker(slot, generation)
        for w in workers:
            rc = w.proc.poll()
            if w.retiring:
                # Scale-down drain: reap on exit, SIGKILL past grace.
                if rc is not None:
                    with self._lock:
                        self._workers.pop(w.slot, None)
                    self._log_event(
                        now, event="worker_retired", worker=w.worker_id,
                        exit_code=rc,
                    )
                elif w.kill_deadline is not None and now >= w.kill_deadline:
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
                continue
            if w.failed:
                if rc is not None:
                    self._reap_and_relaunch(w, now)
                elif w.kill_deadline is not None and now >= w.kill_deadline:
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
                    w.kill_deadline = now + self.drain_grace_sec
                continue
            record = read_heartbeat(w.hb_file)
            if record is not None and record.get("generation") == w.generation:
                w.health.note_beat(record)
            state = w.health.observe(time.time(), exit_code=rc)
            if w.health.failed:
                self._fail_worker(
                    w, now,
                    reason="exit" if state == "dead" else "heartbeat",
                )
                continue
            self._poll_worker_http(w, now)
        self._control_tick(now)

    def _monitor_loop(self) -> None:
        # Initial fleet: spawned from the monitor thread so ALL process
        # lifecycle lives on one thread (supervisor.py's discipline).
        for slot in range(self.n_workers):
            self._spawn_worker(slot, 0)
        while not self._stop_monitor.wait(self.poll_sec):
            self._supervise_tick(self._control_clock())

    # -- SLO control loop ----------------------------------------------

    def _paging_objective(self, slo_block: dict) -> Optional[str]:
        for row in slo_block.get("objectives", ()):
            if row.get("state") == "page":
                return row.get("objective")
        return None

    def _control_tick(self, now: float) -> None:
        """One closed-loop evaluation: windows -> SLO -> policy ->
        actions. Called by the monitor each tick; tests call it directly
        with a fake clock (no sleeps, deterministic transitions)."""
        if self._slo is None:
            return
        short = self._windows.sample(self.slo_short_sec)
        long = self._windows.sample(self.slo_long_sec)
        block = self._slo.evaluate(now, short, long)
        with self._lock:
            self._slo_block = block
            n_live = len(
                [w for w in self._workers.values()
                 if not w.failed and not w.retiring]
            ) + len(self._pending_spawn)
        for tr in block["transitions"]:
            self._log_event(
                now, event="slo_transition", objective=tr["objective"],
                **{"from": tr["from"], "to": tr["to"]},
            )
        objective = self._paging_objective(block) or block["state"]
        actions = self._policy.step(now, block["state"], n_live)
        for action in actions:
            if action == "brownout":
                self._apply_brownout(now, objective)
            elif action == "restore":
                self._apply_restore(now, objective)
            elif action == "scale_up":
                self._apply_scale_up(now, objective, n_live)
            elif action == "scale_down":
                self._apply_scale_down(now, objective, n_live)
        self._forecast_tick(now, block, n_live, actions)

    def _forecast_tick(
        self, now: float, block: dict, n_live: int, burn_actions: List[str]
    ) -> None:
        """Predictive half of the control loop: aggregate polled queue
        depth -> forecaster -> early scale hint. Runs AFTER the burn
        policy so a paging fleet is already handled; forecast actions
        share the policy's cooldown and never touch brown-out."""
        if self._forecaster is None:
            return
        with self._lock:
            depth = sum(
                w.queue_depth + w.inflight
                for w in self._workers.values()
                if not w.failed and not w.retiring
            )
        span = max(self.slo_short_sec, 1e-6)
        service_rate = self._windows.ok.total(self.slo_short_sec) / span
        hint = self._forecaster.step(now, depth, service_rate)
        if hint is None or any(
            a in ("scale_up", "scale_down") for a in burn_actions
        ):
            return
        if (
            hint == "scale_up"
            and block["state"] != "page"
            and n_live < self.max_workers
            and self._policy.cooled(now)
        ):
            self._policy.note_scale(now)
            self._apply_scale_up(
                now, "queue_forecast", n_live, event="forecast_scale_up",
            )
        elif (
            # "warn" is included: the burn policy holds position there,
            # so a sustained-low forecast is the only voice that can
            # shrink an over-provisioned warn-state fleet.
            hint == "scale_down"
            and block["state"] in ("ok", "warn")
            and not self._policy.brownout
            and n_live > self._policy.min_workers
            and self._policy.cooled(now)
        ):
            self._policy.note_scale(now)
            self._apply_scale_down(
                now, "queue_forecast", n_live, event="forecast_scale_down",
            )

    def _ready_workers(self) -> List[FleetWorker]:
        with self._lock:
            return [
                w for w in self._workers.values()
                if w.ready and not w.failed and not w.retiring
            ]

    def _apply_brownout(self, now: float, objective: str) -> None:
        with self._lock:
            self._brownout = True
        for w in self._ready_workers():
            self._apply_policy(w, self.brownout_watermark)
        self._log_event(
            now, event="brownout", objective=objective,
            downgrade_watermark=self.brownout_watermark,
        )

    def _apply_restore(self, now: float, objective: str) -> None:
        with self._lock:
            self._brownout = False
        for w in self._ready_workers():
            self._apply_policy(w, w.baseline_downgrade)
        self._log_event(now, event="restore", objective=objective)

    def _apply_scale_up(
        self, now: float, objective: str, n_live: int,
        event: str = "scale_up",
    ) -> None:
        with self._lock:
            slot = self._next_slot
            self._next_slot += 1
        self._log_event(
            now, event=event, objective=objective,
            workers=n_live + 1, slot=slot,
        )
        # The brown-out policy (if active) lands on the new worker when
        # it reports ready — _note_ready re-applies it.
        self._spawn_worker(slot, 0)

    def _apply_scale_down(
        self, now: float, objective: str, n_live: int,
        event: str = "scale_down",
    ) -> None:
        # Retire the highest live slot: deterministic choice, and the
        # base slots (0..n_workers-1) are never the ones retired.
        with self._lock:
            candidates = [
                w for w in self._workers.values()
                if not w.failed and not w.retiring
                and w.slot >= self.n_workers
            ]
            if not candidates:
                return
            w = max(candidates, key=lambda x: x.slot)
            w.retiring = True
            w.ready = False
            self._ring.remove(w.slot)
        self._set_down_event(w)
        w.kill_deadline = now + self.grace_sec
        try:
            w.proc.send_signal(signal.SIGTERM)
        except OSError:
            pass
        self._log_event(
            now, event=event, objective=objective,
            workers=n_live - 1, worker=w.worker_id,
        )

    # -- stats ---------------------------------------------------------

    def _account_relay(self, w: FleetWorker, status: int) -> None:
        bucket = (
            "ok" if status < 400
            else "shed" if status == 429
            else "deadline_expired" if status == 504
            else "errors"
        )
        with self._lock:
            self._routed["enhance"] += 1
            self._worker_ledger[w.worker_id][bucket] += 1

    def summary(self) -> dict:
        win = self._windows.block(self.slo_short_sec)
        with self._lock:
            events = list(self._events)
            fleet = {
                "workers": len(self._workers),
                "ready": sum(1 for w in self._workers.values() if w.ready),
                "max_workers": self.max_workers,
                "restarts": self._restarts,
                "redispatches": self._redispatches,
                "routed": dict(self._routed),
                "per_worker": {
                    wid: dict(c) for wid, c in self._worker_ledger.items()
                },
                "recovery_sec_last": (
                    round(self._recovery_last, 3)
                    if self._recovery_last is not None else None
                ),
                "recovery_sec_max": round(self._recovery_max, 3),
                "brownout": self._brownout,
                "ring": self._ring.members(),
                "response_cache": (
                    self.response_cache.counters()
                    if self.response_cache is not None
                    else empty_cache_block()
                ),
                "forecast": (
                    self._forecaster.block()
                    if self._forecaster is not None
                    else empty_forecast_block()
                ),
            }
            workers = {
                w.worker_id: w.summary() for w in self._workers.values()
            }
            worker_stats = {
                w.worker_id: w.last_stats
                for w in self._workers.values()
                if w.last_stats is not None
            }
            slo_block = self._slo_block
        fleet["scale_events"] = [
            e for e in events
            if e.get("event") in (
                "scale_up", "scale_down",
                "forecast_scale_up", "forecast_scale_down",
            )
        ]
        fleet["events"] = events[-100:]
        return {
            "fleet": fleet,
            "workers": workers,
            "worker_stats": worker_stats,
            "window": win,
            "slo": slo_block,
        }

    # -- HTTP plumbing (mirrors serving/server.py) ---------------------

    async def _main(self, install_signals: bool) -> int:
        from waternet_tpu.resilience.preemption import PreemptionGuard

        guard = PreemptionGuard() if install_signals else None
        if guard is not None:
            guard.__enter__()
        server = None
        try:
            self._loop = asyncio.get_running_loop()
            server = await asyncio.start_server(
                self._handle_connection, self.host, self.port
            )
            self.bound_port = server.sockets[0].getsockname()[1]
            self._bound.set()
            print(
                f"waternet-fleet: listening on http://{self.host}:"
                f"{self.bound_port}",
                flush=True,
            )
            self._monitor = threading.Thread(
                target=self._monitor_loop,
                name=f"{THREAD_PREFIX}-fleet-monitor",
                daemon=True,
            )
            self._monitor.start()

            while not (
                self._drain_flag or (guard is not None and guard.requested)
            ):
                await asyncio.sleep(0.05)

            # Drain ordering (docs/SERVING.md "Fleet"): the ROUTER stops
            # admitting first (503 + close), relays in flight finish,
            # THEN workers are asked to drain — a worker must never
            # disappear under a relay the router already accepted.
            self.draining.set()
            print("waternet-fleet: draining", flush=True)
            deadline = time.monotonic() + self.grace_sec
            clean = False
            while time.monotonic() < deadline:
                with self._lock:
                    inflight = self._inflight
                if inflight == 0:
                    clean = True
                    break
                await asyncio.sleep(0.02)
            await asyncio.sleep(0.05)
            loop = asyncio.get_running_loop()
            workers_clean = await loop.run_in_executor(
                None, self._shutdown_workers
            )
            return 0 if (clean and workers_clean) else 1
        finally:
            self._stop_monitor.set()
            if self._monitor is not None:
                self._monitor.join(timeout=10.0)
            if server is not None:
                server.close()
                await server.wait_closed()
            # Belt and braces: no worker process survives the router.
            with self._lock:
                leftovers = list(self._workers.values())
            for w in leftovers:
                if w.proc.poll() is None:
                    try:
                        w.proc.kill()
                        w.proc.wait(timeout=5.0)
                    except OSError:
                        pass
            if guard is not None:
                guard.__exit__(None, None, None)
            print(json.dumps(self.summary()), flush=True)

    def _shutdown_workers(self) -> bool:
        """Drain every worker (SIGTERM -> grace -> SIGKILL); True when
        all live workers exited cleanly. Runs in an executor thread
        after the router's own drain, monitor already stopping."""
        self._stop_monitor.set()
        if self._monitor is not None:
            self._monitor.join(timeout=10.0)
        with self._lock:
            workers = list(self._workers.values())
        for w in workers:
            if w.proc.poll() is None:
                try:
                    w.proc.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        clean = True
        deadline = time.monotonic() + self.drain_grace_sec + self.grace_sec
        for w in workers:
            remaining = max(0.1, deadline - time.monotonic())
            try:
                rc = w.proc.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                try:
                    w.proc.kill()
                    w.proc.wait(timeout=5.0)
                except OSError:
                    pass
                rc = w.proc.poll()
            if rc != 0 and not (w.failed or w.retiring):
                clean = False
        return clean

    async def _handle_connection(self, reader, writer):
        try:
            while True:
                req = await self._read_request(reader)
                if req is None:
                    break
                keep = await self._dispatch(req, reader, writer)
                await writer.drain()
                if not keep:
                    break
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:
                pass

    async def _read_request(
        self, reader
    ) -> Optional[Tuple[str, str, dict, bytes]]:
        try:
            line = await reader.readline()
        except (ConnectionError, asyncio.LimitOverrunError, ValueError):
            return None
        if not line or not line.strip():
            return None
        parts = line.decode("latin-1").strip().split()
        if len(parts) < 2:
            return None
        method, target = parts[0].upper(), parts[1]
        headers = {}
        while True:
            try:
                line = await reader.readline()
            except (ConnectionError, asyncio.LimitOverrunError, ValueError):
                return None
            if not line or line in (b"\r\n", b"\n"):
                break
            name, _, value = line.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        length = _content_length(headers)
        if length > MAX_BODY_BYTES:
            return (method, target, headers, b"")
        body = await reader.readexactly(length) if length else b""
        return method, target.split("?", 1)[0], headers, body

    def _respond(
        self, writer, status, body, ctype="application/json", extra=(),
        close=False,
    ) -> bool:
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {len(body)}\r\n"
        )
        for name, value in extra:
            head += f"{name}: {value}\r\n"
        if close:
            head += "Connection: close\r\n"
        writer.write(head.encode("latin-1") + b"\r\n" + body)
        return not close

    def _json(self, writer, status, payload, extra=(), close=False) -> bool:
        return self._respond(
            writer, status, json.dumps(payload).encode(), extra=extra,
            close=close,
        )

    async def _dispatch(self, req, reader, writer) -> bool:
        method, path, headers, body = req
        want_close = headers.get("connection", "").lower() == "close"
        req_id = _request_id(headers)
        rid = (("X-Request-Id", req_id),)
        if _content_length(headers) > MAX_BODY_BYTES:
            return self._json(
                writer, 413, {"error": "payload too large"}, extra=rid,
                close=True,
            )
        if path == "/stream":
            if method != "POST":
                return self._json(
                    writer, 405,
                    {"error": "POST a length-prefixed frame stream "
                     "to /stream"},
                    extra=rid,
                )
            await self._stream(headers, reader, writer, req_id)
            return False
        if path == "/healthz":
            return self._healthz(writer) and not want_close
        if path == "/stats":
            return (
                self._json(writer, 200, self.summary()) and not want_close
            )
        if path == "/metrics":
            return (
                self._respond(
                    writer, 200,
                    render_fleet_prometheus(self.summary()).encode(),
                    ctype="text/plain; version=0.0.4; charset=utf-8",
                )
                and not want_close
            )
        if path in ("/enhance", "/v1/enhance"):
            if method != "POST":
                return self._json(
                    writer, 405,
                    {"error": "POST image bytes to /enhance"}, extra=rid,
                )
            return (
                await self._enhance(path, headers, body, writer, req_id)
                and not want_close
            )
        if path == "/admin/reload":
            if method != "POST":
                return self._json(
                    writer, 405,
                    {"error": 'POST {"weights": path} to /admin/reload'},
                    extra=rid,
                )
            return (
                await self._admin_reload(headers, body, writer, req_id)
                and not want_close
            )
        return self._json(writer, 404, {"error": f"no route {path}"},
                          extra=rid)

    def _healthz(self, writer) -> bool:
        with self._lock:
            workers = {
                w.worker_id: w.summary() for w in self._workers.values()
            }
            n_ready = sum(1 for w in self._workers.values() if w.ready)
            any_sick = any(
                w.failed or w.health.state in ("late",)
                for w in self._workers.values()
            )
            brownout = self._brownout
            slo_block = self._slo_block
        payload = {
            "ready": n_ready > 0 and not self.draining.is_set(),
            "draining": self.draining.is_set(),
            "workers": workers,
            "ready_workers": n_ready,
            "brownout": brownout,
        }
        if slo_block is not None:
            payload["slo"] = {
                "grade": slo_block["grade"],
                "state": slo_block["state"],
                "spec": slo_block["spec"],
            }
        if self.draining.is_set():
            payload["status"] = "draining"
            return self._json(writer, 503, payload)
        if n_ready == 0:
            payload["status"] = "unhealthy"
            return self._json(writer, 503, payload)
        slo_degraded = (
            slo_block is not None and slo_block["grade"] == "degraded"
        )
        payload["status"] = (
            "degraded" if (any_sick or slo_degraded or brownout) else "ok"
        )
        return self._json(writer, 200, payload)

    # -- /enhance relay ------------------------------------------------

    def _pick_worker(
        self, tried, budget_ms: Optional[float]
    ) -> Tuple[Optional[FleetWorker], bool]:
        """Least-loaded ready worker not yet tried; deadline-aware —
        workers whose projected answer time blows the budget are
        skipped. Returns (worker, any_skipped_on_deadline)."""
        skipped = False
        with self._lock:
            cands = [
                w for w in self._workers.values()
                if w.ready and not w.failed and not w.retiring
                and w.slot not in tried
            ]
        if budget_ms is not None:
            fitting = [w for w in cands if w.est_ms() <= budget_ms]
            skipped = len(fitting) < len(cands)
            cands = fitting
        if not cands:
            return None, skipped
        w = min(cands, key=lambda w: (w.inflight, w.queue_depth, w.slot))
        return w, skipped

    async def _relay_enhance(
        self, w: FleetWorker, path: str, headers: dict, body: bytes,
        req_id: str, sink: Optional[_ClientSink] = None,
    ):
        """One relay attempt. Returns (status, ctype, relay_headers,
        body) or None on a demonstrable transport failure (connect
        error, torn response, worker declared down mid-read, per-attempt
        timeout) — the caller re-dispatches those; worker ANSWERS always
        relay. With a ``sink``, the body streams to the client as it
        arrives (returned body is None) and ``sink.committed`` marks the
        point of no redispatch."""
        try:
            wreader, wwriter = await asyncio.open_connection(
                "127.0.0.1", w.port
            )
        except OSError:
            return None
        try:
            head = f"POST {path} HTTP/1.1\r\nHost: 127.0.0.1\r\n"
            fwd = dict(headers)
            fwd["x-request-id"] = req_id
            for name in _FORWARD_HEADERS:
                if name in fwd:
                    head += f"{name}: {fwd[name]}\r\n"
            head += (
                f"Content-Length: {len(body)}\r\nConnection: close\r\n\r\n"
            )
            wwriter.write(head.encode("latin-1") + body)
            await wwriter.drain()
            if w.down_event is None:
                w.down_event = asyncio.Event()
            read = asyncio.ensure_future(
                self._read_worker_response(wreader, sink)
            )
            down = asyncio.ensure_future(w.down_event.wait())
            done, pending = await asyncio.wait(
                {read, down},
                timeout=self.proxy_timeout_sec,
                return_when=asyncio.FIRST_COMPLETED,
            )
            for t in pending:
                t.cancel()
            if down in done and read not in done:
                read.cancel()
                return None
            if read not in done:
                return None  # per-attempt timeout: treat as failed worker
            try:
                return read.result()
            except (
                ConnectionError, asyncio.IncompleteReadError, OSError,
                ValueError,
            ):
                return None
        except (ConnectionError, OSError):
            return None
        finally:
            try:
                wwriter.close()
            except Exception:
                pass

    async def _read_worker_response(
        self, wreader, sink: Optional[_ClientSink] = None
    ):
        line = await wreader.readline()
        parts = line.decode("latin-1").split()
        if len(parts) < 2:
            return None
        status = int(parts[1])
        headers = {}
        while True:
            line = await wreader.readline()
            if not line or line in (b"\r\n", b"\n"):
                break
            name, _, value = line.decode("latin-1").partition(":")
            headers[name.strip().lower()] = value.strip()
        length = _content_length(headers)
        relay = tuple(
            (name.title(), headers[name])
            for name in _RELAY_HEADERS
            if name in headers and name != "content-type"
        )
        ctype = headers.get("content-type", "application/json")
        if sink is None:
            body = await wreader.readexactly(length) if length else b""
            return status, ctype, relay, body
        # Copy-lean path: the head is committed to the client the moment
        # it parses, then the body pumps through in 64 KiB chunks (the
        # /stream relay's unit) — the router never holds the full
        # answer. Tee-accumulate only when the sink's head callback
        # asked for the bytes back (a router cache put).
        sink.begin(status, ctype, relay, length)
        remaining = length
        while remaining:
            chunk = await wreader.read(min(remaining, 1 << 16))
            if not chunk:
                raise asyncio.IncompleteReadError(b"", remaining)
            if sink.tee is not None:
                sink.tee.append(chunk)
            sink.writer.write(chunk)
            await sink.writer.drain()
            remaining -= len(chunk)
        return status, ctype, relay, None

    def _commit_relay_head(
        self, writer, status: int, ctype: str, relay, length: int,
        cache_key, req_tier: str, rid,
    ) -> Optional[List[bytes]]:
        """Write the relayed response head to the client (same bytes
        ``_respond`` would have produced) and decide the tee: a chunk
        list when the router cache will store this body, else None."""
        extra = relay
        if cache_key is not None and not any(
                n == "X-Cache" for n, _ in extra):
            # Router cache enabled but this answer came from a worker
            # (and the worker didn't stamp its own cache state): stamp
            # the router-level miss.
            extra = extra + (("X-Cache", "miss"),)
        if not any(n == "X-Request-Id" for n, _ in extra):
            extra = extra + rid
        head = (
            f"HTTP/1.1 {status} {_REASONS.get(status, 'Unknown')}\r\n"
            f"Content-Type: {ctype}\r\n"
            f"Content-Length: {length}\r\n"
        )
        for name, value in extra:
            head += f"{name}: {value}\r\n"
        writer.write(head.encode("latin-1") + b"\r\n")
        if cache_key is not None and status == 200:
            served = next(
                (v for n, v in relay if n == "X-Tier-Served"), None
            )
            if served is not None and served.strip().lower() == req_tier:
                return []
        return None

    async def _enhance(self, path, headers, body, writer, req_id) -> bool:
        rid = (("X-Request-Id", req_id),)
        if self.draining.is_set():
            return self._json(
                writer, 503, {"error": "draining"}, extra=rid, close=True,
            )
        budget_ms = None
        raw = headers.get("x-deadline-ms")
        if raw is not None:
            try:
                budget_ms = float(raw)
            except ValueError:
                budget_ms = None  # forwarded anyway; the worker 400s it
        t0 = time.monotonic()
        req_tier = headers.get("x-tier", "quality").strip().lower()
        cache_key = None
        if self.response_cache is not None:
            cache_key = self.response_cache.key(body, req_tier)
            cached = self.response_cache.get(cache_key)
            if cached is not None:
                # Replay the stored worker answer without touching a
                # worker. Cached relay headers were stripped of the
                # original X-Request-Id / X-Cache at store time, so the
                # replay carries this request's id and a "hit" stamp.
                c_ctype, c_relay, c_body = cached
                self._windows.observe(200, (time.monotonic() - t0) * 1e3)
                return self._respond(
                    writer, 200, c_body, ctype=c_ctype,
                    extra=c_relay + (("X-Cache", "hit"),) + rid,
                )
        with self._lock:
            self._inflight += 1
        tried: set = set()
        skipped_any = False
        try:
            for _ in range(self.route_retries + 1):
                remaining = (
                    None if budget_ms is None
                    else budget_ms - (time.monotonic() - t0) * 1e3
                )
                w, skipped = self._pick_worker(tried, remaining)
                skipped_any = skipped_any or skipped
                if w is None:
                    break
                with self._lock:
                    w.inflight += 1
                sink = _ClientSink(
                    writer,
                    lambda s, c, r, n: self._commit_relay_head(
                        writer, s, c, r, n, cache_key, req_tier, rid
                    ),
                )
                try:
                    answer = await self._relay_enhance(
                        w, path, headers, body, req_id, sink=sink
                    )
                finally:
                    with self._lock:
                        w.inflight -= 1
                if answer is None:
                    if sink.committed:
                        # The head (and possibly part of the body) is
                        # already on the wire: a redispatch would splice
                        # two answers. Account the torn relay and drop
                        # the connection — the client sees truncation,
                        # exactly what a direct worker death looks like.
                        self._windows.observe(
                            500, (time.monotonic() - t0) * 1e3
                        )
                        self._account_relay(w, 500)
                        return False
                    # Demonstrable transport failure before any byte
                    # reached the client: the worker died or wedged
                    # under this relay. Bounded re-dispatch, same
                    # X-Request-Id — byte-identical by replica invariance.
                    tried.add(w.slot)
                    with self._lock:
                        self._redispatches += 1
                    continue
                status, _ctype, relay, _streamed = answer
                latency_ms = (time.monotonic() - t0) * 1e3
                self._windows.observe(status, latency_ms)
                self._account_relay(w, status)
                if sink.tee is not None:
                    # The head callback teed the body for the router
                    # cache (200, exact requested tier — a brown-out
                    # downgrade is never replayed later).
                    stored_relay = tuple(
                        (n, v) for n, v in relay
                        if n not in ("X-Request-Id", "X-Cache")
                    )
                    self.response_cache.put(
                        cache_key,
                        (_ctype, stored_relay, b"".join(sink.tee)),
                    )
                return True
            # Out of candidates (or retries): the router answers, id
            # echoed, so the client's correlation never dangles.
            self._windows.observe(504 if skipped_any else 503, 0.0)
            if skipped_any:
                return self._json(
                    writer, 504,
                    {"error": "no worker can meet the deadline",
                     "budget_ms": budget_ms},
                    extra=rid,
                )
            return self._json(
                writer, 503,
                {"error": "no healthy worker"},
                extra=(("Retry-After", "1"),) + rid,
            )
        finally:
            with self._lock:
                self._inflight -= 1

    async def _admin_reload(self, headers, body, writer, req_id) -> bool:
        """Broadcast ``POST /admin/reload`` to every ready worker, then
        invalidate the router response cache. The aggregate answer is
        200 only when every ready worker reloaded; per-worker replies
        are included so a mixed fleet is diagnosable from one call.
        Cache invalidation happens even on partial failure — a stale
        replay is worse than a redundant recompute."""
        rid = (("X-Request-Id", req_id),)
        if self.draining.is_set():
            return self._json(
                writer, 503, {"error": "draining"}, extra=rid, close=True,
            )
        with self._lock:
            workers = [
                w for w in self._workers.values()
                if w.ready and not w.failed and not w.retiring
            ]
        if not workers:
            return self._json(
                writer, 503, {"error": "no healthy worker"},
                extra=(("Retry-After", "1"),) + rid,
            )
        results = {}
        all_ok = True
        for w in workers:
            answer = await self._relay_enhance(
                w, "/admin/reload", headers, body, req_id
            )
            if answer is None:
                results[w.worker_id] = {"error": "relay failed"}
                all_ok = False
                continue
            status, _ctype, _relay, resp_body = answer
            try:
                payload = json.loads(resp_body) if resp_body else {}
            except ValueError:
                payload = {"error": "unparseable worker reply"}
            if not isinstance(payload, dict):
                payload = {"reply": payload}
            payload["status"] = status
            results[w.worker_id] = payload
            all_ok = all_ok and status == 200
        if self.response_cache is not None:
            self.response_cache.invalidate()
        return self._json(
            writer, 200 if all_ok else 502,
            {"reloaded": all_ok, "workers": results},
            extra=rid,
        )

    # -- /stream relay -------------------------------------------------

    async def _stream(self, headers, reader, writer, req_id) -> None:
        rid = (("X-Request-Id", req_id),)
        if self.draining.is_set():
            self._json(writer, 503, {"error": "draining"}, extra=rid,
                       close=True)
            return
        with self._lock:
            slot = self._ring.lookup(req_id)
            w = self._workers.get(slot) if slot is not None else None
            pinnable = (
                w is not None and w.ready and not w.failed
                and not w.retiring
            )
            if pinnable:
                w.inflight += 1
                self._routed["stream"] += 1
                self._worker_ledger[w.worker_id]["streams"] += 1
        if not pinnable:
            self._json(
                writer, 503,
                {"error": "pinned worker unavailable"},
                extra=(("Retry-After", "1"),) + rid, close=True,
            )
            return
        try:
            try:
                wreader, wwriter = await asyncio.open_connection(
                    "127.0.0.1", w.port
                )
            except OSError:
                self._json(
                    writer, 503,
                    {"error": "pinned worker unavailable"},
                    extra=(("Retry-After", "1"),) + rid, close=True,
                )
                return
            try:
                head = "POST /stream HTTP/1.1\r\nHost: 127.0.0.1\r\n"
                fwd = dict(headers)
                fwd["x-request-id"] = req_id
                for name in _FORWARD_HEADERS:
                    if name in fwd:
                        head += f"{name}: {fwd[name]}\r\n"
                head += "Connection: close\r\n\r\n"
                wwriter.write(head.encode("latin-1"))
                await wwriter.drain()
                # Raw byte relay both ways from here: the worker's
                # response head (and its in-order frame records) pass
                # through verbatim, so stream bit-identity is the
                # worker's property, untouched by the hop.
                up = asyncio.ensure_future(
                    self._pump(reader, wwriter)
                )
                down = asyncio.ensure_future(
                    self._pump(wreader, writer)
                )
                # The session is over when the WORKER closes (it sends
                # the end-of-stream record and half of the pair ends);
                # the client-side pump is then cancelled.
                await down
                up.cancel()
                try:
                    await up
                except (asyncio.CancelledError, ConnectionError, OSError):
                    pass
            finally:
                try:
                    wwriter.close()
                except Exception:
                    pass
        except (ConnectionError, asyncio.IncompleteReadError):
            pass
        finally:
            with self._lock:
                w.inflight -= 1

    @staticmethod
    async def _pump(src_reader, dst_writer) -> None:
        try:
            while True:
                chunk = await src_reader.read(1 << 16)
                if not chunk:
                    break
                dst_writer.write(chunk)
                await dst_writer.drain()
        except (ConnectionError, asyncio.IncompleteReadError, OSError):
            pass


# ----------------------------------------------------------------------
# Prometheus projection (fleet vocabulary — the worker metrics live on
# each worker's own /metrics; the router exports the FLEET view).
# ----------------------------------------------------------------------


def render_fleet_prometheus(summary: dict) -> str:
    fleet = summary["fleet"]
    lines: List[str] = []

    def metric(name, mtype, help_text, samples):
        lines.append(f"# HELP {name} {help_text}")
        lines.append(f"# TYPE {name} {mtype}")
        for labels, value in samples:
            if labels:
                body = ",".join(f'{k}="{v}"' for k, v in labels.items())
                lines.append(f"{name}{{{body}}} {value}")
            else:
                lines.append(f"{name} {value}")

    metric("waternet_fleet_workers", "gauge", "Workers in the fleet table",
           [(None, fleet["workers"])])
    metric("waternet_fleet_workers_ready", "gauge", "Workers serving",
           [(None, fleet["ready"])])
    metric("waternet_fleet_restarts_total", "counter",
           "Worker relaunches (fresh generations)",
           [(None, fleet["restarts"])])
    metric("waternet_fleet_redispatch_total", "counter",
           "Relays re-dispatched after a worker failure",
           [(None, fleet["redispatches"])])
    metric("waternet_fleet_routed_total", "counter",
           "Requests routed, by route",
           [({"route": k}, v) for k, v in sorted(fleet["routed"].items())])
    metric("waternet_fleet_scale_events_total", "counter",
           "Scale-up/down events", [(None, len(fleet["scale_events"]))])
    metric("waternet_fleet_brownout", "gauge",
           "1 while the brown-out policy shift is applied",
           [(None, 1 if fleet["brownout"] else 0)])
    metric("waternet_fleet_recovery_sec_max", "gauge",
           "Slowest failure-to-ready worker recovery",
           [(None, fleet["recovery_sec_max"])])
    cache = fleet.get("response_cache")
    if cache:
        metric("waternet_fleet_response_cache_enabled", "gauge",
               "1 when the router content-addressed /enhance cache is on",
               [(None, 1 if cache["enabled"] else 0)])
        metric("waternet_fleet_response_cache_hits_total", "counter",
               "Router /enhance answers replayed from cache",
               [(None, cache["hits"])])
        metric("waternet_fleet_response_cache_misses_total", "counter",
               "Router /enhance cache lookups that fell through",
               [(None, cache["misses"])])
        metric("waternet_fleet_response_cache_evictions_total", "counter",
               "Router cache entries evicted by the LRU bound",
               [(None, cache["evictions"])])
        metric("waternet_fleet_response_cache_entries", "gauge",
               "Router cache entries currently held",
               [(None, cache["entries"])])
    forecast = fleet.get("forecast") or {}
    if forecast.get("depth") is not None:
        metric("waternet_fleet_forecast_depth", "gauge",
               "Forecast aggregate queue depth at the scaling horizon",
               [(None, forecast["depth"])])
        metric("waternet_fleet_forecast_breach_eta_sec", "gauge",
               "Seconds until the queue-depth forecast breaches the "
               "latency objective (absent: no breach on the horizon)",
               [(None, forecast["breach_eta_sec"])]
               if forecast.get("breach_eta_sec") is not None else [])
    metric(
        "waternet_fleet_worker_relay_total", "counter",
        "Relayed answers per worker, by outcome",
        [
            ({"worker": wid, "outcome": outcome}, n)
            for wid, counts in sorted(fleet["per_worker"].items())
            for outcome, n in sorted(counts.items())
        ],
    )
    win = summary.get("window") or {}
    lat = win.get("latency_ms") or {}
    metric(
        "waternet_fleet_latency_ms", "gauge",
        "Windowed relay latency quantiles",
        [
            ({"quantile": q}, lat.get(f"p{int(float(q) * 100)}", 0.0))
            for q in ("0.5", "0.9", "0.99")
        ],
    )
    slo = summary.get("slo")
    if slo:
        states = {"ok": 0, "warn": 1, "page": 2}
        metric(
            "waternet_fleet_slo_state", "gauge",
            "Per-objective alert state (ok=0 warn=1 page=2)",
            [
                ({"objective": row["objective"]},
                 states.get(row["state"], 0))
                for row in slo.get("objectives", ())
            ],
        )
    return "\n".join(lines) + "\n"


# ----------------------------------------------------------------------
# CLI
# ----------------------------------------------------------------------


def _parse_worker_faults(specs) -> Dict[Tuple[int, int], str]:
    """``SLOT:PLAN`` or ``SLOT.GEN:PLAN`` -> {(slot, gen): plan}."""
    out: Dict[Tuple[int, int], str] = {}
    for raw in specs or ():
        head, sep, plan = raw.partition(":")
        if not sep or not plan:
            raise SystemExit(
                f"--worker-faults wants SLOT[:.GEN]:PLAN, got {raw!r}"
            )
        if "." in head:
            slot_s, gen_s = head.split(".", 1)
        else:
            slot_s, gen_s = head, "0"
        try:
            out[(int(slot_s), int(gen_s))] = plan
        except ValueError:
            raise SystemExit(
                f"--worker-faults wants integer slot/generation, got {raw!r}"
            )
    return out


def parse_args(argv=None):
    parser = argparse.ArgumentParser(
        prog="waternet-fleet",
        description="Supervised multi-worker serving router "
        "(docs/SERVING.md 'Fleet'). Arguments after -- are passed to "
        "every waternet-serve worker.",
    )
    parser.add_argument("--host", type=str, default="127.0.0.1")
    parser.add_argument(
        "--port", type=int, default=8080,
        help="Router port; 0 = ephemeral (printed on the 'listening on' "
        "line). Workers always bind ephemeral local ports.",
    )
    parser.add_argument(
        "--workers", type=int, default=2,
        help="Initial (and minimum) serving worker processes.",
    )
    parser.add_argument(
        "--max-workers", type=int, default=None,
        help="Scale-up ceiling for the SLO closed loop "
        "(default: --workers, i.e. no autoscaling).",
    )
    parser.add_argument(
        "--late-sec", type=float, default=3.0,
        help="Heartbeat age that marks a worker late (logged only).",
    )
    parser.add_argument(
        "--hang-sec", type=float, default=6.0,
        help="Heartbeat age past which a worker is presumed hung: "
        "drained, SIGKILLed past --drain-grace-sec, relaunched.",
    )
    parser.add_argument(
        "--startup-grace-sec", type=float, default=300.0,
        help="Boot window per generation (jax import + AOT warmup) "
        "before missing serve-phase beats count as a hang.",
    )
    parser.add_argument(
        "--drain-grace-sec", type=float, default=10.0,
        help="SIGTERM-to-SIGKILL window when retiring a failed worker.",
    )
    parser.add_argument(
        "--grace-sec", type=float, default=30.0,
        help="Router drain window: relays in flight must finish within "
        "it for exit 0 (workers are drained after).",
    )
    parser.add_argument("--poll-sec", type=float, default=0.25)
    parser.add_argument("--health-poll-sec", type=float, default=0.5)
    parser.add_argument(
        "--route-retries", type=int, default=2,
        help="Re-dispatch budget for a relay whose worker demonstrably "
        "failed mid-answer (verdict answers like 429/503/504 relay "
        "as-is, they are never retried).",
    )
    parser.add_argument(
        "--proxy-timeout-sec", type=float, default=120.0,
        help="Per-attempt relay timeout; a worker that exceeds it is "
        "treated as failed for this request and the relay re-dispatches.",
    )
    parser.add_argument(
        "--slo", type=str, default=None, metavar="SPEC",
        help="Arm the fleet SLO closed loop over RELAYED outcomes, e.g. "
        '"p99_ms<=250,error_rate<=0.01". Sustained page burn scales the '
        "fleet up (to --max-workers) and applies the brown-out policy; "
        "sustained ok scales down and restores.",
    )
    parser.add_argument("--slo-short-sec", type=float,
                        default=obswin.DEFAULT_WINDOW_SEC)
    parser.add_argument("--slo-long-sec", type=float,
                        default=obswin.DEFAULT_LONG_WINDOW_SEC)
    parser.add_argument("--slo-hold-sec", type=float, default=60.0)
    parser.add_argument(
        "--scale-cooldown-sec", type=float, default=30.0,
        help="Minimum spacing between scale actions (anti-flap).",
    )
    parser.add_argument(
        "--no-forecast", action="store_true",
        help="Disable the queue-depth forecaster (on by default when "
        "the --slo spec has a latency objective): predictive "
        "scale-up/down composing with the burn loop.",
    )
    parser.add_argument(
        "--forecast-horizon-sec", type=float, default=30.0,
        help="Scale up when the forecast queue depth breaches the "
        "latency objective within this many seconds.",
    )
    parser.add_argument(
        "--forecast-up-sustain", type=int, default=2,
        help="Consecutive breach-forecast ticks before a predictive "
        "scale-up (hysteresis).",
    )
    parser.add_argument(
        "--forecast-down-sustain", type=int, default=6,
        help="Consecutive low-forecast ticks before a predictive "
        "scale-down (hysteresis).",
    )
    parser.add_argument(
        "--brownout-watermark", type=int, default=1,
        help="Downgrade watermark POSTed to every worker while paging: "
        "1 = every opted-in quality request downgrades under any load.",
    )
    parser.add_argument(
        "--heartbeat-dir", type=str, default=None,
        help="Root for worker heartbeat files (default: a tempdir).",
    )
    parser.add_argument(
        "--worker-faults", action="append", default=None,
        metavar="SLOT[:.GEN]:PLAN",
        help="Deterministic fault plan for exactly one worker "
        "generation, e.g. '1:gateway_crash@3' (docs/RESILIENCE.md).",
    )
    parser.add_argument("--max-restarts", type=int, default=5)
    parser.add_argument(
        "--response-cache", type=int, default=0, metavar="N",
        help="Router-level content-addressed /enhance response cache "
        "holding up to N answers (0 = off, the default). Keys include "
        "the requested tier; only full-tier answers are stored, and "
        "/admin/reload through the router invalidates everything.",
    )
    parser.add_argument(
        "worker_args", nargs=argparse.REMAINDER,
        help="Arguments after -- go to every waternet-serve worker.",
    )
    return parser.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    worker_args = list(args.worker_args)
    if worker_args and worker_args[0] == "--":
        worker_args = worker_args[1:]
    worker_cmd = [
        sys.executable, "-m", "waternet_tpu.serving.server",
    ] + worker_args
    router = FleetRouter(
        worker_cmd,
        n_workers=args.workers,
        max_workers=args.max_workers,
        host=args.host,
        port=args.port,
        late_sec=args.late_sec,
        hang_sec=args.hang_sec,
        startup_grace_sec=args.startup_grace_sec,
        drain_grace_sec=args.drain_grace_sec,
        poll_sec=args.poll_sec,
        health_poll_sec=args.health_poll_sec,
        route_retries=args.route_retries,
        proxy_timeout_sec=args.proxy_timeout_sec,
        grace_sec=args.grace_sec,
        slo=args.slo,
        slo_short_sec=args.slo_short_sec,
        slo_long_sec=args.slo_long_sec,
        slo_hold_sec=args.slo_hold_sec,
        scale_cooldown_sec=args.scale_cooldown_sec,
        forecast=not args.no_forecast,
        forecast_horizon_sec=args.forecast_horizon_sec,
        forecast_up_sustain=args.forecast_up_sustain,
        forecast_down_sustain=args.forecast_down_sustain,
        brownout_watermark=args.brownout_watermark,
        heartbeat_root=args.heartbeat_dir,
        worker_faults=_parse_worker_faults(args.worker_faults),
        max_restarts=args.max_restarts,
        response_cache=args.response_cache,
    )
    return router.run(install_signal_handlers=True)


if __name__ == "__main__":
    sys.exit(main())
