"""Stream sessions: live video over the serving front door
(docs/SERVING.md "Streaming").

A client opens a session with ``POST /stream`` and uploads
length-prefixed frames on the same connection the enhanced frames come
back on — stdlib framing over the stdlib HTTP server, no new protocol
dependency. The :class:`StreamManager` owns admission (the third rung
of the degradation ladder) and one :class:`StreamSession` per open
connection; each session is two asyncio tasks over the shared
:class:`~waternet_tpu.serving.batcher.DynamicBatcher`:

* the **reader** pulls frames off the socket, decodes them in the
  executor, and submits them to the batcher with a freshness deadline
  derived from the stream's declared fps budget — frames ride the same
  smallest-viable-bucket path as ``/enhance`` requests, so stream
  traffic compiles nothing (the compile-sentinel guarantee holds);
* the **writer** delivers results strictly in submit order, one record
  per frame — a frame that could not be delivered becomes an explicit
  drop or error record with a reason, never a silent gap and never a
  reorder.

Per-stream QoS policies, each deterministically fault-testable via
``WATERNET_FAULTS`` (``stream_stall@K`` / ``stream_disconnect@K`` /
``frame_corrupt@K``):

* **In-order delivery**: the session deque is FIFO in read order;
  PR-9 crash/hang re-dispatch may complete batches out of order, but
  the writer always resolves the head frame first.
* **Bounded latency**: each frame's deadline is ``read time + budget``;
  a frame whose budget runs out is dropped *un-computed* by the batcher
  (``D`` record, reason ``budget``). When more than ``window`` frames
  are awaiting delivery, the oldest pending frame is dropped under the
  explicit drop-oldest policy (reason ``window``) — drop records are
  delivered in sequence position, never mid-reorder.
* **Stall/fault isolation**: a wedged client backpressures only its own
  session — past ``4 x window`` buffered frames the reader stops
  reading (TCP backpressure on that one connection); decode failures
  error only their own frame (``E`` record); a disconnect abandons that
  session's queued frames (the dispatcher and re-dispatch path drop
  them un-computed via ``RequestCancelled``) without touching
  batch-mates from other streams.
* **Degradation ladder**: (1) opted-in streams brown-out to the fast
  CAN tier per frame (``FLAG_DOWNGRADED`` on the record); (2) frame
  dropping holds latency; (3) new sessions are refused with 503 +
  Retry-After while established streams keep their QoS.
* **Temporal reuse** (off by default; ``X-Stream-Reuse`` or the
  server's ``--stream-reuse-threshold`` enables it): a frame whose
  decimated delta against the last frame *submitted for compute* (the
  anchor — submission-time anchoring is what lets reuse fire under
  backlog) is at or below the threshold is answered from the anchor's
  enhanced frame WITHOUT entering the batcher — an ``R`` record
  carrying ``FLAG_REUSED`` (byte-identical to a recompute for a delta
  of zero), bounded by the ``max_reuse_run`` staleness cap. If the
  anchor itself never delivered (dropped/errored), its reuse children
  become ``anchor`` drops rather than replaying the wrong scene
  (waternet_tpu/serving/reuse.py).

Wire protocol (all integers network byte order):

* upload: per frame a 4-byte big-endian length then that many bytes of
  JPEG/PNG; length 0 ends the stream cleanly.
* download: per record a 10-byte header ``!cBII`` = (kind, flags,
  seq, payload_len) then the payload. Kinds: ``F`` enhanced PNG frame;
  ``R`` reused PNG frame (temporal gating answered it from the
  session's cached enhanced frame); ``D`` drop notice (JSON
  ``{"reason": ...}``); ``E`` frame error (JSON); ``Z`` end-of-stream
  session summary (JSON). Flag bit 0 (``FLAG_DOWNGRADED``) marks a
  frame served by the fast tier; bit 1 (``FLAG_REUSED``) marks a
  reused frame.
"""

from __future__ import annotations

import asyncio
import json
import struct
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from waternet_tpu.obs import trace
from waternet_tpu.resilience import faults
from waternet_tpu.serving.batcher import (
    DeadlineExpired,
    QueueFull,
    RequestCancelled,
)
from waternet_tpu.serving.reuse import DEFAULT_MAX_REUSE_RUN, FrameDeltaGate
from waternet_tpu.serving.stats import LATENCY_RESERVOIR, _percentile

#: Upload framing: one 4-byte big-endian payload length per frame.
FRAME_LEN = struct.Struct("!I")
#: Download framing: (kind, flags, seq, payload_len) per record.
REC_HEAD = struct.Struct("!cBII")

KIND_FRAME = b"F"
KIND_REUSED = b"R"
KIND_DROP = b"D"
KIND_ERROR = b"E"
KIND_END = b"Z"

#: Record flag bit: this frame was served by the fast tier under
#: brown-out (the stream opted in via X-Tier-Allow-Downgrade).
FLAG_DOWNGRADED = 1
#: Record flag bit: this frame was answered from the session's cached
#: enhanced frame by temporal gating (reuse.py) — never computed. A
#: reused copy of a downgraded frame carries both bits.
FLAG_REUSED = 2

#: One frame above this is a protocol error (the per-request front door
#: caps bodies the same way): refuse loudly instead of buffering it.
MAX_FRAME_BYTES = 16 << 20

#: The reader stops reading (TCP backpressure on that one connection)
#: once this many frames are buffered for a session that is not
#: consuming: the stall-isolation bound on per-session memory.
HARD_CAP_WINDOWS = 4


class StreamConfig:
    """Per-session QoS contract, parsed once from the request headers.

    ``X-Stream-Fps`` declares the paced rate (default 10); the
    freshness budget defaults to three frame intervals
    (``3000 / fps`` ms) and is overridden with ``X-Stream-Budget-Ms``.
    ``X-Tier`` / ``X-Tier-Allow-Downgrade`` mean exactly what they mean
    on ``/enhance``; ``X-Stream-Window`` bounds the frames awaiting
    delivery before drop-oldest fires (default: the server's
    ``--stream-window``). ``X-Stream-Reuse`` sets the temporal-gating
    delta threshold for this session (``off`` disables it even when the
    server default enables it; absent inherits the server's
    ``--stream-reuse-threshold``, itself off by default);
    ``X-Stream-Max-Reuse-Run`` caps consecutive reuses and
    ``X-Stream-Reuse-Warp`` enables the coarse block-flow pan
    compensation. Raises ValueError on malformed values — the front
    door answers 400."""

    def __init__(self, fps, budget_ms, tier, allow_downgrade, window,
                 reuse_threshold=None,
                 max_reuse_run=DEFAULT_MAX_REUSE_RUN,
                 reuse_warp=False):
        self.fps = fps
        self.budget_ms = budget_ms
        self.tier = tier
        self.allow_downgrade = allow_downgrade
        self.window = window
        self.reuse_threshold = reuse_threshold
        self.max_reuse_run = max_reuse_run
        self.reuse_warp = reuse_warp

    @classmethod
    def from_headers(
        cls,
        headers: dict,
        default_window: int,
        default_reuse: Optional[float] = None,
        default_max_reuse_run: int = DEFAULT_MAX_REUSE_RUN,
    ):
        fps = float(headers.get("x-stream-fps", "10"))
        if not fps > 0:
            raise ValueError(f"X-Stream-Fps must be > 0, got {fps}")
        budget_ms = float(
            headers.get("x-stream-budget-ms", str(3000.0 / fps))
        )
        if not budget_ms > 0:
            raise ValueError(
                f"X-Stream-Budget-Ms must be > 0, got {budget_ms}"
            )
        window = int(headers.get("x-stream-window", str(default_window)))
        if window < 1:
            raise ValueError(f"X-Stream-Window must be >= 1, got {window}")
        tier = headers.get("x-tier", "quality").strip().lower()
        allow_downgrade = headers.get(
            "x-tier-allow-downgrade", ""
        ).strip().lower() in ("1", "true", "yes")
        raw_reuse = headers.get("x-stream-reuse")
        if raw_reuse is None:
            reuse = default_reuse
        elif raw_reuse.strip().lower() in ("off", "none", ""):
            reuse = None
        else:
            reuse = float(raw_reuse)  # ValueError -> 400, like the rest
            if reuse < 0:
                raise ValueError(
                    f"X-Stream-Reuse must be >= 0 or 'off', got {reuse}"
                )
        max_run = int(
            headers.get(
                "x-stream-max-reuse-run", str(default_max_reuse_run)
            )
        )
        if max_run < 1:
            raise ValueError(
                f"X-Stream-Max-Reuse-Run must be >= 1, got {max_run}"
            )
        reuse_warp = headers.get(
            "x-stream-reuse-warp", ""
        ).strip().lower() in ("1", "true", "yes")
        return cls(
            fps, budget_ms, tier, allow_downgrade, window,
            reuse_threshold=reuse, max_reuse_run=max_run,
            reuse_warp=reuse_warp,
        )


class _Frame:
    """One in-flight frame of one session, from socket read to record
    written. Exactly one terminal state: delivered (``future`` result),
    reused (``reused`` holds the cached enhanced frame), dropped
    (``dropped`` holds the reason), or errored (``error``)."""

    __slots__ = (
        "seq", "t_read", "future", "dropped", "error", "delivering",
        "reused",
    )

    def __init__(self, seq: int, t_read: float):
        self.seq = seq
        self.t_read = t_read
        self.future = None  # batcher Future once submitted
        self.dropped: Optional[str] = None
        self.error: Optional[str] = None
        # The writer marks the head frame while awaiting/encoding it;
        # drop-oldest must never evict a frame mid-delivery.
        self.delivering = False
        # Temporal gating (reuse.py): the gate's reuse decision tuple
        # when the reader gated this frame out of compute; the writer
        # materializes the cached enhanced frame from it at delivery.
        self.reused = None


class StreamSession:
    """One open stream: a FIFO of :class:`_Frame` entries between a
    reader task and a writer task (see the module docstring for the
    policies; the manager owns admission and the registry)."""

    def __init__(self, sid, mgr, cfg, reader, writer, request_id=None):
        self.sid = sid
        self.mgr = mgr
        self.cfg = cfg
        self.reader = reader
        self.writer = writer
        # Correlation id for the whole session (the X-Request-Id the
        # front door echoed); per-frame spans use "<id>/<seq>".
        self.req_id = request_id or sid
        self.entries: deque = deque()
        self.progress = asyncio.Event()  # writer wake: new entry/state
        self.space = asyncio.Event()  # reader wake: room under hard cap
        self.dead = False  # connection gone: stop both loops
        self.read_done = False
        fault = faults.stream_session_fault()
        self.stall = fault.stall
        self.disconnect_after = fault.disconnect_after
        # Session accounting (the Z record and the /stats probe).
        self.frames_in = 0
        self.delivered = 0
        self.reused = 0
        self.dropped = 0
        self.out_of_budget = 0
        self.errors = 0
        self.downgraded = 0
        self.lat_s: List[float] = []  # delivered-frame latency sample
        # Temporal gating (off unless the session/server enabled it):
        # reader task checks, writer task anchors — same event loop,
        # so the gate needs no lock. The one exception is materialize,
        # which the writer task awaits on an executor thread (the warp
        # is too heavy for the loop); see FrameDeltaGate's docstring
        # for why that stays race-free.
        self.gate = (
            FrameDeltaGate(
                cfg.reuse_threshold,
                max_reuse_run=cfg.max_reuse_run,
                warp=cfg.reuse_warp,
            )
            if cfg.reuse_threshold is not None
            else None
        )

    # -- reader --------------------------------------------------------

    async def _read_len(self) -> Optional[int]:
        """Next frame length, None on clean end (length 0, EOF, or a
        server drain — sessions stop accepting frames so the drain's
        grace window is spent finishing work, not waiting on sockets)."""
        while True:
            if self.dead or self.mgr.draining.is_set():
                return None
            try:
                raw = await asyncio.wait_for(
                    self.reader.readexactly(FRAME_LEN.size), timeout=0.25
                )
            except asyncio.TimeoutError:
                continue  # re-check draining; readexactly keeps buffer
            except (asyncio.IncompleteReadError, ConnectionError):
                return None
            n = FRAME_LEN.unpack(raw)[0]
            return None if n == 0 else n

    async def run_reader(self) -> None:
        loop = asyncio.get_running_loop()
        try:
            while not self.dead:
                n = await self._read_len()
                if n is None:
                    break
                if n > MAX_FRAME_BYTES:
                    raise ConnectionResetError("oversized frame")
                try:
                    payload = await self.reader.readexactly(n)
                except (asyncio.IncompleteReadError, ConnectionError):
                    raise ConnectionResetError("mid-frame EOF")
                entry = _Frame(self.frames_in, time.perf_counter())
                self.frames_in += 1
                self.mgr.stats.record_stream_frame_in()
                # Stall-isolation hard cap: a session whose client is
                # not consuming stops READING too — backpressure lands
                # on this one connection's TCP window, never on the
                # batcher or on other sessions.
                while (
                    len(self.entries) >= HARD_CAP_WINDOWS * self.cfg.window
                    and not self.dead
                ):
                    self.space.clear()
                    await self.space.wait()
                if self.dead:
                    break
                if faults.frame_should_corrupt():
                    rgb = None
                else:
                    rgb = await loop.run_in_executor(
                        None, self.mgr.decode, payload
                    )
                if rgb is None:
                    # Decode failure quarantines ONLY this frame: an E
                    # record in sequence position, and the stream lives.
                    entry.error = "frame is not a decodable image"
                else:
                    if self.gate is not None:
                        # Temporal gating: a frame the gate recognises
                        # is answered from the anchor's enhanced frame
                        # at delivery and never enters the batcher
                        # (reuse.py — the anchor is the last SUBMITTED
                        # frame, so reuse works even under backlog).
                        entry.reused = self.gate.check(rgb)
                if rgb is not None and entry.reused is None:
                    deadline = entry.t_read + self.cfg.budget_ms / 1e3
                    try:
                        entry.future = self.mgr.batcher.submit(
                            rgb,
                            deadline=deadline,
                            tier=self.cfg.tier,
                            allow_downgrade=self.cfg.allow_downgrade,
                            request_id=f"{self.req_id}/{entry.seq}",
                        )
                        if self.gate is not None:
                            self.gate.note_submitted(rgb, entry.seq)
                    except QueueFull:
                        entry.dropped = "queue"
                    except DeadlineExpired:
                        # Budget already burned before admission (the
                        # session fell that far behind): an explicit
                        # budget drop, NOT a dead batcher — both are
                        # RuntimeError subclasses, so order matters here.
                        entry.dropped = "budget"
                    except RuntimeError:
                        break  # batcher closed under us: drain finished
                self.entries.append(entry)
                self._enforce_window()
                self.progress.set()
                if (
                    self.disconnect_after is not None
                    and self.frames_in >= self.disconnect_after
                ):
                    raise ConnectionResetError("injected stream_disconnect")
        except ConnectionResetError:
            self.dead = True
        finally:
            self.read_done = True
            self.progress.set()

    def _enforce_window(self) -> None:
        """Drop-oldest: past ``window`` frames awaiting delivery, the
        oldest pending frame (never the one the writer is mid-delivery
        on) becomes an explicit ``window`` drop; its future is marked
        abandoned so the batcher drops the compute too."""
        # Reused entries are already answered (no compute pending), so
        # they never count against the window and are never evicted —
        # drop-oldest exists to shed queued COMPUTE, not finished work.
        live = [
            e for e in self.entries
            if e.dropped is None and e.error is None and e.reused is None
        ]
        while len(live) > self.cfg.window:
            victim = next(
                (e for e in live if not e.delivering), None
            )
            if victim is None:
                return
            victim.dropped = "window"
            if victim.future is not None:
                victim.future.abandoned = True
            live.remove(victim)

    # -- writer --------------------------------------------------------

    async def _write_record(self, kind, flags, seq, payload) -> None:
        if self.stall:
            # Injected wedged consumer: every delivery stalls, so the
            # window fills, drop-oldest fires, and eventually the hard
            # cap pauses the reader — all visible to the fault tests.
            await asyncio.sleep(faults.stream_stall_sec())
        self.writer.write(REC_HEAD.pack(kind, flags, seq, len(payload)))
        self.writer.write(payload)
        await self.writer.drain()

    def _trace_frame(self, entry: _Frame, downgraded: bool = False) -> None:
        """Frame lifecycle span (docs/OBSERVABILITY.md): socket read ->
        terminal record written, with the drop/downgrade annotation
        inline — a Perfetto view of a stream shows which frames paid
        what, and why the gaps are gaps."""
        if not trace.enabled():
            return
        trace.record_span(
            "stream_frame", "serving", entry.t_read, time.perf_counter(),
            args={
                "request_id": f"{self.req_id}/{entry.seq}",
                "stream": self.sid,
                "seq": entry.seq,
                "dropped": entry.dropped,
                "downgraded": downgraded,
                "error": entry.error,
            },
        )

    async def _deliver(self, entry: _Frame) -> None:
        loop = asyncio.get_running_loop()
        if entry.reused is not None:
            # The warped replay is full-resolution numpy work (R201:
            # shift_frame is declared loop-blocking), so it runs on the
            # executor. Safe off-loop: materialize reads only the
            # writer-confined fields (_enhanced/_flags/_computed_seq)
            # and this writer task is suspended until it returns.
            hit = await loop.run_in_executor(
                None, self.gate.materialize, entry.reused
            )
            if hit is not None:
                # Temporal reuse: answer from the anchor's enhanced
                # frame — encode and write the R record (byte-identical
                # to a recompute for a delta of zero; the PNG encoder is
                # deterministic on the identical array). The downgrade
                # bit, if any, is inherited from the anchor frame.
                out, anchor_flags = hit
                flags = FLAG_REUSED | anchor_flags
                png = await loop.run_in_executor(
                    None, self.mgr.encode, out
                )
                await self._write_record(
                    KIND_REUSED, flags, entry.seq, png
                )
                self.reused += 1
                self.mgr.stats.record_stream_frame_reused()
                if trace.enabled():
                    # A distinct span name keeps reused frames out of
                    # the device stage in waternet-trace's per-stage
                    # table — they never touched a replica.
                    trace.record_span(
                        "frame_reuse", "serving", entry.t_read,
                        time.perf_counter(),
                        args={
                            "request_id": f"{self.req_id}/{entry.seq}",
                            "stream": self.sid,
                            "seq": entry.seq,
                            "downgraded": bool(flags & FLAG_DOWNGRADED),
                        },
                    )
                return
            # The decision's anchor never delivered (dropped or
            # errored before its turn): the cached output belongs to
            # an older scene, so replaying it would show the wrong
            # content. An honest drop instead.
            entry.dropped = "anchor"
        if entry.dropped is None and entry.error is None:
            try:
                out = await asyncio.wrap_future(entry.future)
            except DeadlineExpired:
                entry.dropped = "budget"
            except RequestCancelled:
                entry.dropped = (
                    "window" if getattr(
                        entry.future, "abandoned", False
                    ) else "cancelled"
                )
            except Exception as err:
                entry.error = f"{type(err).__name__}: {err}"
        if entry.error is not None:
            self.errors += 1
            await self._write_record(
                KIND_ERROR, 0, entry.seq,
                json.dumps({"error": entry.error}).encode(),
            )
            self._trace_frame(entry)
            return
        if entry.dropped is not None:
            self.mgr.stats.record_stream_drop(entry.dropped)
            if entry.dropped == "budget":
                self.out_of_budget += 1
            else:
                self.dropped += 1
            await self._write_record(
                KIND_DROP, 0, entry.seq,
                json.dumps({"reason": entry.dropped}).encode(),
            )
            self._trace_frame(entry)
            return
        served = getattr(entry.future, "tier", self.cfg.tier)
        flags = 0
        if served != self.cfg.tier:
            flags |= FLAG_DOWNGRADED
            self.downgraded += 1
            self.mgr.stats.record_stream_downgrade()
        if self.gate is not None:
            # Record the delivered output so this frame's reuse
            # children (gated while it was still in flight) can
            # materialize it — inheriting the downgrade bit, so a
            # browned-out anchor never masquerades as quality.
            self.gate.note_computed(entry.seq, out, flags)
        png = await loop.run_in_executor(None, self.mgr.encode, out)
        await self._write_record(KIND_FRAME, flags, entry.seq, png)
        span = time.perf_counter() - entry.t_read
        self.delivered += 1
        self.lat_s.append(span)
        if len(self.lat_s) > LATENCY_RESERVOIR:
            del self.lat_s[0]
        self.mgr.stats.record_stream_frame_delivered(span)
        self._trace_frame(entry, downgraded=bool(flags & FLAG_DOWNGRADED))

    async def run_writer(self) -> None:
        try:
            while True:
                while not self.entries:
                    if self.read_done or self.dead:
                        return
                    self.progress.clear()
                    await self.progress.wait()
                if self.dead:
                    return
                entry = self.entries[0]
                entry.delivering = True
                await self._deliver(entry)
                self.entries.popleft()
                self.space.set()
        except (ConnectionError, asyncio.IncompleteReadError):
            self.dead = True
            self.space.set()

    # -- lifecycle -----------------------------------------------------

    def summary(self) -> dict:
        return {
            "stream_id": self.sid,
            "frames_in": self.frames_in,
            "delivered": self.delivered,
            "reused": self.reused,
            "dropped": self.dropped,
            "out_of_budget": self.out_of_budget,
            "errors": self.errors,
            "downgraded": self.downgraded,
        }

    def p99_ms(self) -> float:
        return round(_percentile(sorted(self.lat_s), 0.99) * 1e3, 3)

    async def run(self) -> None:
        reader_task = asyncio.ensure_future(self.run_reader())
        try:
            await self.run_writer()
        finally:
            if not self.read_done:
                # The writer bailed (connection gone) while the reader
                # was still reading: the session is dead, not clean.
                self.dead = True
            self.space.set()
            self.progress.set()
            await reader_task
            self._abandon_pending()
        if not self.dead:
            try:
                await self._write_record(
                    KIND_END, 0, self.frames_in,
                    json.dumps(self.summary()).encode(),
                )
            except (ConnectionError, asyncio.IncompleteReadError):
                pass

    def _abandon_pending(self) -> None:
        """Disconnect cleanup: every queued frame of THIS session is
        abandoned (the batcher/redispatch paths drop them un-computed)
        and accounted as a disconnect drop — batch-mates from other
        sessions are untouched."""
        while self.entries:
            e = self.entries.popleft()
            if e.future is not None and not e.future.done():
                e.future.abandoned = True
            if e.dropped is None and e.error is None:
                self.mgr.stats.record_stream_drop("disconnect")
                self.dropped += 1
        self.space.set()


class StreamManager:
    """Admission + registry for stream sessions (one per server).

    Admission is the third rung of the degradation ladder: a NEW
    session is refused with 503 + Retry-After when ``max_streams``
    sessions are already open or the batcher queue sits at/past the
    admit watermark — established streams keep their windows, budgets,
    and (opted-in) brown-out; refusal never touches them. Decode and
    encode are injected callables (the front door's cv2 helpers) so
    this module never imports the server."""

    def __init__(
        self,
        batcher,
        stats,
        max_streams: int,
        window: int,
        admit_watermark: int,
        decode,
        encode,
        draining: threading.Event,
    ):
        self.batcher = batcher
        self.stats = stats
        self.max_streams = int(max_streams)
        self.window = int(window)
        self.admit_watermark = int(admit_watermark)
        self.decode = decode
        self.encode = encode
        self.draining = draining
        self._lock = threading.Lock()
        self._sessions: Dict[str, StreamSession] = {}  # guarded-by: self._lock
        self._next_id = 0  # guarded-by: self._lock
        stats.stream_probe = self._probe

    def active_count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def _probe(self) -> dict:
        """The live gauge ``stats.summary()`` reads (any thread)."""
        with self._lock:
            sessions = list(self._sessions.values())
        return {
            "active_streams": len(sessions),
            "per_session_p99_ms": {
                s.sid: s.p99_ms() for s in sessions
            },
        }

    def refusal(self) -> Optional[str]:
        """Why a NEW session cannot be admitted right now (None = admit).
        Counted by the caller via ``stats.record_stream_refused``."""
        if self.active_count() >= self.max_streams:
            return (
                f"stream limit reached ({self.max_streams} sessions open)"
            )
        if self.batcher.queue_depth() >= self.admit_watermark:
            return "pool saturated (queue at admission watermark)"
        return None

    async def handle(
        self, cfg: StreamConfig, reader, writer, request_id=None
    ) -> None:
        """Run one admitted session to completion (the front door has
        already validated tier/headers and written the response head)."""
        with self._lock:
            self._next_id += 1
            sid = f"s{self._next_id}"
            session = StreamSession(
                sid, self, cfg, reader, writer, request_id=request_id
            )
            self._sessions[sid] = session
        self.stats.record_stream_open()
        t_open = time.perf_counter() if trace.enabled() else None
        try:
            await session.run()
        finally:
            with self._lock:
                self._sessions.pop(sid, None)
            if t_open is not None:
                trace.record_span(
                    "stream_session", "serving", t_open,
                    time.perf_counter(),
                    args=dict(session.summary(),
                              request_id=session.req_id),
                )
