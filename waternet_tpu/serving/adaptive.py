"""Adaptive serving scheduler: load-aware coalescing + queue forecasting.

Two small, deterministic controllers (docs/SERVING.md "Adaptive
scheduling"):

* :class:`CoalesceController` — replaces the batcher's FIXED
  ``max_wait_ms`` hold with a per-(tier, bucket) EWMA arrival-rate
  estimator. ``max_wait_ms`` becomes a CAP: when the expected number of
  batch-mates inside that cap is below ``gain_threshold`` the window
  collapses to zero (an empty-queue request flushes immediately — its
  p50 drops by ~the cap), and as arrival rate rises the window grows
  linearly toward the cap (occupancy and throughput preserved under
  load). ``mode="fixed"`` reproduces the historical constant hold
  byte-for-byte in timing semantics. Per-request deadlines clamp the
  effective window in the batcher exactly as before — this class only
  decides the coalescing budget, never the clamp. The batcher adds one
  work-conserving refinement on top (``DynamicBatcher._window_for``):
  while every replica of a tier is busy, a shrunken window is extended
  back to the cap — flushing a partial bucket early cannot start its
  compute sooner (the batch would queue behind the pool anyway), it
  only locks in a slot-padded partial fill, so under saturation the
  adaptive dispatcher coalesces exactly like the fixed hold.

* :class:`QueueForecaster` — EWMA level + slope over sampled queue
  depth, with a Little's-law drain-time estimate against the SLO's p99
  objective: ``breach_depth = service_rate * objective_sec`` is the
  depth at which the queue alone eats the whole latency budget, and the
  slope gives an ETA to that depth. The fleet supervisor scales up on a
  *predicted* breach (before the burn-rate engine pages) and down on a
  sustained low forecast; both directions are hysteresis-gated
  (``up_sustain`` / ``down_sustain`` consecutive agreeing ticks) so
  sample noise cannot flap the fleet. Pure step API like
  :class:`~waternet_tpu.serving.fleet.FleetPolicy`: tests drive it with
  a fake clock, no sleeps.

Neither controller touches request bytes: outputs stay byte-identical
across modes — only WHEN batches form and WHEN workers scale changes.
"""

from __future__ import annotations

import math
import threading
import time
from typing import Dict, Optional, Tuple


class CoalesceController:
    """Per-(tier, bucket) effective coalescing window under a fixed cap.

    The dispatcher thread feeds arrivals (:meth:`observe_arrival`) and
    batch flushes (:meth:`observe_flush`) and reads the live window
    (:meth:`window_s`); the stats thread snapshots gauges
    (:meth:`eff_wait_ms`) — hence the lock.

    Controller math: each key keeps an EWMA arrival-rate estimate
    ``lam`` (req/s), updated from inter-arrival gaps with a time-decayed
    smoothing factor ``alpha = 1 - exp(-gap / tau_s)``. When reading,
    the estimate is clamped by the time since the last arrival
    (``lam_eff = min(lam, 1 / idle_gap)``) so a stale burst decays
    instead of holding the window open forever. The expected batch-mates
    inside the cap are ``E = lam_eff * max_wait_s``:

    * ``E < gain_threshold`` → window 0 (flush now: the wait would
      almost surely buy no batch-mate);
    * otherwise → ``window = max_wait_s * min(1, E / target_mates)`` —
      linear growth toward the cap as load rises.

    ``mode="fixed"`` short-circuits everything to the constant cap.
    """

    MODES = ("adaptive", "fixed")

    def __init__(
        self,
        max_wait_s: float,
        mode: str = "adaptive",
        gain_threshold: float = 0.5,
        target_mates: float = 3.0,
        tau_s: float = 0.5,
        clock=time.perf_counter,
    ):
        if mode not in self.MODES:
            raise ValueError(
                f"coalesce mode must be one of {self.MODES}, got {mode!r}"
            )
        if max_wait_s < 0:
            raise ValueError(f"max_wait_s must be >= 0, got {max_wait_s}")
        if gain_threshold <= 0 or target_mates <= 0 or tau_s <= 0:
            raise ValueError(
                "gain_threshold, target_mates and tau_s must be > 0"
            )
        self.mode = mode
        self.max_wait_s = float(max_wait_s)
        self.gain_threshold = float(gain_threshold)
        self.target_mates = float(target_mates)
        self.tau_s = float(tau_s)
        self._clock = clock
        self._lock = threading.Lock()
        # (tier, bucket) -> [ewma_rate_per_sec, t_last_arrival]
        self._rate: Dict[Tuple, list] = {}  # guarded-by: self._lock
        # tier -> EWMA batch fill fraction (gauge-only; the window is
        # driven by arrival rate so it reacts BEFORE occupancy moves)
        self._occupancy: Dict[str, float] = {}  # guarded-by: self._lock

    # -- feeds (dispatcher thread) -------------------------------------

    def observe_arrival(self, tier: str, bucket, now: Optional[float] = None) -> None:
        """One request admitted into (tier, bucket)'s pending list."""
        now = self._clock() if now is None else now
        key = (tier, bucket)
        with self._lock:
            st = self._rate.get(key)
            if st is None:
                # First arrival carries no rate information yet; it
                # anchors the inter-arrival clock.
                self._rate[key] = [0.0, now]
                return
            gap = max(now - st[1], 1e-6)
            alpha = 1.0 - math.exp(-gap / self.tau_s)
            st[0] += alpha * (1.0 / gap - st[0])
            st[1] = now

    def observe_flush(self, tier: str, fill: float) -> None:
        """One batch flushed for ``tier`` at ``fill`` = real/slots."""
        fill = min(max(float(fill), 0.0), 1.0)
        with self._lock:
            prev = self._occupancy.get(tier)
            self._occupancy[tier] = (
                fill if prev is None else prev + 0.2 * (fill - prev)
            )

    # -- reads ---------------------------------------------------------

    def _window_from(self, lam: float, t_last: float, now: float) -> float:
        """Pure window math from one key's snapshot (no lock held)."""
        idle = max(now - t_last, 1e-6)
        lam_eff = min(lam, 1.0 / idle)
        expected = lam_eff * self.max_wait_s
        if expected < self.gain_threshold:
            return 0.0
        return self.max_wait_s * min(1.0, expected / self.target_mates)

    def window_s(self, tier: str, bucket, now: Optional[float] = None) -> float:
        """Effective coalescing budget for (tier, bucket), in seconds —
        always within [0, max_wait_s]. Fixed mode: the cap, always."""
        if self.mode == "fixed":
            return self.max_wait_s
        now = self._clock() if now is None else now
        with self._lock:
            st = self._rate.get((tier, bucket))
            if st is None:
                return 0.0
            lam, t_last = st[0], st[1]
        return self._window_from(lam, t_last, now)

    def eff_wait_ms(self) -> Dict[str, float]:
        """Live per-tier effective window gauge (ms): the max over that
        tier's buckets — the budget the busiest bucket is running at.
        Fixed mode reports the cap for every tier seen."""
        now = self._clock()
        with self._lock:
            snap = [(k, st[0], st[1]) for k, st in self._rate.items()]
        out: Dict[str, float] = {}
        for (tier, _bucket), lam, t_last in snap:
            if self.mode == "fixed":
                w = self.max_wait_s
            else:
                w = self._window_from(lam, t_last, now)
            out[tier] = max(out.get(tier, 0.0), round(w * 1e3, 3))
        return out

    def occupancy(self) -> Dict[str, float]:
        """EWMA batch-fill gauge per tier (what flushes have looked like
        recently; bench's serve_adaptive line reports it)."""
        with self._lock:
            return {t: round(v, 4) for t, v in self._occupancy.items()}


class QueueForecaster:
    """EWMA level+slope queue-depth forecast with Little's-law breach ETA.

    Pure decision engine: :meth:`step` is called once per control tick
    with the clock, the observed aggregate queue depth, and the current
    service rate (completed requests/sec). It returns ``"scale_up"``,
    ``"scale_down"``, or None. All state is private to the calling
    thread (the fleet monitor) — no lock needed; gauges are snapshotted
    into plain floats the summary thread reads atomically.

    * level: ``L += alpha * (depth - L)`` with ``alpha`` derived from the
      tick gap and ``tau_sec``; slope is the EWMA of ``d(depth)/dt``.
    * ``breach_depth = max(service_rate, min_rate) * objective_sec`` —
      the depth whose Little's-law drain time alone equals the p99
      objective.
    * breach ETA: 0 if ``L >= breach_depth``; else
      ``(breach_depth - L) / slope`` when the slope is positive; else
      None (no breach on the horizon).
    * scale-up: ETA within ``horizon_sec`` for ``up_sustain``
      consecutive ticks. Scale-down: forecast depth at the horizon
      below ``down_frac * breach_depth`` for ``down_sustain``
      consecutive ticks. Any contrary tick resets its counter — the
      hysteresis that keeps noise from flapping the fleet.
    """

    def __init__(
        self,
        objective_ms: float,
        horizon_sec: float = 30.0,
        tau_sec: float = 5.0,
        up_sustain: int = 2,
        down_sustain: int = 6,
        down_frac: float = 0.25,
        min_rate: float = 0.5,
    ):
        if objective_ms <= 0:
            raise ValueError(f"objective_ms must be > 0, got {objective_ms}")
        if horizon_sec <= 0 or tau_sec <= 0:
            raise ValueError("horizon_sec and tau_sec must be > 0")
        if up_sustain < 1 or down_sustain < 1:
            raise ValueError("sustain counts must be >= 1")
        if not (0.0 < down_frac < 1.0):
            raise ValueError(f"down_frac must be in (0, 1), got {down_frac}")
        self.objective_sec = float(objective_ms) / 1e3
        self.horizon_sec = float(horizon_sec)
        self.tau_sec = float(tau_sec)
        self.up_sustain = int(up_sustain)
        self.down_sustain = int(down_sustain)
        self.down_frac = float(down_frac)
        self.min_rate = float(min_rate)
        self._t_last: Optional[float] = None
        self._level = 0.0
        self._slope = 0.0
        self._up_count = 0
        self._down_count = 0
        # Gauge snapshot (floats assigned whole — atomic reads for the
        # summary thread; the monitor thread is the only writer).
        self.forecast_depth = 0.0
        self.breach_eta_sec: Optional[float] = None

    def step(
        self, now: float, depth: float, service_rate: float
    ) -> Optional[str]:
        """One control tick. Returns a scale hint or None."""
        depth = max(float(depth), 0.0)
        if self._t_last is None:
            self._t_last = now
            self._level = depth
            self.forecast_depth = round(depth, 2)
            return None
        dt = max(now - self._t_last, 1e-6)
        self._t_last = now
        alpha = 1.0 - math.exp(-dt / self.tau_sec)
        inst_slope = (depth - self._level) / dt
        self._level += alpha * (depth - self._level)
        self._slope += alpha * (inst_slope - self._slope)

        rate = max(float(service_rate), self.min_rate)
        breach_depth = rate * self.objective_sec
        if self._level >= breach_depth:
            eta: Optional[float] = 0.0
        elif self._slope > 1e-9:
            eta = (breach_depth - self._level) / self._slope
        else:
            eta = None
        forecast = max(self._level + self._slope * self.horizon_sec, 0.0)
        self.forecast_depth = round(forecast, 2)
        self.breach_eta_sec = None if eta is None else round(eta, 2)

        if eta is not None and eta <= self.horizon_sec:
            self._up_count += 1
            self._down_count = 0
            if self._up_count >= self.up_sustain:
                self._up_count = 0
                return "scale_up"
            return None
        self._up_count = 0
        if forecast <= self.down_frac * breach_depth:
            self._down_count += 1
            if self._down_count >= self.down_sustain:
                self._down_count = 0
                return "scale_down"
        else:
            self._down_count = 0
        return None

    def block(self) -> dict:
        """The ``forecast`` gauge block for /stats + /metrics."""
        return {
            "depth": self.forecast_depth,
            "breach_eta_sec": self.breach_eta_sec,
            "horizon_sec": self.horizon_sec,
            "objective_ms": round(self.objective_sec * 1e3, 3),
        }


def empty_forecast_block() -> dict:
    """The all-None forecast block for fleets running without an armed
    SLO p99 objective — presence means 'not armed', not 'no queue'."""
    return {
        "depth": None,
        "breach_eta_sec": None,
        "horizon_sec": None,
        "objective_ms": None,
    }
