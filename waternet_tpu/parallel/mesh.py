"""Device mesh + sharding helpers.

The reference is strictly single-device (`/root/reference/train.py:238,247`;
no torch.distributed anywhere). The TPU-native scaling story instead:

* a 2-D logical mesh ``(data, spatial)`` over whatever devices exist —
  a single chip, a v4-8 slice, or a multi-host pod (``jax.devices()`` is
  already global under multi-host jax.distributed initialization);
* **data axis**: batch sharding for training. Params are replicated; XLA
  inserts the gradient ``psum`` over ICI automatically when the loss is
  jitted with these shardings (no hand-written collectives, no NCCL
  translation).
* **spatial axis**: H-dimension sharding for huge single images / frames —
  the FCN analog of sequence/context parallelism — implemented with
  explicit halo exchange in :mod:`waternet_tpu.parallel.spatial`.

Keep shardings coarse: one `NamedSharding` per argument, XLA does the rest.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
SPATIAL_AXIS = "spatial"


def make_mesh(
    n_data: Optional[int] = None,
    n_spatial: int = 1,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a (data, spatial) mesh. Defaults to all devices on the data axis."""
    devices = list(devices if devices is not None else jax.devices())
    if n_data is None:
        if len(devices) % n_spatial != 0:
            raise ValueError(
                f"{len(devices)} devices not divisible by n_spatial={n_spatial}"
            )
        n_data = len(devices) // n_spatial
    n = n_data * n_spatial
    if len(devices) < n:
        raise ValueError(
            f"mesh ({n_data} data x {n_spatial} spatial) needs {n} devices, "
            f"but only {len(devices)} are available"
        )
    grid = np.array(devices[:n]).reshape(n_data, n_spatial)
    return Mesh(grid, (DATA_AXIS, SPATIAL_AXIS))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the leading (batch) axis over the data axis."""
    return NamedSharding(mesh, P(DATA_AXIS))


def image_batch_sharding(mesh: Mesh) -> NamedSharding:
    """NHWC batches: batch over the data axis AND H over the spatial axis.

    With a spatial axis of size 1 this degenerates to plain batch sharding;
    with more, XLA's SPMD partitioner materializes the spatial split (conv
    halo exchanges, collective quantiles/pools) from the annotation alone.
    """
    return NamedSharding(mesh, P(DATA_AXIS, SPATIAL_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def spatial_sharding(mesh: Mesh) -> NamedSharding:
    """Shard the H axis (axis 1 of NHWC) over the spatial axis."""
    return NamedSharding(mesh, P(None, SPATIAL_AXIS))


def pad_to_multiple(batch: np.ndarray, multiple: int):
    """Pad the batch axis up to a multiple (repeat-edge); returns (arr, n_real)."""
    n = batch.shape[0]
    if n % multiple == 0:
        return batch, n
    pad = multiple - n % multiple
    reps = np.repeat(batch[-1:], pad, axis=0)
    return np.concatenate([batch, reps], axis=0), n
