"""Multi-host (multi-process) initialization and batch distribution.

The reference has no distributed backend at all (no torch.distributed /
NCCL / MPI anywhere — single `.to(device)` placement,
`/root/reference/train.py:247`). The TPU-native story needs no hand-rolled
backend either: on a multi-host pod slice,

1. every host calls :func:`initialize` (a thin, idempotent wrapper over
   ``jax.distributed.initialize`` — on TPU pods coordinator discovery is
   automatic from the TPU environment);
2. ``jax.devices()`` then returns the *global* device list, so the same
   ``make_mesh()`` + NamedSharding code that runs single-host runs
   pod-scale: XLA routes the gradient all-reduce over ICI within a slice
   and DCN across slices, chosen by the mesh axis ordering;
3. each host feeds only its local shard of the batch
   (:func:`local_batch_slice`), and `jax.make_array_from_process_local_data`
   assembles the global sharded array.

Single-host (including CI) is the degenerate case: process_count == 1 and
everything below is a no-op passthrough.
"""

from __future__ import annotations

import os
from typing import NamedTuple, Optional

import jax
import numpy as np

# ---------------------------------------------------------------------------
# Restart-context env contract (docs/RESILIENCE.md "Multi-process
# supervision"): the supervisor (waternet_tpu/resilience/supervisor.py)
# stamps these into each worker's environment, a fresh coordinator port
# and generation per relaunch; :func:`initialize` with no explicit args
# consumes them. Absent all of them, behavior is byte-identical to the
# historical single-process / TPU-auto-discovery path.
# ---------------------------------------------------------------------------
ENV_COORDINATOR = "WATERNET_COORDINATOR"
ENV_NUM_PROCESSES = "WATERNET_NUM_PROCESSES"
ENV_PROCESS_ID = "WATERNET_PROCESS_ID"
ENV_GENERATION = "WATERNET_GENERATION"
#: CPU rehearsal flag: gloo collectives + serialized dispatch (the PR-5
#: transport constraint — one collective stream per rank or gloo crashes
#: with ``op.preamble.length <= op.nbytes``).
ENV_CPU_GLOO = "WATERNET_CPU_GLOO"
#: Bounded coordinator-connect timeout (seconds) for explicit mode.
ENV_CONNECT_TIMEOUT = "WATERNET_CONNECT_TIMEOUT_SEC"

_CONTEXT_VARS = (ENV_COORDINATOR, ENV_NUM_PROCESSES, ENV_PROCESS_ID)


class RestartContext(NamedTuple):
    """One worker's identity within a supervised (possibly relaunched) job."""

    coordinator_address: str
    num_processes: int
    process_id: int
    generation: int


def restart_context(env=None) -> Optional[RestartContext]:
    """Parse the supervisor's env contract; None when absent.

    A *partial* contract (some of the three identity vars set, others not)
    is a wiring bug that would silently train N duplicate single-process
    runs — it raises, naming exactly what is set and what is missing.
    """
    env = os.environ if env is None else env
    present = {v: env.get(v) for v in _CONTEXT_VARS if env.get(v) is not None}
    if not present:
        return None
    if len(present) != len(_CONTEXT_VARS):
        missing = [v for v in _CONTEXT_VARS if v not in present]
        raise ValueError(
            f"partial multi-process restart context: {present} set but "
            f"{missing} missing — the supervisor must provide all of "
            f"{_CONTEXT_VARS}"
        )
    return RestartContext(
        coordinator_address=env[ENV_COORDINATOR],
        num_processes=int(env[ENV_NUM_PROCESSES]),
        process_id=int(env[ENV_PROCESS_ID]),
        generation=int(env.get(ENV_GENERATION, "0")),
    )


def generation(env=None) -> int:
    """The restart generation this process belongs to (0 unsupervised)."""
    env = os.environ if env is None else env
    return int(env.get(ENV_GENERATION, "0"))


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    connect_timeout_sec: Optional[float] = None,
) -> None:
    """Idempotent `jax.distributed.initialize` (no-op when single-process
    or already initialized). On TPU pods all arguments are discovered from
    the environment; set them explicitly only for CPU/GPU multi-process —
    or run under ``waternet-launch``, whose restart-context env vars
    (:func:`restart_context`) are consumed here, generation-aware: each
    relaunched generation re-initializes against its own fresh coordinator.

    Explicit-mode failures are bounded (``connect_timeout_sec``, default
    from ``WATERNET_CONNECT_TIMEOUT_SEC`` else jax's 300 s) and re-raised
    naming the coordinator, this process's id/count, the generation, and
    the env vars consulted — instead of a bare jax traceback after an
    unbounded wait.

    Must be called before any other jax API (anything that initializes the
    XLA backend makes `jax.distributed.initialize` impossible — so this
    deliberately avoids `jax.devices()` / `jax.process_count()` itself and
    checks the distributed client state directly).
    """
    # ``jax._src.distributed.global_state`` is a private internal used only
    # for the idempotence check; if a jax upgrade moves it, fall through to
    # ``jax.distributed.initialize`` and let its own "already initialized"
    # RuntimeError be handled below.
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return  # already initialized
    except (ImportError, AttributeError):  # pragma: no cover
        pass
    ctx = None
    if coordinator_address is None and num_processes is None:
        ctx = restart_context()  # partial contract raises here, loudly
        if ctx is not None:
            coordinator_address = ctx.coordinator_address
            num_processes = ctx.num_processes
            process_id = ctx.process_id
    explicit = coordinator_address is not None or num_processes is not None
    if explicit and os.environ.get(ENV_CPU_GLOO, "") in ("1", "true"):
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.config.update("jax_cpu_enable_async_dispatch", False)
    if connect_timeout_sec is None:
        timeout = float(os.environ.get(ENV_CONNECT_TIMEOUT, "300"))
    else:
        timeout = float(connect_timeout_sec)
    try:
        if explicit:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
                initialization_timeout=max(1, int(timeout)),
            )
        else:
            # TPU pod auto-discovery; fails benignly on plain single hosts.
            jax.distributed.initialize()
    except (RuntimeError, ValueError) as e:
        if "already initialized" in str(e).lower():
            return  # idempotence backstop when the private-state check above
            # was unavailable
        if explicit:
            # User asked for multi-process; failing silently would let every
            # host train an independent duplicate run. Name everything the
            # operator needs to debug the join.
            gen = ctx.generation if ctx is not None else generation()
            consulted = ", ".join(
                f"{v}={os.environ.get(v)!r}"
                for v in (*_CONTEXT_VARS, ENV_GENERATION, ENV_CPU_GLOO)
            )
            raise RuntimeError(
                f"multi-process init failed: process "
                f"{process_id}/{num_processes} could not join coordinator "
                f"{coordinator_address} within {timeout:.0f}s "
                f"(restart generation {gen}; {type(e).__name__}: {e}). "
                f"Env consulted: {consulted}"
            ) from e
        import sys

        print(
            f"[waternet_tpu] single-process mode ({type(e).__name__}: {e})",
            file=sys.stderr,
        )


def local_batch_slice(global_batch: int) -> slice:
    """The half-open index range of the global batch this host should load.

    Dataset indices are globally shuffled with the same seed on every host
    (deterministic Philox in `waternet_tpu.data.batching`), so slicing the
    order per host partitions the epoch without communication.
    """
    n, i = jax.process_count(), jax.process_index()
    per = global_batch // n
    rem = global_batch % n
    start = i * per + min(i, rem)
    return slice(start, start + per + (1 if i < rem else 0))


def global_sharded_batch(local_arr: np.ndarray, mesh, spec):
    """Assemble a globally-sharded jax.Array from this host's local shard."""
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local_arr
    )
