"""Multi-host (multi-process) initialization and batch distribution.

The reference has no distributed backend at all (no torch.distributed /
NCCL / MPI anywhere — single `.to(device)` placement,
`/root/reference/train.py:247`). The TPU-native story needs no hand-rolled
backend either: on a multi-host pod slice,

1. every host calls :func:`initialize` (a thin, idempotent wrapper over
   ``jax.distributed.initialize`` — on TPU pods coordinator discovery is
   automatic from the TPU environment);
2. ``jax.devices()`` then returns the *global* device list, so the same
   ``make_mesh()`` + NamedSharding code that runs single-host runs
   pod-scale: XLA routes the gradient all-reduce over ICI within a slice
   and DCN across slices, chosen by the mesh axis ordering;
3. each host feeds only its local shard of the batch
   (:func:`local_batch_slice`), and `jax.make_array_from_process_local_data`
   assembles the global sharded array.

Single-host (including CI) is the degenerate case: process_count == 1 and
everything below is a no-op passthrough.
"""

from __future__ import annotations

from typing import Optional

import jax
import numpy as np


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Idempotent `jax.distributed.initialize` (no-op when single-process
    or already initialized). On TPU pods all arguments are discovered from
    the environment; set them explicitly only for CPU/GPU multi-process.

    Must be called before any other jax API (anything that initializes the
    XLA backend makes `jax.distributed.initialize` impossible — so this
    deliberately avoids `jax.devices()` / `jax.process_count()` itself and
    checks the distributed client state directly).
    """
    # ``jax._src.distributed.global_state`` is a private internal used only
    # for the idempotence check; if a jax upgrade moves it, fall through to
    # ``jax.distributed.initialize`` and let its own "already initialized"
    # RuntimeError be handled below.
    try:
        from jax._src import distributed as _dist

        if getattr(_dist.global_state, "client", None) is not None:
            return  # already initialized
    except (ImportError, AttributeError):  # pragma: no cover
        pass
    explicit = coordinator_address is not None or num_processes is not None
    try:
        if explicit:
            jax.distributed.initialize(
                coordinator_address=coordinator_address,
                num_processes=num_processes,
                process_id=process_id,
            )
        else:
            # TPU pod auto-discovery; fails benignly on plain single hosts.
            jax.distributed.initialize()
    except (RuntimeError, ValueError) as e:
        if "already initialized" in str(e).lower():
            return  # idempotence backstop when the private-state check above
            # was unavailable
        if explicit:
            raise  # user asked for multi-process; failing silently would
            # let every host train an independent duplicate run
        import sys

        print(
            f"[waternet_tpu] single-process mode ({type(e).__name__}: {e})",
            file=sys.stderr,
        )


def local_batch_slice(global_batch: int) -> slice:
    """The half-open index range of the global batch this host should load.

    Dataset indices are globally shuffled with the same seed on every host
    (deterministic Philox in `waternet_tpu.data.batching`), so slicing the
    order per host partitions the epoch without communication.
    """
    n, i = jax.process_count(), jax.process_index()
    per = global_batch // n
    rem = global_batch % n
    start = i * per + min(i, rem)
    return slice(start, start + per + (1 if i < rem else 0))


def global_sharded_batch(local_arr: np.ndarray, mesh, spec):
    """Assemble a globally-sharded jax.Array from this host's local shard."""
    from jax.sharding import NamedSharding

    return jax.make_array_from_process_local_data(
        NamedSharding(mesh, spec), local_arr
    )
