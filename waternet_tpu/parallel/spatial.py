"""Spatial sharding: exact FCN forward with the image height split across
devices (halo exchange over ICI).

WaterNet has no sequence dimension — its long-context analog is *spatial
resolution*: the reference runs full-res 1080p video frames through the FCN
one at a time (`/root/reference/inference.py:268-283`). For images too large
for one chip's HBM (or to cut latency), we shard the H axis over the mesh's
``spatial`` axis and run the whole network on overlapping slabs, exchanging
halos like ring attention exchanges KV blocks.

Exactness argument:

* The network's total receptive-field radius is **13 rows**: the
  confidence-map trunk stacks 7/5/3/1/7/5/3/3 kernels
  (`/root/reference/waternet/net.py:12-43`) = 3+2+1+0+3+2+1+1 = 13; the
  refiner branches need only 6, and the gated fusion is pointwise.
* Interior slab boundaries: 13 rows of true neighbor data make every kept
  output row identical to the unsharded forward.
* **True image edges are subtler**: SAME convolution pads every *layer's
  input* with zeros, so feeding an edge shard 13 zero input rows is NOT
  equivalent (conv(0)+bias passes through ReLU and contaminates deeper
  layers). Instead each shard computes on a *window of true data* whose
  outer boundary coincides with the true image edge for edge shards — then
  XLA's SAME padding at the slab edge is exactly the unsharded model's
  behavior. Uniform SPMD shapes are kept by sliding the window (edge shards
  extend further inward) and cropping at a shard-dependent offset.

Mechanics (K = 13, slab S = H / n_shards, requires S >= 2K):

* every shard sends its first/last 2K rows to its neighbors (one
  ``lax.ppermute`` hop each way over ICI);
* shard i assembles ``[recv_top(2K) | core(S) | recv_bot(2K)]`` and takes a
  window of S + 2K rows starting at 2K (first shard: window = global rows
  [0, S+2K)), K (interior: [g-K, g+S+K)), or 0 (last: [g-2K, g+S));
* runs the full network on the window, then crops ``2K - start`` .. ``+S``.

Per-device compute overlap is 26 rows — negligible for the hundreds-of-rows
slabs this is built for; verified equal to the unsharded forward to float
tolerance in tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

try:  # jax >= 0.4.35 exports shard_map at top level ...
    from jax import shard_map
except ImportError:  # ... older builds only under experimental
    from jax.experimental.shard_map import shard_map

from waternet_tpu.parallel.mesh import SPATIAL_AXIS

# Receptive-field radius of WaterNet (see module docstring).
HALO = 13


def spatial_sharded_apply(module, mesh: Mesh):
    """Build a jitted forward running H-sharded over ``mesh``'s spatial axis.

    ``module`` is a Flax module (its ``.apply`` is used) or any callable
    ``fn(params, x, wb, ce, gc) -> out`` with the WaterNet receptive field —
    e.g. the int8 :func:`waternet_tpu.models.quant.quant_forward`, whose
    quantize/rescale steps are pointwise and so commute with the windowing.

    Returns ``fn(params, x, wb, ce, gc) -> out`` operating on full (global)
    NHWC arrays; the spatial axis size (n_shards) must divide H and each
    slab must have at least ``2 * HALO`` rows.
    """
    apply_fn = module.apply if hasattr(module, "apply") else module
    n_shards = mesh.shape[SPATIAL_AXIS]
    img_spec = P(None, SPATIAL_AXIS, None, None)
    k2 = 2 * HALO

    if n_shards == 1:
        def unsharded(params, x, wb, ce, gc):
            return apply_fn(params, x, wb, ce, gc)

        return jax.jit(unsharded)

    def local_fwd(params, x, wb, ce, gc):
        slab = x.shape[1]
        if slab < k2:
            raise ValueError(
                f"spatial slab of {slab} rows < 2*HALO={k2}; use fewer "
                f"spatial shards for this image height"
            )
        idx = lax.axis_index(SPATIAL_AXIS)
        down = [(i, i + 1) for i in range(n_shards - 1)]
        up = [(i + 1, i) for i in range(n_shards - 1)]
        start = jnp.where(idx == 0, k2, jnp.where(idx == n_shards - 1, 0, HALO))

        def window(t):
            recv_top = lax.ppermute(t[:, -k2:], SPATIAL_AXIS, down)
            recv_bot = lax.ppermute(t[:, :k2], SPATIAL_AXIS, up)
            c = jnp.concatenate([recv_top, t, recv_bot], axis=1)
            return lax.dynamic_slice_in_dim(c, start, slab + k2, axis=1)

        out = apply_fn(params, window(x), window(wb), window(ce), window(gc))
        return lax.dynamic_slice_in_dim(out, k2 - start, slab, axis=1)

    sharded = shard_map(
        local_fwd,
        mesh=mesh,
        in_specs=(P(), img_spec, img_spec, img_spec, img_spec),
        out_specs=img_spec,
    )
    return jax.jit(sharded)
