"""The public Python API: ``preprocess, postprocess, model = waternet(...)``.

Shape-compatible with the reference's torchhub contract
(`/root/reference/hubconf.py:37-96`): ``preprocess`` maps one uint8 HWC RGB
array to the 4-tuple ``(rgb, wb, he, gc)`` in exactly the positional order
the model consumes (`net.py:99` takes ``(x, wb, ce, gc)`` where ce=he), and
``postprocess`` maps the model output back to uint8. Differences, all
deliberate and TPU-idiomatic:

* tensors are NHWC jax arrays (not NCHW torch tensors);
* ``model`` is a jitted pure function closed over the params — it is also
  exposed unjitted via ``model.apply_fn`` / ``model.params`` for composition;
* no network download: weights resolve from an explicit path, the
  ``WATERNET_TPU_WEIGHTS`` env var, a local ``weights/`` dir, or a reference
  torch checkpoint (auto-converted via
  :mod:`waternet_tpu.utils.torch_port`); zero-egress environments are the
  norm on TPU pods, so missing weights raise with instructions instead of
  downloading.

Example::

    from waternet_tpu.hub import waternet
    preprocess, postprocess, model = waternet(pretrained=True)
    rgb = cv2.cvtColor(cv2.imread("example.png"), cv2.COLOR_BGR2RGB)
    rgb_t, wb_t, he_t, gc_t = preprocess(rgb)
    out = model(rgb_t, wb_t, he_t, gc_t)     # (1, H, W, 3) float32 in [0,1]
    out_im = postprocess(out)                # (1, H, W, 3) uint8
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from waternet_tpu.models import WaterNet
from waternet_tpu.ops import transform_np
from waternet_tpu.utils.checkpoint import load_weights
from waternet_tpu.utils.tensor import arr2ten, ten2arr


class JittedModel:
    """Callable wrapper pairing a jitted apply with its params.

    Keeps the reference's ``model(x, wb, ce, gc)`` call shape while exposing
    the functional pieces (``apply_fn``, ``params``) for jax composition.
    """

    def __init__(self, module: WaterNet, params):
        self.module = module
        self.params = params
        self.apply_fn = module.apply
        self._jitted = jax.jit(module.apply)

    def __call__(self, x, wb, ce, gc):
        return self._jitted(self.params, x, wb, ce, gc)


def resolve_weights(weights=None, search_dirs=(".", "weights")) -> dict | None:
    """Find and load WaterNet weights. Returns a param pytree or None.

    An explicitly named path that does not exist raises immediately —
    silently falling through to whatever checkpoint happens to be lying in
    ./weights would train/infer with the wrong weights.
    """
    def _load_strict(path: Path, origin: str) -> dict:
        if not path.exists():
            raise FileNotFoundError(f"{origin} path does not exist: {path}")
        if path.suffix == ".npz":
            return load_weights(path)
        if path.suffix in (".pt", ".pth"):
            from waternet_tpu.utils.torch_port import waternet_params_from_torch

            return waternet_params_from_torch(path)
        raise ValueError(
            f"{origin} path has unsupported suffix {path.suffix!r} "
            f"(expected .npz or .pt/.pth): {path}"
        )

    # Explicitly named paths (argument or env var) are strict: any problem
    # raises rather than silently falling back to checkpoints in ./weights.
    if weights is not None:
        return _load_strict(Path(weights), "weights")
    env = os.environ.get("WATERNET_TPU_WEIGHTS")
    if env:
        return _load_strict(Path(env), "WATERNET_TPU_WEIGHTS")

    candidates = []
    for d in search_dirs:
        d = Path(d)
        if d.is_dir():
            candidates.extend(sorted(d.glob("waternet_tpu-*.npz")))
            candidates.extend(sorted(d.glob("waternet_exported_state_dict*.pt")))
            # Broad fallback, excluding VGG19 perceptual-loss weight files
            # which share these dirs (see resolve_vgg_params).
            candidates.extend(
                p
                for pat in ("*.npz", "*.pt")
                for p in sorted(d.glob(pat))
                if not p.name.lower().startswith("vgg")
            )
    for c in candidates:
        if not c.exists():
            continue
        if c.suffix == ".npz":
            return load_weights(c)
        if c.suffix in (".pt", ".pth"):
            from waternet_tpu.utils.torch_port import waternet_params_from_torch

            return waternet_params_from_torch(c)
    return None


def waternet(
    pretrained: bool = True,
    weights=None,
    dtype=jnp.float32,
) -> Tuple[Callable, Callable, JittedModel]:
    """Build the (preprocess, postprocess, model) triple.

    Args:
        pretrained: load weights (from ``weights``/env/local dirs). If none
            are found, raises with pointers; pass ``pretrained=False`` for a
            randomly initialized model.
        weights: optional explicit path (.npz ours, or reference .pt).
        dtype: compute dtype for the model (bfloat16 recommended on TPU).
    """
    from waternet_tpu.utils.platform import ensure_platform

    ensure_platform()
    module = WaterNet(dtype=dtype)
    if pretrained:
        params = resolve_weights(weights)
        if params is None:
            raise FileNotFoundError(
                "No WaterNet weights found. Provide `weights=...`, set "
                "WATERNET_TPU_WEIGHTS, or place waternet_tpu-*.npz / the "
                "reference's waternet_exported_state_dict-*.pt in ./weights. "
                "(This framework does not download weights: TPU environments "
                "are commonly egress-less; fetch once and ship the file.)"
            )
    else:
        zeros = jnp.zeros((1, 32, 32, 3), jnp.float32)
        params = module.init(jax.random.PRNGKey(0), zeros, zeros, zeros, zeros)

    def preprocess(rgb_arr: np.ndarray):
        wb, gc, he = transform_np(rgb_arr)
        return arr2ten(rgb_arr), arr2ten(wb), arr2ten(he), arr2ten(gc)

    def postprocess(model_out):
        return ten2arr(model_out)

    return preprocess, postprocess, JittedModel(module, params)
