"""The public Python API: ``preprocess, postprocess, model = waternet(...)``.

Shape-compatible with the reference's torchhub contract
(`/root/reference/hubconf.py:37-96`): ``preprocess`` maps one uint8 HWC RGB
array to the 4-tuple ``(rgb, wb, he, gc)`` in exactly the positional order
the model consumes (`net.py:99` takes ``(x, wb, ce, gc)`` where ce=he), and
``postprocess`` maps the model output back to uint8. Differences, all
deliberate and TPU-idiomatic:

* tensors are NHWC jax arrays (not NCHW torch tensors);
* ``model`` is a jitted pure function closed over the params — it is also
  exposed unjitted via ``model.apply_fn`` / ``model.params`` for composition;
* no network download: weights resolve from an explicit path, the
  ``WATERNET_TPU_WEIGHTS`` env var, a local ``weights/`` dir, or a reference
  torch checkpoint (auto-converted via
  :mod:`waternet_tpu.utils.torch_port`); zero-egress environments are the
  norm on TPU pods, so missing weights raise with instructions instead of
  downloading.

Example::

    from waternet_tpu.hub import waternet
    preprocess, postprocess, model = waternet(pretrained=True)
    rgb = cv2.cvtColor(cv2.imread("example.png"), cv2.COLOR_BGR2RGB)
    rgb_t, wb_t, he_t, gc_t = preprocess(rgb)
    out = model(rgb_t, wb_t, he_t, gc_t)     # (1, H, W, 3) float32 in [0,1]
    out_im = postprocess(out)                # (1, H, W, 3) uint8
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from waternet_tpu.models import WaterNet
from waternet_tpu.ops import transform_np
from waternet_tpu.utils.checkpoint import load_weights
from waternet_tpu.utils.tensor import arr2ten, ten2arr


class JittedModel:
    """Callable wrapper pairing a jitted apply with its params.

    Keeps the reference's ``model(x, wb, ce, gc)`` call shape while exposing
    the functional pieces (``apply_fn``, ``params``) for jax composition.
    """

    def __init__(self, module: WaterNet, params):
        self.module = module
        self.params = params
        self.apply_fn = module.apply
        self._jitted = jax.jit(module.apply)

    def __call__(self, x, wb, ce, gc):
        return self._jitted(self.params, x, wb, ce, gc)


class JittedStudent:
    """Fast-tier counterpart of :class:`JittedModel`: the distilled CAN
    student's single-input call shape ``model(x)`` (raw RGB in [0, 1] ->
    enhanced RGB; no WB/GC/CLAHE variants to feed)."""

    def __init__(self, module, params):
        self.module = module
        self.params = params
        self.apply_fn = module.apply
        self._jitted = jax.jit(module.apply)

    def __call__(self, x):
        return self._jitted(self.params, x)


def waternet_student(
    weights, dtype=jnp.float32
) -> Tuple[Callable, Callable, JittedStudent]:
    """Build the fast tier's ``(preprocess, postprocess, model)`` triple
    alongside the teacher's (docs/SERVING.md "Quality tiers").

    ``weights`` must name a distilled student checkpoint explicitly (a
    ``train.py --distill`` product) — the implicit ./weights resolution
    is reserved for the teacher, so the two tiers can never silently
    swap checkpoints. The tree is validated against
    :class:`waternet_tpu.models.CANStudent` (width/depth inferred), with
    a named shape diff — and a loud tier-mismatch message when handed
    WaterNet weights. ``preprocess`` is just uint8 -> [0, 1] scaling:
    the student consumes raw RGB only.
    """
    from waternet_tpu.models import CANStudent
    from waternet_tpu.models.can import can_config_from_params
    from waternet_tpu.utils.platform import ensure_platform

    ensure_platform()
    if weights is None:
        raise FileNotFoundError(
            "waternet_student needs an explicit student checkpoint path "
            "(a train.py --distill product)"
        )
    params = resolve_weights(weights)
    width, depth = can_config_from_params(params)
    module = CANStudent(width=width, depth=depth, dtype=dtype)

    def preprocess(rgb_arr: np.ndarray):
        return arr2ten(rgb_arr)

    def postprocess(model_out):
        return ten2arr(model_out)

    return preprocess, postprocess, JittedStudent(module, params)


# The reference's pretrained checkpoint (`/root/reference/hubconf.py:5`,
# `inference.py:15-21`): the filename embeds the sha256 prefix that
# torch.hub's check_hash verifies; download_weights reproduces exactly that
# contract without torch.
DEFAULT_CKPT_URL = (
    "https://www.dropbox.com/s/j8ida1d86hy5tm4/"
    "waternet_exported_state_dict-daa0ee.pt?dl=1"
)


def download_weights(
    url: str = DEFAULT_CKPT_URL, dest_dir="weights", timeout: int = 60
) -> Path:
    """Opt-in pretrained-weight download with hash verification.

    Mirrors the reference's ``torch.hub.load_state_dict_from_url(...,
    check_hash=True)`` semantics (`/root/reference/inference.py:103-109`):
    the expected sha256 *prefix* is parsed from the ``-<hex>`` suffix of the
    URL's filename and the downloaded bytes must match it, else the file is
    discarded and the call raises. An existing file that already matches is
    reused without touching the network.

    Zero-egress TPU environments are this framework's default posture, so
    nothing calls this implicitly — it runs only via ``inference.py
    --download``, ``waternet(..., download=True)``, or a direct call.
    """
    import hashlib
    import re
    import urllib.parse
    import urllib.request

    fname = Path(urllib.parse.urlparse(url).path).name
    m = re.search(r"-([0-9a-f]{6,64})\.(?:pt|pth|npz)$", fname)
    if m is None:
        raise ValueError(
            f"cannot verify download: no -<sha256-prefix> suffix in {fname!r}"
        )
    expect = m.group(1)

    def _ok(path: Path) -> bool:
        digest = hashlib.sha256(path.read_bytes()).hexdigest()
        return digest.startswith(expect)

    dest_dir = Path(dest_dir)
    dest = dest_dir / fname
    if dest.exists():
        if _ok(dest):
            return dest
        raise RuntimeError(
            f"existing file {dest} fails its hash check (expected sha256 "
            f"prefix {expect}); refusing to overwrite or use it"
        )
    dest_dir.mkdir(parents=True, exist_ok=True)
    tmp = dest.with_suffix(dest.suffix + ".part")
    with urllib.request.urlopen(url, timeout=timeout) as r, open(tmp, "wb") as f:
        while True:
            chunk = r.read(1 << 20)
            if not chunk:
                break
            f.write(chunk)
    if not _ok(tmp):
        tmp.unlink()
        raise RuntimeError(
            f"downloaded file from {url} fails its hash check "
            f"(expected sha256 prefix {expect}); deleted"
        )
    tmp.rename(dest)
    return dest


def find_weights_path(search_dirs=(".", "weights")) -> Path | None:
    """Locate (but do not load) the implicit-resolution weight candidate."""
    candidates = []
    for d in search_dirs:
        d = Path(d)
        if d.is_dir():
            candidates.extend(sorted(d.glob("waternet_tpu-*.npz")))
            candidates.extend(sorted(d.glob("waternet_exported_state_dict*.pt")))
            # Broad fallback, excluding VGG19 perceptual-loss weight files
            # which share these dirs (see resolve_vgg_params).
            candidates.extend(
                p
                for pat in ("*.npz", "*.pt")
                for p in sorted(d.glob(pat))
                if not p.name.lower().startswith("vgg")
            )
    for c in candidates:
        if c.exists() and c.suffix in (".npz", ".pt", ".pth"):
            return c
    return None


def resolve_weights(weights=None, search_dirs=(".", "weights")) -> dict | None:
    """Find and load WaterNet weights. Returns a param pytree or None.

    An explicitly named path that does not exist raises immediately —
    silently falling through to whatever checkpoint happens to be lying in
    ./weights would train/infer with the wrong weights.
    """
    def _load_strict(path: Path, origin: str) -> dict:
        if not path.exists():
            raise FileNotFoundError(f"{origin} path does not exist: {path}")
        if path.suffix == ".npz":
            return load_weights(path)
        if path.suffix in (".pt", ".pth"):
            from waternet_tpu.utils.torch_port import waternet_params_from_torch

            return waternet_params_from_torch(path)
        raise ValueError(
            f"{origin} path has unsupported suffix {path.suffix!r} "
            f"(expected .npz or .pt/.pth): {path}"
        )

    # Explicitly named paths (argument or env var) are strict: any problem
    # raises rather than silently falling back to checkpoints in ./weights.
    if weights is not None:
        return _load_strict(Path(weights), "weights")
    env = os.environ.get("WATERNET_TPU_WEIGHTS")
    if env:
        return _load_strict(Path(env), "WATERNET_TPU_WEIGHTS")

    found = find_weights_path(search_dirs)
    return _load_strict(found, "discovered") if found is not None else None


def waternet(
    pretrained: bool = True,
    weights=None,
    dtype=jnp.float32,
    download: bool = False,
) -> Tuple[Callable, Callable, JittedModel]:
    """Build the (preprocess, postprocess, model) triple.

    Args:
        pretrained: load weights (from ``weights``/env/local dirs). If none
            are found, raises with pointers; pass ``pretrained=False`` for a
            randomly initialized model.
        weights: optional explicit path (.npz ours, or reference .pt).
        dtype: compute dtype for the model (bfloat16 recommended on TPU).
        download: opt in to fetching the reference's pretrained checkpoint
            (hash-verified, see :func:`download_weights`) when no local
            weights are found. Off by default: zero-egress posture.
    """
    from waternet_tpu.utils.platform import ensure_platform

    ensure_platform()
    module = WaterNet(dtype=dtype)
    if pretrained:
        params = resolve_weights(weights)
        if params is None and download:
            params = resolve_weights(download_weights())
        if params is None:
            raise FileNotFoundError(
                "No WaterNet weights found. Provide `weights=...`, set "
                "WATERNET_TPU_WEIGHTS, or place waternet_tpu-*.npz / the "
                "reference's waternet_exported_state_dict-*.pt in ./weights; "
                "or opt in to a hash-verified fetch with download=True "
                "(CLI: --download). Nothing downloads by default: TPU "
                "environments are commonly egress-less."
            )
    else:
        zeros = jnp.zeros((1, 32, 32, 3), jnp.float32)
        params = module.init(jax.random.PRNGKey(0), zeros, zeros, zeros, zeros)

    def preprocess(rgb_arr: np.ndarray):
        wb, gc, he = transform_np(rgb_arr)
        return arr2ten(rgb_arr), arr2ten(wb), arr2ten(he), arr2ten(gc)

    def postprocess(model_out):
        return ten2arr(model_out)

    return preprocess, postprocess, JittedModel(module, params)
