"""Checkpoint I/O.

Two artifact kinds, mirroring the reference's split between training
checkpoints (`/root/reference/train.py:308`) and the exported, content-hashed
inference weights (`/root/reference/inference.py:15-21`):

* **Weights-only**: a flat ``.npz`` of the param pytree (keys are
  ``/``-joined tree paths). Portable, torch-free, and hashable —
  :func:`export_weights` embeds the first 6 hex chars of the file's sha256 in
  the filename (``waternet_tpu-<hash>.npz``), preserving the reference's
  hash-in-filename integrity convention, and :func:`load_weights` verifies it.
* **Full train state** (params + optimizer state + step) via Orbax — see
  :mod:`waternet_tpu.training.train_state`. The reference only ever saved
  model weights, silently resetting Adam moments and the LR schedule on
  resume (`/root/reference/train.py:243-245`); we fix that.
"""

from __future__ import annotations

import hashlib
import re
from pathlib import Path

import jax
import numpy as np


def _flatten(params) -> dict:
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(params)[0]:
        key = "/".join(
            p.key if hasattr(p, "key") else str(p.idx) for p in path
        )
        flat[key] = np.asarray(leaf)
    return flat


def _unflatten(flat: dict) -> dict:
    tree: dict = {}
    for key, val in flat.items():
        node = tree
        parts = key.split("/")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = val
    return tree


def param_shapes(tree, with_dtype: bool = False) -> dict:
    """Flat ``{"a/b/c": shape}`` (or ``(shape, dtype)``) view of a nested
    param pytree — the shared vocabulary of every "does this checkpoint
    fit this model" check (trainer restore, serving hot reload)."""
    flat = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        key = "/".join(
            str(p.key) if hasattr(p, "key") else str(getattr(p, "idx", p))
            for p in path
        )
        shape = tuple(np.shape(leaf))
        if with_dtype:
            flat[key] = (shape, str(np.asarray(leaf).dtype))
        else:
            flat[key] = shape
    return flat


def params_mismatch_report(
    ckpt_params, model_params, check_dtype: bool = False
) -> str:
    """Human-readable diff of two param trees; empty string when they fit.

    The one validation path behind both the trainer's restore (shape
    check: ``TrainingEngine.restore``) and the serving front door's hot
    weight reload, which also checks dtypes (``check_dtype=True``) —
    its AOT executables were lowered against exact dtypes, so an fp32
    file cannot hot-swap into a bf16-param server.
    """
    ck = param_shapes(ckpt_params, with_dtype=check_dtype)
    mo = param_shapes(model_params, with_dtype=check_dtype)
    lines = []
    for key in sorted(set(ck) | set(mo)):
        if key not in ck:
            lines.append(f"  missing from checkpoint: {key} (model {mo[key]})")
        elif key not in mo:
            lines.append(f"  not in model: {key} (checkpoint {ck[key]})")
        elif ck[key] != mo[key]:
            what = "shape/dtype" if check_dtype else "shape"
            lines.append(
                f"  {what} mismatch at {key}: checkpoint {ck[key]} "
                f"vs model {mo[key]}"
            )
    return "\n".join(lines)


def save_weights(params, path) -> Path:
    """Save a param pytree as a flat npz — atomically.

    Write to a temp file in the same directory, then ``os.replace``: a crash
    mid-save can never leave a truncated ``last.npz`` that
    :func:`load_weights` chokes on (same protocol as :func:`export_weights`).
    The temp name keeps the ``.npz`` suffix because ``np.savez`` appends it
    otherwise.
    """
    import os

    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.parent / f".{path.name}.tmp.npz"
    try:
        np.savez(tmp, **_flatten(params))
        os.replace(tmp, path)
    finally:
        tmp.unlink(missing_ok=True)
    return path


def save_state_atomic(state_tree, path) -> Path:
    """Orbax-save a state pytree with atomic finalize (tmp + ``os.replace``).

    A preemption or crash mid-save leaves only a ``.tmp-*`` directory; the
    final path either doesn't exist or is a complete checkpoint. Multi-host:
    the Orbax save is process-collective (every process must call this with
    the same path — it synchronizes internally); only process 0 performs the
    rename, after the collective save has completed on all hosts.
    """
    import os
    import shutil

    import orbax.checkpoint as ocp

    path = Path(path).absolute()
    tmp = path.parent / f".tmp-{path.name}"
    path.parent.mkdir(parents=True, exist_ok=True)
    ocp.PyTreeCheckpointer().save(tmp, state_tree, force=True)
    if jax.process_index() == 0:
        if path.exists():
            shutil.rmtree(path)
        os.replace(tmp, path)
    return path


def load_weights(path) -> dict:
    """Load a flat npz back into a nested param pytree.

    If the filename carries a ``-<6 hex>`` content hash, verify it.
    """
    path = Path(path)
    m = re.search(r"-([0-9a-f]{6})\.npz$", path.name)
    if m:
        digest = hashlib.sha256(path.read_bytes()).hexdigest()[:6]
        if digest != m.group(1):
            raise ValueError(
                f"checkpoint hash mismatch for {path.name}: file hashes to {digest}"
            )
    with np.load(path) as data:
        return _unflatten({k: data[k] for k in data.files})


def export_weights(params, directory, stem: str = "waternet_tpu") -> Path:
    """Weights-only export with content hash in the filename."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    tmp = directory / f"{stem}-tmp.npz"
    np.savez(tmp, **_flatten(params))
    digest = hashlib.sha256(tmp.read_bytes()).hexdigest()[:6]
    final = directory / f"{stem}-{digest}.npz"
    tmp.rename(final)
    return final
