"""Platform selection guard.

On TPU-attached hosts a sitecustomize may import jax and register an
accelerator PJRT plugin before any user code runs; jax then initializes
*every* registered backend on first use, dialing the accelerator even when
the user asked for CPU (``JAX_PLATFORMS=cpu``). On a host where the tunnel
is absent or broken that first ``jax.devices()`` blocks forever.

:func:`ensure_platform` makes an explicit CPU request authoritative: when
``JAX_PLATFORMS`` (or ``WATERNET_TPU_PLATFORM``) is ``cpu``, the non-CPU
backend factories are deregistered before first backend init. Call it at
CLI entry, before any jax computation. No-op otherwise.
"""

from __future__ import annotations

import os


def ensure_platform() -> None:
    want = (
        os.environ.get("WATERNET_TPU_PLATFORM")
        or os.environ.get("JAX_PLATFORMS")
        or ""
    ).strip().lower()
    if want != "cpu":
        return
    import jax
    import jax._src.xla_bridge as xb

    # Keep core platforms registered (their names back MLIR lowering
    # registries); drop only experimental plugin factories like "axon".
    for name in list(xb._backend_factories):
        if name not in ("cpu", "tpu", "cuda", "rocm"):
            xb._backend_factories.pop(name, None)
    jax.config.update("jax_platforms", "cpu")
