"""Platform selection guard.

On TPU-attached hosts a sitecustomize may import jax and register an
accelerator PJRT plugin before any user code runs; jax then initializes
*every* registered backend on first use, dialing the accelerator even when
the user asked for CPU (``JAX_PLATFORMS=cpu``). On a host where the tunnel
is absent or broken that first ``jax.devices()`` blocks forever.

:func:`ensure_platform` makes an explicit CPU request authoritative: when
``JAX_PLATFORMS`` (or ``WATERNET_TPU_PLATFORM``) is ``cpu``, the non-CPU
backend factories are deregistered before first backend init. Call it at
CLI entry, before any jax computation. No-op otherwise.
"""

from __future__ import annotations

import os


def relay_stack_busy(states, port: int) -> bool:
    """Pure predicate over parsed TCP states ``[(local_port, remote_port,
    state_hex), ...]``: is any client ESTABLISHED into a port the relay
    stack currently LISTENs on? The ONE place the stack window is defined —
    bench.py's wait check and tools/relay_watch.py's launch gate both
    delegate here, so a grid change cannot desynchronize them. Lives in
    this stdlib-only module so the long-lived watcher never imports heavy
    bench code at poll time.

    The window starts AT the relay port: every observed stack service sits
    at a non-negative offset (8082/83/87, +10 repeating, compile :8103 =
    +21, device :8113 = +31). Reaching below (port-2 = 8080) would let an
    unrelated dev server with one client stall the bench for its whole
    wait budget."""
    stack = {
        lp for lp, _, st in states if st == "0A" and port <= lp < port + 38
    }
    return any(
        st == "01" and (lp in stack or rp in stack) for lp, rp, st in states
    )


def enable_compile_cache() -> None:
    """Enable jax's persistent compilation cache (default: ~/.cache/...).

    TPU compiles of the fused train step take 20-40s; the cache makes every
    later CLI invocation with the same shapes start instantly. Honors an
    existing ``JAX_COMPILATION_CACHE_DIR``; disable with
    ``WATERNET_TPU_NO_CACHE=1``.
    """
    if os.environ.get("WATERNET_TPU_NO_CACHE") == "1":
        return
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        return  # user already configured it via env
    import pathlib

    import jax

    cache_dir = pathlib.Path.home() / ".cache" / "waternet_tpu" / "xla"
    try:
        cache_dir.mkdir(parents=True, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
        # Cache everything, including sub-second compiles.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
    except Exception:
        pass  # cache is an optimization; never fail startup over it


def is_tpu_backend() -> bool:
    """True when the default JAX backend executes on TPU hardware.

    ``jax.default_backend() == "tpu"`` is NOT sufficient: tunnelled PJRT
    plugins register under their own platform name (e.g. ``"axon"``) while
    still compiling for and executing on a TPU (the plugin aliases the TPU
    MLIR lowering rules). Strategy choices that key on "is this a TPU"
    (MXU-friendly CLAHE modes, Pallas kernels) must use this helper, or
    they silently pick CPU-tuned paths on the real chip.
    """
    import jax

    backend = jax.default_backend()
    if backend == "tpu":
        return True
    if backend in ("cpu", "gpu", "cuda", "rocm"):
        return False
    # Opaque plugin platform: trust the device's own attributes first,
    # then the TPU-generation hint the tunnel environment exports.
    try:
        d = jax.devices()[0]
        if getattr(d, "platform", "") == "tpu":
            return True
        if "tpu" in getattr(d, "device_kind", "").lower():
            return True
    except Exception:
        pass
    return bool(os.environ.get("PALLAS_AXON_TPU_GEN"))


def ensure_platform() -> None:
    want = (
        os.environ.get("WATERNET_TPU_PLATFORM")
        or os.environ.get("JAX_PLATFORMS")
        or ""
    ).strip().lower()
    if want != "cpu":
        return
    import jax

    # Keep core platforms registered (their names back MLIR lowering
    # registries); drop only experimental plugin factories like "axon".
    # ``_backend_factories`` is a private jax internal; if a jax upgrade
    # moves it, degrade to the documented config knob alone rather than
    # failing every CLI at startup.
    try:
        import jax._src.xla_bridge as xb

        for name in list(xb._backend_factories):
            if name not in ("cpu", "tpu", "cuda", "rocm"):
                xb._backend_factories.pop(name, None)
    except (ImportError, AttributeError) as e:  # pragma: no cover
        import sys

        print(
            f"[waternet_tpu] could not deregister plugin backends "
            f"({type(e).__name__}: {e}); relying on jax_platforms=cpu only",
            file=sys.stderr,
        )
    jax.config.update("jax_platforms", "cpu")
