"""One-way bridges from the reference's torch checkpoints to our param trees.

Two artifact families exist upstream:

* WaterNet state_dicts — the exported pretrained checkpoint
  (``waternet_exported_state_dict-daa0ee.pt``, `/root/reference/inference.py:15`)
  and per-run ``last.pt`` training checkpoints (`/root/reference/train.py:308`).
  Keys: ``{cmg,wb_refiner,ce_refiner,gc_refiner}.conv{k}.{weight,bias}`` with
  OIHW conv weights.
* torchvision VGG19 state_dicts (for the perceptual loss,
  `/root/reference/train.py:254-267`). Keys ``features.{idx}.{weight,bias}``.

Conversion is pure tensor relayout (OIHW -> HWIO transpose); no torch model
code is executed. ``torch.load`` is used only for deserialization and is
imported lazily so the framework has zero torch dependency unless a torch
checkpoint is actually being converted.
"""

from __future__ import annotations

from pathlib import Path
from typing import Dict

import numpy as np

# Module name in our Flax tree -> torch prefix, and conv count per module.
_WATERNET_MODULES = {
    "cmg": ("cmg", 8),
    "wb_refiner": ("wb_refiner", 3),
    "ce_refiner": ("ce_refiner", 3),
    "gc_refiner": ("gc_refiner", 3),
}


def _load_torch_state_dict(path) -> Dict[str, np.ndarray]:
    import torch

    with open(path, "rb") as f:
        sd = torch.load(f, map_location="cpu", weights_only=True)
    if hasattr(sd, "state_dict"):
        sd = sd.state_dict()
    return {k: v.numpy() for k, v in sd.items()}


def _oihw_to_hwio(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(np.transpose(w, (2, 3, 1, 0)))


def waternet_params_from_torch(path) -> dict:
    """Convert a reference WaterNet state_dict file to our Flax param tree.

    Returns a pytree shaped like ``WaterNet().init(...)`` output:
    ``{"params": {module: {"Conv_i": {"kernel", "bias"}}}}``.
    """
    sd = _load_torch_state_dict(path)
    params: dict = {}
    for ours, (theirs, n_convs) in _WATERNET_MODULES.items():
        mod: dict = {}
        for i in range(n_convs):
            w = sd[f"{theirs}.conv{i + 1}.weight"]
            b = sd[f"{theirs}.conv{i + 1}.bias"]
            mod[f"Conv_{i}"] = {
                "kernel": _oihw_to_hwio(w).astype(np.float32),
                "bias": b.astype(np.float32),
            }
        params[ours] = mod
    return {"params": params}


def vgg19_params_from_torch(path) -> dict:
    """Convert a torchvision VGG19 state_dict (full model or features-only)
    into the param tree used by :class:`waternet_tpu.models.vgg.VGG19Features`.

    Accepts key prefixes ``features.N.*`` (torchvision vgg19) or ``model.N.*``
    (the reference's `PerceptualModel` wrapper, `/root/reference/train.py:254-263`).
    """
    sd = _load_torch_state_dict(path)
    convs = {}
    for key, val in sd.items():
        parts = key.split(".")
        if len(parts) != 3 or parts[2] not in ("weight", "bias"):
            continue
        if parts[0] not in ("features", "model"):
            continue
        idx = int(parts[1])
        convs.setdefault(idx, {})[parts[2]] = val
    if not convs:
        raise ValueError(f"no conv layers found in state dict at {path}")
    params: dict = {}
    for n, idx in enumerate(sorted(convs)):
        layer = convs[idx]
        params[f"Conv_{n}"] = {
            "kernel": _oihw_to_hwio(layer["weight"]).astype(np.float32),
            "bias": layer["bias"].astype(np.float32),
        }
    return {"params": params}


def maybe_find_torch_checkpoint(search_dirs) -> Path | None:
    """Look for a reference-style exported WaterNet .pt in the given dirs."""
    for d in search_dirs:
        d = Path(d)
        if not d.is_dir():
            continue
        for pattern in ("waternet_exported_state_dict*.pt", "last.pt"):
            hits = sorted(d.glob(pattern))
            if hits:
                return hits[0]
    return None
