"""Header-only image metadata (no pixel decode).

Lifted out of score.py so the serving layer's bucket auto-derivation
(``waternet_tpu.serving.bucketing.scan_shapes``) and the no-reference
scoring pass share one parser: both only need shapes to GROUP files, and
a full ``cv2.imread`` per file decodes gigabytes just to read two ints.
"""

from __future__ import annotations

#: EXIF orientation values whose decode involves a 90-degree rotation
#: (transpose / rotate-90 variants): the decoded H and W swap vs the SOF
#: header. 1-4 are identity/flip (dimensions preserved); 0 and >8 are
#: out-of-spec and treated as identity, matching decoders.
_EXIF_TRANSPOSED = (5, 6, 7, 8)


def _exif_orientation(app1_payload: bytes) -> "int | None":
    """Orientation (tag 0x0112) from a JPEG APP1/Exif segment payload
    (the bytes after the segment length), or None when absent/garbled.
    Only IFD0 is walked — that is where orientation lives per EXIF 2.x.
    """
    if not app1_payload.startswith(b"Exif\x00\x00"):
        return None
    tiff = app1_payload[6:]
    if len(tiff) < 8:
        return None
    if tiff[:2] == b"II":
        endian = "little"
    elif tiff[:2] == b"MM":
        endian = "big"
    else:
        return None
    if int.from_bytes(tiff[2:4], endian) != 42:
        return None
    off = int.from_bytes(tiff[4:8], endian)
    if off + 2 > len(tiff):
        return None
    n_entries = int.from_bytes(tiff[off : off + 2], endian)
    for i in range(n_entries):
        e = off + 2 + 12 * i
        if e + 12 > len(tiff):
            return None
        if int.from_bytes(tiff[e : e + 2], endian) == 0x0112:
            # Type SHORT, count 1: the value sits in the first two bytes
            # of the 4-byte value field.
            return int.from_bytes(tiff[e + 8 : e + 10], endian)
    return None


def image_shape(path) -> "tuple[int, int, int] | None":
    """``(h, w, 3)`` of the image **as a decoder produces it** — from the
    file header alone, no pixel decode.

    Reads <=64 bytes for PNG/BMP and the marker chain for JPEG. Returns
    ``None`` when the header can't be parsed so the caller falls back to
    a full decode; channel count is pinned to 3 because ``cv2.imread``'s
    default flag decodes to 3-channel BGR regardless of the file's own
    channel count. For JPEGs the EXIF orientation tag is honored the way
    cv2 honors it at decode time: orientations 5-8 (90-degree rotations)
    swap the SOF header's H and W, so portrait phone photos report their
    decoded portrait shape — the serving layer's bucket ladders
    (waternet_tpu/serving/bucketing.py) and score.py's shape grouping
    both depend on header shapes matching decoded shapes. score.py
    additionally re-queues any residual header/decode disagreement under
    the decoded shape as a safety net.
    """
    try:
        with open(path, "rb") as fh:
            head = fh.read(32)
            if head[:8] == b"\x89PNG\r\n\x1a\n" and head[12:16] == b"IHDR":
                w = int.from_bytes(head[16:20], "big")
                h = int.from_bytes(head[20:24], "big")
                return (h, w, 3) if h > 0 and w > 0 else None
            if head[:2] == b"BM" and len(head) >= 26:
                # BITMAPINFOHEADER: int32 width/height at 18/22; height<0
                # means top-down row order, same pixel dimensions.
                w = int.from_bytes(head[18:22], "little", signed=True)
                h = int.from_bytes(head[22:26], "little", signed=True)
                return (abs(h), abs(w), 3) if h != 0 and w > 0 else None
            if head[:2] == b"\xff\xd8":  # JPEG: walk markers to SOFn
                fh.seek(2)
                orientation = None
                while True:
                    b = fh.read(1)
                    if not b:
                        return None
                    if b != b"\xff":
                        continue
                    marker = fh.read(1)
                    while marker == b"\xff":  # legal fill bytes
                        marker = fh.read(1)
                    if not marker:
                        return None
                    m = marker[0]
                    # Standalone markers (no length field): TEM, RSTn, SOI.
                    if m == 0x01 or 0xD0 <= m <= 0xD8:
                        continue
                    if m == 0xD9:  # EOI before any SOF
                        return None
                    if m == 0xDA:
                        # SOS before any SOF: what follows is
                        # entropy-coded data where 0xFF bytes are
                        # stuffing/restart markers, not a marker chain —
                        # walking on can "find" a fake SOF and return a
                        # garbage shape. Give up; the caller falls back
                        # to a full decode.
                        return None
                    seg = fh.read(2)
                    if len(seg) < 2:
                        return None
                    seglen = int.from_bytes(seg, "big")
                    if seglen < 2:
                        return None
                    # SOF0..SOF15 carry the frame size; C4/C8/CC are
                    # DHT/JPG/DAC, not frame headers.
                    if 0xC0 <= m <= 0xCF and m not in (0xC4, 0xC8, 0xCC):
                        sof = fh.read(5)
                        if len(sof) < 5:
                            return None
                        h = int.from_bytes(sof[1:3], "big")
                        w = int.from_bytes(sof[3:5], "big")
                        if h <= 0 or w <= 0:
                            return None
                        if orientation in _EXIF_TRANSPOSED:
                            h, w = w, h  # decoder rotates 90 degrees
                        return (h, w, 3)
                    if m == 0xE1 and orientation is None:
                        # APP1: may carry the Exif orientation that cv2
                        # applies at decode time — read it so the shape
                        # we report is the shape a decode produces.
                        orientation = _exif_orientation(fh.read(seglen - 2))
                        continue
                    fh.seek(seglen - 2, 1)
    except OSError:
        return None
    return None
