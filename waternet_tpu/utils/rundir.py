"""Auto-numbered run directories.

Replicates the reference's savedir convention used by both training and
inference (`/root/reference/train.py:210-221`,
`/root/reference/inference.py:148-162`): numeric subdirs under a base output
dir, next run gets ``max + 1``; creation is deferred so early failures don't
leave empty dirs (`/root/reference/train.py:303-306`).
"""

from __future__ import annotations

from pathlib import Path


def next_run_dir(base: Path, name: str | None = None) -> Path:
    """Pick (but do not create) the run directory under ``base``."""
    base = Path(base)
    if name is not None:
        return base / name
    if not base.exists():
        return base / "0"
    nums = [
        int(p.stem) for p in base.glob("*") if p.is_dir() and p.stem.isdecimal()
    ]
    return base / (str(max(nums) + 1) if nums else "0")


def latest_run_dir(base: Path) -> Path | None:
    """The highest-numbered existing run dir under ``base``, or None."""
    dirs = run_dirs_desc(base)
    return dirs[0] if dirs else None


def run_dirs_desc(base: Path) -> list[Path]:
    """All numbered run dirs under ``base``, newest (highest) first.

    ``--resume auto`` walks this: when the latest run holds nothing
    restorable (no checkpoints yet, or all of them corrupt), resume falls
    back to earlier runs instead of crashing or silently starting over.
    """
    base = Path(base)
    if not base.exists():
        return []
    nums = sorted(
        (
            int(p.stem)
            for p in base.glob("*")
            if p.is_dir() and p.stem.isdecimal()
        ),
        reverse=True,
    )
    return [base / str(n) for n in nums]


def ensure_dir(path: Path) -> Path:
    path = Path(path)
    path.mkdir(parents=True, exist_ok=True)
    return path
