"""Array <-> network-input conversion utilities.

The reference keeps three near-identical copies of `arr2ten`/`ten2arr`
(`/root/reference/waternet/training_utils.py:11-43`,
`/root/reference/inference.py:26-52`, `/root/reference/hubconf.py:8-34`) that
scale uint8 [0,255] to float [0,1] and permute HWC->CHW for torch.

Here there is one copy and **no permute**: TPU/XLA prefers NHWC, and the
whole framework keeps images in NHWC end-to-end. The names are kept for
discoverability by reference users.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def arr2ten(arr: np.ndarray) -> jnp.ndarray:
    """uint8 (N)HWC [0,255] -> float32 NHWC [0,1]; adds batch dim if absent."""
    ten = jnp.asarray(arr, dtype=jnp.float32) / 255.0
    if ten.ndim == 3:
        ten = ten[None]
    return ten


def ten2arr(ten: jnp.ndarray) -> np.ndarray:
    """float NHWC [0,1] -> uint8 NHWC [0,255] (clipped), as host numpy."""
    arr = np.asarray(ten)
    arr = np.clip(arr, 0.0, 1.0)
    return (arr * 255).astype(np.uint8)
