"""Model layer (L3): Flax modules."""

from waternet_tpu.models.waternet import ConfidenceMapGenerator, Refiner, WaterNet

__all__ = ["ConfidenceMapGenerator", "Refiner", "WaterNet"]
