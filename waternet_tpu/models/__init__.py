"""Model layer (L3): Flax modules."""

from waternet_tpu.models.can import CANStudent
from waternet_tpu.models.waternet import ConfidenceMapGenerator, Refiner, WaterNet

__all__ = ["CANStudent", "ConfidenceMapGenerator", "Refiner", "WaterNet"]
