"""CAN student: a compact dilated context-aggregation network that maps
raw RGB directly to enhanced RGB — the fast serving tier.

Per *Fast Image Processing with Fully-Convolutional Networks* (Chen et
al., arXiv:1709.00643), a small fully-convolutional network whose 3x3
convs use exponentially growing dilations aggregates global context at a
tiny, resolution-linear cost and can approximate an entire image-
processing operator end-to-end. Here the approximated operator is the
WHOLE WaterNet quality pipeline — host/device WB+GC+CLAHE preprocessing
*plus* the 4-input gated-fusion forward — distilled into one raw-RGB-in
network with the *Perceptual Losses* recipe (arXiv:1603.08155) already
implemented in ``training/losses.py`` (``train.py --distill``,
docs/SERVING.md "Quality tiers").

Architecture (CAN24-shaped, width/depth configurable):

* ``depth`` 3x3 conv stages of ``width`` channels with dilations
  ``1, 2, 4, ..., 2^(depth-2), 1`` and LeakyReLU(0.2) — the paper's
  schedule: the receptive radius is the dilation sum (64 px at the
  default depth 7, covering the 112^2 training crops);
* a final linear 1x1 conv to 3 channels, added RESIDUALLY to the input:
  enhancement is a near-identity operator, so the student learns the
  correction, not the image.

Why this is the fast tier: the student needs **no WB/GC/CLAHE at all**
(the ~22 ms/step host-transform cost from the round-5 hardware
measurement simply disappears) and its conv forward is a small fraction
of the teacher's — asserted, not vibes: :func:`flops_ratio` computes
both sides analytically from the layer specs, and tests pin
``>= 5x`` at 112^2 (the default configuration measures ~34x).
"""

from __future__ import annotations

from typing import Any, List, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp

from waternet_tpu.models.waternet import _CMG_SPEC, _REFINER_SPEC

#: Default student shape: width 24, 7 dilated 3x3 stages (+ the 1x1 head).
DEFAULT_WIDTH = 24
DEFAULT_DEPTH = 7


def can_dilations(depth: int) -> List[int]:
    """The dilation schedule of the ``depth`` 3x3 stages:
    ``1, 2, 4, ..., 2^(depth-2)`` then a final dilation-1 stage (the
    paper's CAN layout). ``depth >= 2`` required — one growing stage and
    the closing dilation-1 stage are the minimum meaningful network."""
    if depth < 2:
        raise ValueError(f"CAN depth must be >= 2, got {depth}")
    return [2 ** i for i in range(depth - 1)] + [1]


def can_receptive_radius(depth: int = DEFAULT_DEPTH) -> int:
    """Receptive-field radius in pixels: each 3x3 stage at dilation d
    widens the field by d per side (the 1x1 head adds nothing). The
    fast tier's analog of ``serving.RECEPTIVE_RADIUS``: output pixels
    farther than this from a pad seam never see padded content."""
    return sum(can_dilations(depth))


class CANStudent(nn.Module):
    """Raw RGB in [0, 1] -> enhanced RGB, single input, fully
    convolutional (any H, W). ``dtype`` controls compute precision
    (params stay fp32 via Flax's default param_dtype); the residual add
    and output run in fp32 at the boundary, like WaterNet."""

    width: int = DEFAULT_WIDTH
    depth: int = DEFAULT_DEPTH
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x) -> jnp.ndarray:
        h = x.astype(self.dtype)
        for d in can_dilations(self.depth):
            h = nn.leaky_relu(
                nn.Conv(
                    self.width, (3, 3), kernel_dilation=(d, d),
                    padding="SAME", dtype=self.dtype,
                )(h),
                negative_slope=0.2,
            )
        delta = nn.Conv(3, (1, 1), dtype=self.dtype)(h)
        return (x.astype(jnp.float32) + delta.astype(jnp.float32))


# ----------------------------------------------------------------------
# FLOP accounting — the >=5x cost-reduction acceptance criterion is
# asserted against these, derived from the same layer specs the modules
# are built from (a spec change cannot silently drift the claim).
# ----------------------------------------------------------------------


def _conv_flops(h: int, w: int, cin: int, cout: int, k: int) -> int:
    """2 * MACs of one SAME kxk conv over an (h, w) plane."""
    return 2 * h * w * cin * cout * k * k


def can_forward_flops(
    h: int, w: int, width: int = DEFAULT_WIDTH, depth: int = DEFAULT_DEPTH
) -> int:
    """Per-image forward FLOPs of the student at (h, w)."""
    total = 0
    cin = 3
    for _ in can_dilations(depth):  # dilation does not change the MACs
        total += _conv_flops(h, w, cin, width, 3)
        cin = width
    total += _conv_flops(h, w, cin, 3, 1)
    return total


def waternet_forward_flops(h: int, w: int) -> int:
    """Per-image forward FLOPs of the WaterNet teacher at (h, w),
    derived from the module's own ``_CMG_SPEC`` / ``_REFINER_SPEC``."""
    total = 0
    cin = 12  # concat(x, wb, ce, gc)
    for feat, k in _CMG_SPEC:
        total += _conv_flops(h, w, cin, feat, k)
        cin = feat
    total += _conv_flops(h, w, cin, 3, 3)  # sigmoid head
    refiner = 0
    cin = 6  # concat(x, variant)
    for feat, k in _REFINER_SPEC:
        refiner += _conv_flops(h, w, cin, feat, k)
        cin = feat
    refiner += _conv_flops(h, w, cin, 3, 3)
    return total + 3 * refiner


def teacher_pipeline_flops(h: int, w: int) -> int:
    """Per-image FLOPs of the quality pipeline the student replaces.

    Counted as the WaterNet conv forward alone — deliberately
    conservative: the WB/GC/CLAHE preprocessing the student ALSO removes
    is byte-bound, not FLOP-bound (docs/MFU.md round 6: ~0.05 GFLOP but
    ~73 MB/batch), so adding its FLOPs would barely move this number
    while its real cost (the ~22 ms/step host transforms) is pure upside
    for the fast tier on top of the asserted ratio."""
    return waternet_forward_flops(h, w)


def flops_ratio(
    h: int = 112, w: int = 112,
    width: int = DEFAULT_WIDTH, depth: int = DEFAULT_DEPTH,
) -> float:
    """teacher-pipeline FLOPs / student FLOPs at (h, w) — the asserted
    cost-reduction factor (>= 5 is the acceptance floor; the default
    student measures ~34x)."""
    return teacher_pipeline_flops(h, w) / can_forward_flops(h, w, width, depth)


def train_flops_per_image(
    h: int, w: int,
    width: int = DEFAULT_WIDTH, depth: int = DEFAULT_DEPTH,
    distill: bool = False,
) -> int:
    """Per-image FLOPs of one training step: the standard fwd + 2x-bwd
    conv estimate (3x forward), plus one inference-only teacher forward
    under distillation. An analytic figure for the live MFU gauge —
    expect it below XLA's counted ``cost_analysis`` FLOPs (which include
    loss/metric/optimizer arithmetic); the gap in bench output is the
    cost-model delta, not a measurement error."""
    total = 3 * can_forward_flops(h, w, width, depth)
    if distill:
        total += waternet_forward_flops(h, w)
    return total


# ----------------------------------------------------------------------
# Param-tree validation — one vocabulary for "these weights are not a
# student" (serving engines, hub loaders, hot-reload style checks).
# ----------------------------------------------------------------------


def can_config_from_params(params) -> Tuple[int, int]:
    """Infer ``(width, depth)`` from a CAN param tree and validate it
    fits :class:`CANStudent` exactly, via the same
    ``params_mismatch_report`` vocabulary the trainer restore and the
    serving hot reload use. Raises ``ValueError`` with a named diff on
    mismatch — including the common operator error of pointing the fast
    tier at quality-tier (WaterNet) weights."""
    from waternet_tpu.utils.checkpoint import params_mismatch_report

    inner = params.get("params", params) if isinstance(params, dict) else None
    if not isinstance(inner, dict) or not inner:
        raise ValueError(
            "student weights are not a CAN param tree (empty or non-dict)"
        )
    names = set(inner)
    if {"cmg", "wb_refiner", "ce_refiner", "gc_refiner"} & names:
        raise ValueError(
            "these are quality-tier WaterNet weights (cmg/*_refiner "
            "branches), not a CAN student checkpoint — pass them to the "
            "quality engine (--weights), and point --student-weights at a "
            "distilled student (train.py --distill)"
        )
    if any(not n.startswith("Conv_") for n in names):
        raise ValueError(
            f"not a CAN student param tree: unexpected top-level keys "
            f"{sorted(n for n in names if not n.startswith('Conv_'))}"
        )
    depth = len(names) - 1  # the 1x1 head is the last conv
    try:
        width = int(inner["Conv_0"]["kernel"].shape[-1])
        dilations = can_dilations(depth)
    except (KeyError, AttributeError, IndexError, ValueError) as err:
        raise ValueError(f"malformed CAN student param tree: {err}") from None
    del dilations
    expect = CANStudent(width=width, depth=depth).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8, 8, 3), jnp.float32)
    )
    have = params if "params" in params else {"params": params}
    report = params_mismatch_report(have, expect)
    if report:
        raise ValueError(
            f"student weights do not fit CANStudent(width={width}, "
            f"depth={depth}):\n{report}"
        )
    return width, depth
