"""Post-training int8 quantization of WaterNet for inference.

A beyond-parity, TPU-first inference path the reference has no analog of:
the TPU's MXU runs int8 x int8 -> int32 at roughly twice the bf16 rate
(v5e: ~394 TOPS int8 vs ~197 TFLOP/s bf16) and int8 activations halve the
HBM bytes per conv — exactly the regime of full-resolution video
enhancement, which is this model's heaviest inference workload
(reference behavior being one fp32 frame at a time,
`/root/reference/inference.py:261-323`).

Scheme: static symmetric PTQ.

* Weights: per-output-channel symmetric int8 (scale = absmax/127 per
  channel), computed directly from the float checkpoint.
* Activations: per-conv-input symmetric int8 with scales calibrated as the
  running absmax over calibration batches (all model inputs live in [0,1],
  so scales are tightly bounded and synthetic calibration frames work —
  see :func:`default_calibration_inputs`).
* Each conv runs int8 x int8 -> int32 (``preferred_element_type``), then a
  float rescale ``s_in * s_w[c]`` + bias + activation. Concats/activations
  stay float; every conv re-quantizes its own input. XLA fuses the
  quantize/rescale elementwise chains into the conv epilogues.

The forward topology mirrors :class:`waternet_tpu.models.WaterNet`
(reference spec `/root/reference/waternet/net.py:7-108`): the 8-conv
confidence-map trunk with sigmoid head, three 3-conv refiner branches, and
the gated-fusion sum — expressed functionally over the quantized layer
pytree so the whole thing jits as one XLA program.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from waternet_tpu.models.waternet import _CMG_SPEC, _REFINER_SPEC

# Derived from the Flax module's own layer specs so trunk-depth changes in
# waternet.py can't silently drift from the quantized topology.
_CMG_ACTS = ["relu"] * len(_CMG_SPEC) + ["sigmoid"]
_REFINER_ACTS = ["relu"] * (len(_REFINER_SPEC) + 1)
_BRANCHES: Tuple[Tuple[str, int], ...] = (
    ("cmg", len(_CMG_ACTS)),
    ("wb_refiner", len(_REFINER_ACTS)),
    ("ce_refiner", len(_REFINER_ACTS)),
    ("gc_refiner", len(_REFINER_ACTS)),
)
_DN = ("NHWC", "HWIO", "NHWC")


def _layer_tree(params) -> Dict[str, List[dict]]:
    """Flax WaterNet params -> {branch: [ {kernel, bias}, ... ]}."""
    p = params["params"] if "params" in params else params
    return {
        name: [p[name][f"Conv_{i}"] for i in range(n)]
        for name, n in _BRANCHES
    }


def _conv_f32(layer, x, dilation: int = 1):
    y = lax.conv_general_dilated(
        x, layer["kernel"].astype(x.dtype), (1, 1), "SAME",
        rhs_dilation=(dilation, dilation),
        dimension_numbers=_DN,
    )
    return y + layer["bias"].astype(x.dtype)


def _conv_int8(qlayer, x, dilation: int = 1):
    """Quantize input with the calibrated scale, int8 conv, float rescale."""
    xq = jnp.clip(jnp.round(x / qlayer["s_in"]), -127, 127).astype(jnp.int8)
    y = lax.conv_general_dilated(
        xq, qlayer["wq"], (1, 1), "SAME",
        rhs_dilation=(dilation, dilation),
        dimension_numbers=_DN,
        preferred_element_type=jnp.int32,
    )
    return y.astype(jnp.float32) * qlayer["rescale"] + qlayer["bias"]


def _forward(layers, x, wb, ce, gc, conv, observe=None):
    """Shared WaterNet topology over a per-layer ``conv`` primitive.

    ``observe(branch, i, inp)`` (calibration hook) sees every conv input.
    """

    def run(branch, inp, acts):
        for i, act in enumerate(acts):
            if observe is not None:
                observe(branch, i, inp)
            out = conv(layers[branch][i], inp)
            inp = jax.nn.sigmoid(out) if act == "sigmoid" else jax.nn.relu(out)
        return inp

    cm = run("cmg", jnp.concatenate([x, wb, ce, gc], axis=-1), _CMG_ACTS)
    fused = 0.0
    for name, var, sl in (
        ("wb_refiner", wb, 0), ("ce_refiner", ce, 1), ("gc_refiner", gc, 2)
    ):
        refined = run(name, jnp.concatenate([x, var], axis=-1), _REFINER_ACTS)
        fused = fused + refined * cm[..., sl:sl + 1]
    return fused.astype(jnp.float32)


def float_forward(params, x, wb, ce, gc):
    """fp32 reference forward over the same functional topology (used to
    validate that the topology matches the Flax module exactly)."""
    return _forward(_layer_tree(params), x, wb, ce, gc, _conv_f32)


def calibration_stats(params, batches: Sequence[Tuple]) -> Dict[str, float]:
    """absmax of every conv input over the calibration batches.

    ``batches`` yields (x, wb, ce, gc) float arrays in [0, 1].
    """
    layers = _layer_tree(params)

    @jax.jit
    def one(x, wb, ce, gc):
        stats = {}

        def observe(branch, i, inp):
            stats[f"{branch}/{i}"] = jnp.max(jnp.abs(inp))

        _forward(layers, x, wb, ce, gc, _conv_f32, observe=observe)
        return stats

    # Dispatch every calibration batch before fetching anything: the
    # per-batch device_get serialized host and device per step (R003).
    pending = [
        one(jnp.asarray(x), jnp.asarray(wb), jnp.asarray(ce), jnp.asarray(gc))
        for x, wb, ce, gc in batches
    ]
    agg: Dict[str, float] = {}
    for stats in jax.device_get(pending):
        for k, v in stats.items():
            agg[k] = max(agg.get(k, 0.0), float(v))
    return agg


def default_calibration_inputs(n: int = 8, hw: int = 112, seed: int = 0):
    """Synthetic calibration batch: WB/GC/CLAHE variants of synthetic
    underwater frames — same input distribution shape ([0,1], enhanced
    variants included) the model sees at inference."""
    from waternet_tpu.data.synthetic import SyntheticPairs
    from waternet_tpu.ops import transform_np

    data = SyntheticPairs(n, hw, hw, seed=seed)
    xs, wbs, hes, gcs = [], [], [], []
    for i in range(n):
        raw, _ = data.load_pair(i)
        wb, gc, he = transform_np(raw)
        xs.append(raw)
        wbs.append(wb)
        hes.append(he)
        gcs.append(gc)
    f = lambda a: np.stack(a).astype(np.float32) / 255.0
    return [(f(xs), f(wbs), f(hes), f(gcs))]


def _quantize_layers(convs, stats, branch: str) -> List[dict]:
    """One branch's float conv layers -> int8 layer dicts, with input
    scales read from the calibration ``stats`` under ``{branch}/{i}``."""
    qconvs = []
    for i, layer in enumerate(convs):
        w = np.asarray(layer["kernel"], np.float32)  # (kh, kw, in, out)
        s_w = np.abs(w).reshape(-1, w.shape[-1]).max(axis=0) / 127.0
        s_w = np.maximum(s_w, 1e-12)
        wq = np.clip(np.round(w / s_w), -127, 127).astype(np.int8)
        s_in = max(stats[f"{branch}/{i}"], 1e-12) / 127.0
        qconvs.append(
            {
                "wq": jnp.asarray(wq),
                "bias": jnp.asarray(layer["bias"], jnp.float32),
                "s_in": jnp.float32(s_in),
                "rescale": jnp.asarray(s_in * s_w, jnp.float32),
            }
        )
    return qconvs


def quantize_waternet(params, calib_batches=None):
    """Float checkpoint -> int8 inference pytree.

    Returns {branch: [ {wq, bias, s_in, rescale}, ... ]} where ``wq`` is the
    per-output-channel int8 kernel, ``s_in`` the calibrated input scale and
    ``rescale = s_in * s_w`` the per-channel output dequantization factor.
    """
    if calib_batches is None:
        calib_batches = default_calibration_inputs()
    stats = calibration_stats(params, calib_batches)
    layers = _layer_tree(params)
    return {
        branch: _quantize_layers(convs, stats, branch)
        for branch, convs in layers.items()
    }


def quant_forward(qtree, x, wb, ce, gc):
    """int8 inference forward; jit this (or let InferenceEngine do it)."""
    return _forward(qtree, x, wb, ce, gc, _conv_int8)


# ----------------------------------------------------------------------
# CAN student (models/can.py) — the fast serving tier's int8 forward.
# Same scheme (static symmetric PTQ, per-output-channel weights,
# calibrated per-conv-input activation scales), over the student's
# dilated conv stack. Unlike WaterNet's [0,1]-bounded conv inputs, the
# student's hidden activations are signed (LeakyReLU) and unbounded, so
# calibration on representative frames is what pins the scales — the
# int8-vs-float error bound is tested on held-out UIEB-style crops.
# ----------------------------------------------------------------------


def _can_layers(params) -> List[dict]:
    """CAN student params -> ordered [ {kernel, bias}, ... ] (the last
    entry is the linear 1x1 head)."""
    p = params["params"] if "params" in params else params
    return [p[f"Conv_{i}"] for i in range(len(p))]


def _can_forward(layers, x, conv, observe=None):
    """Shared CAN topology over a per-layer ``conv`` primitive — must
    mirror :class:`waternet_tpu.models.can.CANStudent` exactly (pinned
    bit-identical in tests/test_can.py)."""
    from waternet_tpu.models.can import can_dilations

    h = x
    dilations = can_dilations(len(layers) - 1)
    for i, d in enumerate(dilations):
        if observe is not None:
            observe("can", i, h)
        h = jax.nn.leaky_relu(conv(layers[i], h, d), negative_slope=0.2)
    if observe is not None:
        observe("can", len(dilations), h)
    delta = conv(layers[-1], h, 1)
    return x.astype(jnp.float32) + delta.astype(jnp.float32)


def can_float_forward(params, x):
    """fp32 reference forward over the functional CAN topology (validated
    bit-identical to the Flax module in tests/test_can.py)."""
    return _can_forward(_can_layers(params), x, _conv_f32)


def can_calibration_stats(params, batches: Sequence) -> Dict[str, float]:
    """absmax of every student conv input over raw-RGB calibration
    batches (float arrays in [0, 1])."""
    layers = _can_layers(params)

    @jax.jit
    def one(x):
        stats = {}

        def observe(branch, i, inp):
            stats[f"{branch}/{i}"] = jnp.max(jnp.abs(inp))

        _can_forward(layers, x, _conv_f32, observe=observe)
        return stats

    # Same deferred-fetch discipline as calibration_stats (R003).
    pending = [one(jnp.asarray(x)) for x in batches]
    agg: Dict[str, float] = {}
    for stats in jax.device_get(pending):
        for k, v in stats.items():
            agg[k] = max(agg.get(k, 0.0), float(v))
    return agg


def default_can_calibration_inputs(n: int = 8, hw: int = 112, seed: int = 0):
    """Synthetic raw-RGB calibration frames in [0, 1] — the student's
    whole input distribution (it consumes no enhanced variants)."""
    from waternet_tpu.data.synthetic import SyntheticPairs

    data = SyntheticPairs(n, hw, hw, seed=seed)
    raw = np.stack([data.load_pair(i)[0] for i in range(n)])
    return [raw.astype(np.float32) / 255.0]


def quantize_can(params, calib_batches=None):
    """Student float checkpoint -> int8 inference pytree
    ``{"can": [ {wq, bias, s_in, rescale}, ... ]}`` (deterministic for a
    given (params, calibration) pair — pinned in tests/test_quant.py)."""
    if calib_batches is None:
        calib_batches = default_can_calibration_inputs()
    stats = can_calibration_stats(params, calib_batches)
    return {"can": _quantize_layers(_can_layers(params), stats, "can")}


def can_quant_forward(qtree, x):
    """Student int8 inference forward; jit this (or let StudentEngine)."""
    return _can_forward(qtree["can"], x, _conv_int8)
