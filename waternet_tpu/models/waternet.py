"""WaterNet: gated-fusion fully-convolutional underwater image enhancement.

A fresh NHWC Flax implementation with the same math as the reference's
torch modules (`/root/reference/waternet/net.py:7-108`):

* :class:`ConfidenceMapGenerator` — 8 convs over the concat of the raw image
  and its three enhanced variants (12 input channels), kernel sizes
  7/5/3/1/7/5/3/3 with widths 128/128/128/64/64/64/64/3, ReLU between, sigmoid
  at the end, split into three 1-channel confidence maps (`net.py:7-56`).
* :class:`Refiner` — per-variant 3-conv branch (7/5/3 kernels, widths
  32/32/3, ReLU each) over the concat of the raw image with one variant
  (`net.py:59-80`). Three independent instances (wb / ce / gc).
* :class:`WaterNet` — ``out = Σ refined_i ⊙ confidence_i`` (`net.py:99-108`).

TPU-first choices (deliberately NOT a translation):
* NHWC layout end-to-end (TPU conv-friendly), vs the reference's NCHW.
* ``dtype`` controls compute precision (bfloat16 recommended on TPU;
  params always fp32 via ``param_dtype``). The sigmoid/fusion runs in the
  compute dtype; cast back to fp32 at the output boundary.
* Fully shape-polymorphic: works at any H, W (the FCN property the reference
  relies on for full-resolution video inference, `net.py:84-90`).

~1.09 M parameters, matching the reference (tested).
"""

from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax.numpy as jnp

# (features, kernel) for the confidence-map trunk, reference `net.py:12-43`.
_CMG_SPEC = ((128, 7), (128, 5), (128, 3), (64, 1), (64, 7), (64, 5), (64, 3))
_REFINER_SPEC = ((32, 7), (32, 5))


class ConfidenceMapGenerator(nn.Module):
    """12-channel input -> three (N, H, W, 1) confidence maps."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, wb, ce, gc) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
        out = jnp.concatenate([x, wb, ce, gc], axis=-1).astype(self.dtype)
        for feat, k in _CMG_SPEC:
            out = nn.relu(
                nn.Conv(feat, (k, k), padding="SAME", dtype=self.dtype)(out)
            )
        out = nn.sigmoid(nn.Conv(3, (3, 3), padding="SAME", dtype=self.dtype)(out))
        return out[..., 0:1], out[..., 1:2], out[..., 2:3]


class Refiner(nn.Module):
    """concat(x, variant) 6-channel input -> refined 3-channel image."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, xbar) -> jnp.ndarray:
        out = jnp.concatenate([x, xbar], axis=-1).astype(self.dtype)
        for feat, k in _REFINER_SPEC:
            out = nn.relu(
                nn.Conv(feat, (k, k), padding="SAME", dtype=self.dtype)(out)
            )
        return nn.relu(nn.Conv(3, (3, 3), padding="SAME", dtype=self.dtype)(out))


class WaterNet(nn.Module):
    """Gated fusion of three refined enhancement branches.

    Call signature matches the reference positionally
    (`net.py:99`): ``model(x, wb, ce, gc)`` where ``ce`` is the
    histogram-equalized variant and ``gc`` the gamma-corrected one. All
    inputs are (N, H, W, 3) floats in [0, 1]; output likewise.
    """

    dtype: Any = jnp.float32

    def setup(self):
        self.cmg = ConfidenceMapGenerator(dtype=self.dtype)
        self.wb_refiner = Refiner(dtype=self.dtype)
        self.ce_refiner = Refiner(dtype=self.dtype)
        self.gc_refiner = Refiner(dtype=self.dtype)

    def __call__(self, x, wb, ce, gc) -> jnp.ndarray:
        wb_cm, ce_cm, gc_cm = self.cmg(x, wb, ce, gc)
        out = (
            self.wb_refiner(x, wb) * wb_cm
            + self.ce_refiner(x, ce) * ce_cm
            + self.gc_refiner(x, gc) * gc_cm
        )
        return out.astype(jnp.float32)
