"""VGG19 feature extractor for the perceptual loss.

The reference builds ``torchvision.models.vgg19(pretrained=True).features``
minus the final maxpool (`/root/reference/train.py:254-267`, duplicated at
`/root/reference/score.py:159-172`) — i.e. features through relu5_4 — and
compares 255-scaled feature maps of ImageNet-normalized images.

This is the NHWC Flax equivalent. Weights come from a one-time torchvision
state_dict port (:func:`waternet_tpu.utils.torch_port.vgg19_params_from_torch`);
in environments with no VGG weights available (zero-egress TPU pods), a
deterministic randomly-initialized network is used as a fallback feature
projector — random conv features still define a useful perceptual distance
(distance-preserving random projections), but results are not
reference-parity, so the trainer warns loudly.

VGG19 dominates the training FLOPs (~20 GFLOP/image at 112x112 vs ~0.1 for
WaterNet itself), so it runs in the same jitted step as the model, in the
compute dtype (bf16 on TPU keeps it on the MXU).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

# Conv widths; "M" = 2x2/stride-2 maxpool. torchvision vgg19 `features`
# topology; the final "M" (features[36]) is dropped per the reference cut.
_CFG = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
        512, 512, 512, 512, "M", 512, 512, 512, 512)

# NumPy on purpose: module-level jnp arrays would initialize the jax backend
# at import time, before CLIs can pick a platform.
IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)


class VGG19Features(nn.Module):
    """NHWC [0,1]-image -> relu5_4 feature map (N, H/16, W/16, 512)."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        out = x.astype(self.dtype)
        for v in _CFG:
            if v == "M":
                out = nn.max_pool(out, (2, 2), strides=(2, 2))
            else:
                out = nn.relu(
                    nn.Conv(v, (3, 3), padding="SAME", dtype=self.dtype)(out)
                )
        return out.astype(jnp.float32)


def imagenet_normalize(x: jnp.ndarray) -> jnp.ndarray:
    """Per-channel ImageNet normalization of [0,1] NHWC images
    (`/root/reference/train.py:111-116`)."""
    return (x - IMAGENET_MEAN) / IMAGENET_STD


def init_vgg_params(dtype=jnp.float32, seed: int = 42) -> dict:
    """Deterministic random init (the documented no-weights fallback)."""
    module = VGG19Features(dtype=dtype)
    return module.init(
        jax.random.PRNGKey(seed), jnp.zeros((1, 32, 32, 3), jnp.float32)
    )


def resolve_vgg_params(path=None, dtype=jnp.float32, verbose=True):
    """Load VGG19 weights for the perceptual loss, or fall back to random.

    Resolution order: explicit ``path`` (.npz native / .pt torchvision) ->
    ``WATERNET_TPU_VGG`` env var -> ``weights/vgg19*.{npz,pt}`` ->
    deterministic random init (with a loud warning: training still works —
    random conv features define a usable perceptual distance — but is not
    reference-parity).
    """
    import os
    import sys
    from pathlib import Path

    candidates = []
    if path is not None:
        candidates.append(Path(path))
    env = os.environ.get("WATERNET_TPU_VGG")
    if env:
        candidates.append(Path(env))
    for d in (Path("weights"), Path(".")):
        if d.is_dir():
            candidates.extend(sorted(d.glob("vgg19*.npz")))
            candidates.extend(sorted(d.glob("vgg19*.pt")))
            candidates.extend(sorted(d.glob("vgg19*.pth")))
    for c in candidates:
        if not c.exists():
            continue
        if c.suffix == ".npz":
            from waternet_tpu.utils.checkpoint import load_weights

            return load_weights(c)
        from waternet_tpu.utils.torch_port import vgg19_params_from_torch

        return vgg19_params_from_torch(c)
    if verbose:
        print(
            "[waternet_tpu] WARNING: no VGG19 weights found — using a "
            "deterministic random-feature perceptual loss. For "
            "reference-parity training, provide torchvision vgg19 weights "
            "via --vgg-weights / WATERNET_TPU_VGG.",
            file=sys.stderr,
        )
    return init_vgg_params(dtype=dtype)
