"""Checkpoint manager: atomic finalize, retention, validated auto-resume.

Layout under a run dir (``training/<n>/checkpoints/``)::

    step-0000000042/
        state/            Orbax tree (params + Adam moments + step)
        _COMPLETE.json    marker, written LAST; holds the resume metadata

The marker is the finalize: a checkpoint without it is, by construction,
half-written (the directory itself appears atomically via tmp +
``os.replace`` in :func:`waternet_tpu.utils.checkpoint.save_state_atomic`,
and the marker lands only after that rename). Readers therefore never need
to guess — :meth:`CheckpointManager.restore_latest_good` walks checkpoints
newest-first, skips unmarked ones, *test-restores* marked ones, and falls
back to the previous checkpoint when restore fails (truncated payloads,
torn volumes — the cases a marker alone can't catch).

Resume metadata records the exact dataloader position ``(epoch,
batch_index)`` plus the per-step metrics of the partial epoch and the
completed-epoch history, so a resumed run reproduces the uninterrupted
run's CSV artifacts bit-for-bit (batch composition is a pure function of
``(seed, epoch)`` via the shared Philox stream).

Retention keeps the last ``keep`` checkpoints by step plus the single best
by validation PSNR — the one you'd actually ship if the run dies for good.

Multi-host: ``save`` must be called by every process (the Orbax save inside
is collective); markers, pruning, and fault hooks run on process 0 only.
"""

from __future__ import annotations

import json
import re
import shutil
from pathlib import Path
from typing import NamedTuple, Optional

MARKER = "_COMPLETE.json"

#: A *finalized* step dir is exactly ``step-<digits>``. Anything else the
#: glob can catch — ``step-42.tmp`` / ``step-42.orbax-checkpoint-tmp-...``
#: staging conventions of a concurrently-finalizing peer generation — is
#: in-progress by construction and must never be scanned as a checkpoint.
_STEP_DIR = re.compile(r"step-\d+")


class Checkpoint(NamedTuple):
    path: Path  # the step-* directory
    step: int
    meta: dict

    @property
    def state_dir(self) -> Path:
        return self.path / "state"


class CheckpointManager:
    def __init__(self, root, keep: int = 3):
        self.root = Path(root)
        self.keep = max(1, int(keep))
        self._saves = 0  # ordinal for the fault-injection hook

    # ------------------------------------------------------------------
    # Write path
    # ------------------------------------------------------------------

    def save(self, engine, meta: Optional[dict] = None) -> Path:
        """Atomic checkpoint of ``engine``'s full train state + metadata."""
        import jax

        from waternet_tpu.resilience import faults
        from waternet_tpu.utils.checkpoint import save_state_atomic

        meta = dict(meta or {})
        step = int(meta.get("step", getattr(engine, "_host_step", 0)))
        meta["step"] = step
        final = self.root / f"step-{step:010d}"
        # Orbax saves into a tmp sibling; the whole step dir then appears
        # atomically, and the marker is written strictly after.
        tmp = self.root / f".tmp-step-{step:010d}"
        if jax.process_index() == 0:
            if tmp.exists():
                shutil.rmtree(tmp)
            if final.exists():  # re-save of the same step (epoch end after
                shutil.rmtree(final)  # an interval save): replace it
        save_state_atomic(jax.device_get(engine.state), tmp / "state")
        self._saves += 1
        if jax.process_index() == 0:
            import os

            os.replace(tmp, final)
            (final / MARKER).write_text(json.dumps(meta, indent=2))
            faults.after_checkpoint_save(final, self._saves)
            self.prune()
        return final

    def prune(self) -> None:
        """Keep the newest ``keep`` checkpoints + the best-val-PSNR one."""
        cks = self.checkpoints()
        if len(cks) <= self.keep:
            return
        keep = set(ck.path for ck in cks[-self.keep :])
        scored = [ck for ck in cks if ck.meta.get("val_psnr") is not None]
        if scored:
            best = max(scored, key=lambda ck: ck.meta["val_psnr"])
            keep.add(best.path)
        for ck in cks:
            if ck.path not in keep:
                shutil.rmtree(ck.path, ignore_errors=True)

    # ------------------------------------------------------------------
    # Read path
    # ------------------------------------------------------------------

    def checkpoints(self) -> list:
        """Complete (marker-finalized) checkpoints, ascending by step.

        Concurrency-tolerant by construction: a restarting peer generation
        may be finalizing (``*.tmp`` staging) or pruning (entries vanish
        between the glob and the marker read) this very directory. Staging
        names are rejected by pattern; a vanished/torn marker read raises
        ``OSError``/``JSONDecodeError`` and the entry is simply skipped —
        the marker protocol guarantees anything skipped was not (or no
        longer is) a complete checkpoint.
        """
        out = []
        try:
            entries = sorted(self.root.glob("step-*"))
        except OSError:  # root itself vanished mid-scan
            return out
        for p in entries:
            if not _STEP_DIR.fullmatch(p.name):
                continue  # in-progress staging dir, never a checkpoint
            # No is_dir/is_file pre-checks: they would only widen the
            # check-to-read race. The read itself is the check.
            try:
                meta = json.loads((p / MARKER).read_text())
            except (OSError, json.JSONDecodeError):
                continue  # unfinalized, torn, or vanished mid-scan
            out.append(Checkpoint(p, int(meta.get("step", -1)), meta))
        out.sort(key=lambda ck: ck.step)
        return out

    def restore_latest_good(self, engine) -> Optional[Checkpoint]:
        """Restore the newest checkpoint that actually loads.

        Integrity validation IS a restore attempt: a truncated or corrupt
        checkpoint raises inside ``engine.restore`` and we fall back to the
        previous one instead of crashing, warning loudly about each reject.
        A model-config MISMATCH is not corruption: every checkpoint of the
        run would fail identically and the fallback would silently retrain
        from scratch, so it propagates (with the shape report) instead.
        """
        import warnings

        from waternet_tpu.training.trainer import CheckpointMismatchError

        for ck in reversed(self.checkpoints()):
            if not ck.state_dir.is_dir():
                continue  # pruned by a peer between the scan and this
                # restore attempt: not corruption, just gone — skip quietly
            try:
                engine.restore(ck.state_dir)
                return ck
            except CheckpointMismatchError:
                raise
            except Exception as e:  # corrupt/truncated: fall back
                warnings.warn(
                    f"checkpoint {ck.path.name} failed to restore "
                    f"({type(e).__name__}: {e}); falling back to the "
                    "previous checkpoint",
                    RuntimeWarning,
                    stacklevel=2,
                )
        return None


def auto_resume(engine, training_base) -> Optional[dict]:
    """``--resume auto``: restore the newest good state across run dirs.

    Walks run dirs newest-first. Per run: managed checkpoints first (with
    corrupt-checkpoint fallback), then the legacy per-epoch ``state/`` dir.
    Returns the resume metadata dict (``{}`` for legacy states, which carry
    no position — training restarts its epoch loop with restored params,
    moments, and schedule), or ``None`` for a fresh start.
    """
    import warnings

    from waternet_tpu.training.trainer import CheckpointMismatchError
    from waternet_tpu.utils.rundir import run_dirs_desc

    for run in run_dirs_desc(training_base):
        mgr = CheckpointManager(run / "checkpoints")
        ck = mgr.restore_latest_good(engine)
        if ck is not None:
            print(f"Auto-resuming from {ck.path}")
            return ck.meta
        legacy = run / "state"
        if legacy.is_dir():
            try:
                engine.restore(legacy)
                print(f"Auto-resuming from legacy checkpoint {legacy}")
                return {}
            except CheckpointMismatchError:
                raise
            except Exception as e:
                warnings.warn(
                    f"legacy checkpoint {legacy} failed to restore "
                    f"({type(e).__name__}: {e}); trying earlier runs",
                    RuntimeWarning,
                    stacklevel=2,
                )
    return None
