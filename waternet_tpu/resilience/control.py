"""Per-epoch resilience bundle consulted by the trainer's epoch driver.

One object instead of four keyword arguments: the driver asks it (a) has a
preemption been requested, (b) is a divergence sentinel active, (c) is a
mid-epoch checkpoint due. train.py builds one per epoch with a checkpoint
callback that closes over the run's CheckpointManager and metric history.

Checkpoint cadence: ``every_steps`` counts dispatched steps (deterministic
across hosts — safe for the collective Orbax save); ``every_secs`` uses the
host monotonic clock, which is NOT synchronized across hosts, so train.py
refuses time-based cadence for multi-process runs (see docs/RESILIENCE.md).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Optional

from waternet_tpu.resilience.preemption import PreemptionGuard
from waternet_tpu.resilience.sentinel import DivergenceSentinel


@dataclasses.dataclass
class EpochControl:
    preemption: Optional[PreemptionGuard] = None
    sentinel: Optional[DivergenceSentinel] = None
    # checkpoint_cb(next_batch, partial_step_metrics) — set by train.py to
    # CheckpointManager.save with the epoch's position + metric carry.
    checkpoint_cb: Optional[Callable[[int, list], None]] = None
    every_steps: int = 0
    every_secs: float = 0.0
    # Supervision liveness (docs/RESILIENCE.md "Multi-process
    # supervision"): a HeartbeatWriter the driver ticks once per
    # dispatched step — pure host work riding the deferred-metrics loop
    # (no device fetch), throttled inside the writer.
    heartbeat: Optional[object] = None
    _steps_since_ckpt: int = 0
    _last_ckpt_time: float = dataclasses.field(default_factory=time.monotonic)

    def preempt_requested(self) -> bool:
        return self.preemption is not None and self.preemption.requested

    def checkpoint_due(self) -> bool:
        """Called once per completed step; latches the interval cadence."""
        if self.checkpoint_cb is None:
            return False
        self._steps_since_ckpt += 1
        if self.every_steps and self._steps_since_ckpt >= self.every_steps:
            return True
        if self.every_secs and (
            time.monotonic() - self._last_ckpt_time >= self.every_secs
        ):
            return True
        return False

    def checkpoint(self, next_batch: int, partial: list) -> None:
        self.checkpoint_cb(next_batch, partial)
        self._steps_since_ckpt = 0
        self._last_ckpt_time = time.monotonic()
