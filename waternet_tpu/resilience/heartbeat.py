"""Worker heartbeats + the per-worker health state machine.

The supervisor (:mod:`waternet_tpu.resilience.supervisor`) cannot tell a
worker that is *computing* from one that is *wedged* by looking at the
process table — both are alive. The trainer therefore emits a tiny
heartbeat record at step boundaries (:class:`HeartbeatWriter`, wired
through :class:`waternet_tpu.resilience.control.EpochControl`), and the
supervisor drives a per-worker state machine off record freshness
(:class:`WorkerHealth`):

    starting -> running -> late -> presumed-hung
                 \\------------------> dead / done   (process exited)

Design constraints, in order:

* **Step time unchanged.** A beat is a single ``time.monotonic()``
  comparison on the hot path; at most once per ``min_interval_sec`` it
  writes ~200 bytes via tmp + ``os.replace``. No device interaction at
  all — emission rides the trainer's deferred-metrics loop *without*
  fetching anything, so jaxlint's R003 (host sync in hot loop) stays
  structurally clean and the step's async dispatch is untouched.
* **Torn reads impossible.** ``os.replace`` makes each record atomic;
  readers (:func:`read_heartbeat`) additionally tolerate records that are
  missing, vanishing, or truncated mid-swap and simply report ``None``.
* **Restart-generation aware.** Every record carries the generation so a
  supervisor never mistakes a stale gen-N file for gen-N+1 progress; the
  supervisor also points each generation at a fresh directory.

The state machine is pure — ``observe(now, ...)`` takes explicit
timestamps — so thresholds, budgets, and transitions are unit-testable
with no processes and no sleeping (tests/test_supervisor.py).
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path
from typing import Optional

#: Supervisor -> worker contract: directory heartbeat records go in.
ENV_HEARTBEAT_DIR = "WATERNET_HEARTBEAT_DIR"
#: Emission throttle (seconds between records; beats inside the window are
#: a no-op comparison).
ENV_HEARTBEAT_SEC = "WATERNET_HEARTBEAT_SEC"
#: Fleet-router -> serving-worker identity contract
#: (waternet_tpu.serving.fleet): the slot index and restart generation a
#: worker writes into its heartbeat records, and the opaque worker id it
#: stamps on every response as ``X-Worker-Id`` so client ledgers can
#: split accounting by the worker that actually served.
ENV_WORKER_SLOT = "WATERNET_WORKER_SLOT"
ENV_WORKER_GENERATION = "WATERNET_WORKER_GENERATION"
ENV_WORKER_ID = "WATERNET_WORKER_ID"

# Health states (str, not enum: they go straight into JSON reports).
STARTING = "starting"  # launched, no heartbeat yet (compile / data warmup)
RUNNING = "running"
LATE = "late"  # no beat for late_sec: worth logging, not yet actionable
HUNG = "presumed-hung"  # no beat for hang_sec: treated as failed
DEAD = "dead"  # process exited nonzero (or exited while work remained)
DONE = "done"  # process exited 0


def heartbeat_path(directory, process_id: int) -> Path:
    return Path(directory) / f"worker-{int(process_id):03d}.json"


class HeartbeatWriter:
    """Throttled atomic heartbeat records for one worker process."""

    def __init__(
        self,
        path,
        min_interval_sec: float = 1.0,
        process_id: int = 0,
        generation: int = 0,
    ):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.min_interval_sec = float(min_interval_sec)
        self.process_id = int(process_id)
        self.generation = int(generation)
        self.epoch: Optional[int] = None  # stamped per epoch by train.py
        self._seq = 0
        self._last_mono = float("-inf")

    @classmethod
    def resolve(
        cls, directory=None, process_id: int = 0, generation: int = 0
    ) -> "HeartbeatWriter | None":
        """Build a writer from an explicit ``--heartbeat-dir`` or the
        supervisor's env contract; ``None`` (no heartbeating) when neither
        names a directory."""
        directory = directory or os.environ.get(ENV_HEARTBEAT_DIR)
        if not directory:
            return None
        interval = float(os.environ.get(ENV_HEARTBEAT_SEC, "1.0"))
        return cls(
            heartbeat_path(directory, process_id),
            min_interval_sec=interval,
            process_id=process_id,
            generation=generation,
        )

    def beat(self, step: int = 0, phase: str = "train", force: bool = False) -> bool:
        """Emit a record unless one was written < min_interval_sec ago.

        Hot-path cost when throttled: one monotonic read + compare. Returns
        whether a record was written (tests assert the throttle).
        """
        now = time.monotonic()
        if not force and now - self._last_mono < self.min_interval_sec:
            return False
        self._last_mono = now
        self._seq += 1
        record = {
            "pid": os.getpid(),
            "process_id": self.process_id,
            "generation": self.generation,
            "seq": self._seq,
            "step": int(step),
            "epoch": self.epoch,
            "phase": phase,
            "time": time.time(),
        }
        tmp = self.path.with_name(self.path.name + ".tmp")
        tmp.write_text(json.dumps(record))
        os.replace(tmp, self.path)
        return True


def read_heartbeat(path) -> Optional[dict]:
    """Latest record at ``path``, or None (missing / mid-swap / torn)."""
    try:
        return json.loads(Path(path).read_text())
    except (OSError, json.JSONDecodeError, ValueError):
        return None


class WorkerHealth:
    """Freshness-driven health state machine for one worker.

    Pure: every input (wall-clock ``now``, last heartbeat time, exit code)
    is an explicit argument to :meth:`observe`, so every transition is
    unit-testable without processes or sleeps. The supervisor feeds it
    ``record["time"]`` from :func:`read_heartbeat` (same machine, same
    clock) and ``Popen.poll()``.

    A worker that exits is terminal (``done``/``dead``) regardless of
    heartbeat age. Until the first *live-phase* beat (``live_phase``,
    default ``"train"`` for trainer gangs, ``"serve"`` under the fleet
    router), only ``startup_grace_sec`` (measured from launch) can
    declare a hang —
    that window legitimately holds the jax import, the coordinator join,
    checkpoint restore, and the cold compile, announced only by
    startup-phase beats. From the first train beat on, record freshness
    drives ``running -> late -> presumed-hung`` via ``late_sec`` /
    ``hang_sec``. ``late`` is an observability state only: the
    supervisor logs it but acts solely on ``presumed-hung`` / ``dead``.
    """

    def __init__(
        self,
        late_sec: float,
        hang_sec: float,
        startup_grace_sec: float,
        started_at: float,
        live_phase: str = "train",
    ):
        if not late_sec <= hang_sec:
            raise ValueError(f"late_sec {late_sec} must be <= hang_sec {hang_sec}")
        self.late_sec = float(late_sec)
        self.hang_sec = float(hang_sec)
        self.startup_grace_sec = float(startup_grace_sec)
        self.started_at = float(started_at)
        # Which beat phase proves the worker reached steady state: "train"
        # for trainer gangs (the original machine), "serve" for the fleet
        # router's serving workers. Until the first live-phase beat, only
        # the startup grace can declare a hang — same reasoning, different
        # warmup (AOT compile + bucket warm instead of restore + step one).
        self.live_phase = str(live_phase)
        self.state = STARTING
        self.last_beat: Optional[float] = None
        self.first_step: Optional[int] = None
        self.last_step: Optional[int] = None
        self.exit_code: Optional[int] = None

    def note_beat(self, record: dict) -> None:
        """Fold a heartbeat record in (before calling :meth:`observe`)."""
        t = float(record.get("time", 0.0))
        if self.last_beat is None or t > self.last_beat:
            self.last_beat = t
            step = int(record.get("step", 0))
            # first_step anchors "where this generation resumed": the first
            # *live-phase* beat carries the first post-warmup step, while
            # the startup beat is step 0 by construction and would pollute
            # it.
            if self.first_step is None and record.get("phase") == self.live_phase:
                self.first_step = step
            if self.last_step is None or step > self.last_step:
                self.last_step = step

    def observe(self, now: float, exit_code: Optional[int] = None) -> str:
        """Advance the state machine; returns the (possibly new) state."""
        if self.state in (DONE, DEAD):
            return self.state  # terminal
        if exit_code is not None:
            self.exit_code = int(exit_code)
            self.state = DONE if exit_code == 0 else DEAD
            return self.state
        if self.last_beat is None or self.first_step is None:
            # Between launch and the first *train-step* beat sit the jax
            # import, the coordinator join, checkpoint restore, and the
            # cold train-step compile — with only startup-phase beats in
            # between. Only the startup grace bounds this window: arming
            # hang_sec off the startup beat false-triggers on any compile
            # or restore longer than a few step times (observed as a
            # resumed generation "hanging" mid-restore, the supervisor
            # then draining perfectly healthy workers).
            if now - self.started_at >= self.startup_grace_sec:
                self.state = HUNG
            return self.state
        age = now - self.last_beat
        if age >= self.hang_sec:
            self.state = HUNG
        elif age >= self.late_sec:
            self.state = LATE
        else:
            self.state = RUNNING
        return self.state

    @property
    def failed(self) -> bool:
        return self.state in (HUNG, DEAD)

    def summary(self) -> dict:
        return {
            "state": self.state,
            "exit_code": self.exit_code,
            "first_step": self.first_step,
            "last_step": self.last_step,
        }
