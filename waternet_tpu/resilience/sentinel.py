"""Divergence sentinel: bounded NaN/Inf containment for the train loop.

A single non-finite loss step poisons params *and* Adam moments, and with
the repo's deferred-metrics fetch (metrics are pulled once per epoch) an
unguarded run can burn a whole epoch of TPU time training garbage. The
sentinel folds finite checks into that deferred fetch: the epoch driver
verifies pending metrics every ``window`` steps (keeping the async pipeline
``window`` deep instead of fully epoch-deep), and on the first non-finite
value rolls the engine back to the snapshot taken at the last verified
boundary, replays the verified-good prefix (bit-identical — batches, rng
folds, and augment draws are pure functions of (seed, epoch, batch index)),
skips the offending batch, and re-runs the tail. Skips are bounded:
exceeding ``max_skips`` in one epoch raises :class:`DivergenceError`
because at that point the run is diverging, not hitting a stray batch.

Multi-host: decisions are made from replicated metric values, so every
process computes the same first-bad index and takes the same rollback path.
"""

from __future__ import annotations

import dataclasses


class DivergenceError(RuntimeError):
    """Too many non-finite steps in one epoch: the run is diverging."""


@dataclasses.dataclass
class DivergenceSentinel:
    """Counters + policy; the replay mechanics live in the epoch driver."""

    window: int = 16  # steps between deferred finite checks (pipeline depth)
    max_skips: int = 8  # per-epoch skip budget before declaring divergence
    skipped: int = 0
    rollbacks: int = 0

    def begin_epoch(self) -> None:
        self.skipped = 0
        self.rollbacks = 0

    def note_skip(self, batch_index: int) -> None:
        self.rollbacks += 1
        self.skipped += 1
        if self.skipped > self.max_skips:
            raise DivergenceError(
                f"skipped {self.skipped} non-finite steps this epoch "
                f"(budget {self.max_skips}); last at batch {batch_index}. "
                "The run is diverging — lower the LR or inspect the data."
            )

    @staticmethod
    def first_bad(values: list) -> int | None:
        """Index of the first per-step metrics dict with a non-finite value."""
        import math

        for i, m in enumerate(values):
            if any(not math.isfinite(v) for v in m.values()):
                return i
        return None
