"""Fault tolerance for long training runs.

Production TPU fleets preempt VMs, feed corrupt inputs, and NaN out
multi-day runs; at the reference's 400-epoch horizon (PAPER.md) losing a
run to any of those is the dominant failure mode. This package holds the
pieces train.py wires through the trainer, checkpoint layer, and data
pipelines:

* :mod:`preemption` — SIGTERM/SIGINT -> checkpoint-at-next-step-boundary
  (:class:`PreemptionGuard`, :class:`Preempted`);
* :mod:`manager` — atomic, marker-finalized checkpoints with retention and
  validated ``--resume auto`` fallback (:class:`CheckpointManager`,
  :func:`auto_resume`);
* :mod:`sentinel` — non-finite loss detection with rollback to a last-good
  snapshot and bounded batch-skip (:class:`DivergenceSentinel`);
* :mod:`control` — the per-epoch bundle the trainer's epoch driver consults
  at step boundaries (:class:`EpochControl`);
* :mod:`faults` — the deterministic fault-injection harness the resilience
  tests drive (env var ``WATERNET_FAULTS`` or programmatic plans);
* :mod:`heartbeat` — step-boundary liveness records + the per-worker
  health state machine (:class:`HeartbeatWriter`, :class:`WorkerHealth`);
* :mod:`supervisor` — the ``waternet-launch`` gang supervisor: spawn N
  train.py workers, detect crash/hang/preemption via heartbeats, drain
  survivors, and relaunch generations that resume from the latest
  complete checkpoint (:class:`Supervisor`).

Everything here is multi-host-aware: checkpoint saves stay process-collective
(each process calls them; process 0 alone touches the filesystem markers),
and rollback/skip decisions are pure functions of replicated metric values,
so every process takes the same branch. See docs/RESILIENCE.md.
"""

from waternet_tpu.resilience.control import EpochControl
from waternet_tpu.resilience.heartbeat import HeartbeatWriter, WorkerHealth
from waternet_tpu.resilience.manager import CheckpointManager, auto_resume
from waternet_tpu.resilience.preemption import Preempted, PreemptionGuard
from waternet_tpu.resilience.sentinel import DivergenceError, DivergenceSentinel

__all__ = [
    "CheckpointManager",
    "DivergenceError",
    "DivergenceSentinel",
    "EpochControl",
    "HeartbeatWriter",
    "Preempted",
    "PreemptionGuard",
    "WorkerHealth",
    "auto_resume",
]
