"""Elastic multi-process training: a local gang supervisor.

Concurrency note (threadlint): this module is deliberately
single-threaded — isolation comes from *processes* (``subprocess.Popen``
+ heartbeat files), so there are no locks and nothing to declare
``guarded-by``. The supervisor loop owns all mutable state.

``waternet-launch`` (== ``python -m waternet_tpu.resilience.supervisor``)
spawns N training worker processes — each running today's ``train.py``
unchanged — and keeps the *job* alive across worker crash, hang, and
preemption, the training-side mirror of the serving replica supervision
in docs/SERVING.md "Fault isolation":

1. **Gang launch.** Each generation gets a fresh coordinator port and a
   fresh heartbeat directory; workers receive the restart-context env
   contract (``WATERNET_COORDINATOR`` / ``_NUM_PROCESSES`` /
   ``_PROCESS_ID`` / ``_GENERATION`` / ``_HEARTBEAT_DIR``) which
   ``parallel.distributed.initialize`` and ``train.py`` consume — no
   worker-side flags needed.
2. **Health tracking.** Workers heartbeat at step boundaries
   (:mod:`waternet_tpu.resilience.heartbeat`); the supervisor drives the
   per-worker ``starting -> running -> late -> presumed-hung`` machine
   off record freshness plus ``Popen.poll()``. A hang is detected by
   heartbeat timeout — never by waiting on a collective that will never
   complete.
3. **Coordinated restart.** On any worker failure, survivors are drained
   at a step boundary via the PR-1 control plane (SIGTERM ->
   checkpoint -> exit 0; a survivor stuck in a dead collective is
   SIGKILLed after ``drain_grace_sec``), the gang is torn down, and —
   after exponential backoff — a new generation relaunches with
   ``--resume auto``, resuming from the latest *complete, validated*
   checkpoint. The PR-1 replay guarantee makes the finished job's metric
   CSVs and weights byte-identical to an uninterrupted run.
4. **Bounded budgets.** ``max_restarts`` caps restarts; when exhausted
   the supervisor prints a per-generation failure report and exits
   nonzero instead of hanging or retrying forever. The machine-readable
   report also lands at ``<heartbeat-dir>/supervisor-report.json``.

Deterministic fire drills: ``--worker-faults GEN:RANK:SPEC`` injects a
``WATERNET_FAULTS`` plan (e.g. ``proc_kill@3``) into exactly one worker
of exactly one generation, so recovery is a reproducible test, not a
chaos lottery (tests/test_supervisor.py pins kill-mid-epoch bit-exact).
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import time
from pathlib import Path
from typing import Optional

from waternet_tpu.obs import trace
from waternet_tpu.parallel import distributed as dist
from waternet_tpu.resilience import heartbeat as hb

#: Exit code when the retry budget is exhausted (distinct from a worker's
#: own failure codes so wrappers can tell "job failed" from "launcher bug").
EXIT_BUDGET_EXHAUSTED = 3


def backoff_sec(base: float, cap: float, restart_index: int) -> float:
    """Exponential backoff before restart #``restart_index`` (1-based):
    base * 2**(i-1), capped. Pure, so the schedule is unit-testable."""
    return min(float(cap), float(base) * (2.0 ** (max(1, restart_index) - 1)))


def _free_port() -> int:
    with socket.socket(socket.AF_INET, socket.SOCK_STREAM) as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class SupervisorConfig:
    num_workers: int = 1
    #: Restart budget: total generations allowed = max_restarts + 1.
    max_restarts: int = 3
    backoff_base_sec: float = 1.0
    backoff_cap_sec: float = 30.0
    #: Heartbeat freshness thresholds (see WorkerHealth).
    late_sec: float = 15.0
    hang_sec: float = 120.0
    startup_grace_sec: float = 600.0
    #: SIGTERM -> wait -> SIGKILL window for draining survivors.
    drain_grace_sec: float = 30.0
    poll_sec: float = 0.25
    #: Worker-side emission throttle (WATERNET_HEARTBEAT_SEC).
    heartbeat_sec: float = 1.0
    coordinator_host: str = "127.0.0.1"
    #: CPU rehearsal mode: workers get WATERNET_CPU_GLOO=1 (gloo
    #: collectives + serialized dispatch, the PR-5 transport constraint)
    #: and 1 forced host device each.
    cpu_gloo: bool = False


class Supervisor:
    """Run one supervised job to completion (or budget exhaustion).

    ``worker_cmd`` is the base argv every worker runs (normally
    ``[sys.executable, train.py, ...train args]``); generation > 0 argv
    gains ``--resume auto`` unless the caller already passed ``--resume``.
    ``faults`` maps ``(generation, rank) -> WATERNET_FAULTS spec`` for
    deterministic fire drills; unlisted workers get the var *removed* so a
    drill never leaks into relaunched generations.
    """

    def __init__(
        self,
        worker_cmd,
        heartbeat_dir,
        config: Optional[SupervisorConfig] = None,
        env: Optional[dict] = None,
        faults: Optional[dict] = None,
    ):
        self.worker_cmd = [str(c) for c in worker_cmd]
        self.heartbeat_dir = Path(heartbeat_dir)
        self.config = config or SupervisorConfig()
        self.base_env = dict(os.environ if env is None else env)
        self.faults = dict(faults or {})
        self.generations: list = []  # per-generation report dicts
        self.restarts = 0
        self.recovery_secs: list = []  # failure-detect -> first new-gen beat

    # -- launch ---------------------------------------------------------

    def _worker_env(self, generation: int, rank: int, port: int, gen_dir: Path):
        env = dict(self.base_env)
        env[dist.ENV_COORDINATOR] = f"{self.config.coordinator_host}:{port}"
        env[dist.ENV_NUM_PROCESSES] = str(self.config.num_workers)
        env[dist.ENV_PROCESS_ID] = str(rank)
        env[dist.ENV_GENERATION] = str(generation)
        env[hb.ENV_HEARTBEAT_DIR] = str(gen_dir)
        env[hb.ENV_HEARTBEAT_SEC] = str(self.config.heartbeat_sec)
        spec = self.faults.get((generation, rank))
        if spec:
            env["WATERNET_FAULTS"] = spec
        else:  # a drill must never leak into other workers / generations
            env.pop("WATERNET_FAULTS", None)
        if self.config.cpu_gloo:
            env["JAX_PLATFORMS"] = "cpu"
            env[dist.ENV_CPU_GLOO] = "1"
            # One collective stream per rank (CHANGES PR 5): 1 device per
            # process; initialize() serializes dispatch via ENV_CPU_GLOO.
            env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
        return env

    def _worker_argv(self, generation: int):
        argv = list(self.worker_cmd)
        if generation > 0 and "--resume" not in argv:
            argv += ["--resume", "auto"]
        return argv

    def _spawn(self, generation: int, port: int, gen_dir: Path):
        argv = self._worker_argv(generation)
        procs = []
        for rank in range(self.config.num_workers):
            procs.append(
                subprocess.Popen(
                    argv, env=self._worker_env(generation, rank, port, gen_dir)
                )
            )
        return procs

    # -- monitor --------------------------------------------------------

    def _log(self, msg: str) -> None:
        print(f"[waternet-launch] {msg}", flush=True)

    def _sleep(self, sec: float) -> None:  # test seam (backoff assertions)
        time.sleep(sec)

    def _poll_health(self, procs, health, gen_dir: Path):
        """One monitor pass: fold fresh heartbeats, advance every state
        machine, log late workers, return the failure trigger (or None)."""
        now = time.time()
        trigger = None
        for rank, (p, w) in enumerate(zip(procs, health)):
            rec = hb.read_heartbeat(hb.heartbeat_path(gen_dir, rank))
            if rec is not None:
                w.note_beat(rec)
            prev = w.state
            state = w.observe(now, exit_code=p.poll())
            if state != prev and state == hb.LATE:
                self._log(
                    f"worker {rank} late: no heartbeat for "
                    f"{now - w.last_beat:.1f}s"
                )
            if trigger is None:
                if state == hb.DEAD:
                    trigger = (
                        f"worker {rank} exited rc={w.exit_code} "
                        f"(last step {w.last_step})"
                    )
                elif state == hb.HUNG:
                    since = (
                        f"{now - w.last_beat:.1f}s since last heartbeat"
                        if w.last_beat is not None
                        else "no heartbeat since launch"
                    )
                    trigger = f"worker {rank} presumed hung ({since})"
        return trigger

    def _drain(self, procs, health) -> None:
        """SIGTERM survivors (PR-1: checkpoint at the next step boundary,
        exit 0), give them ``drain_grace_sec``, SIGKILL stragglers — a
        worker wedged inside a dead collective never reaches a step
        boundary, so the grace is what bounds teardown."""
        for p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.config.drain_grace_sec
        for p in procs:
            while p.poll() is None and time.monotonic() < deadline:
                self._sleep(min(self.config.poll_sec, 0.1))
            if p.poll() is None:
                self._log(f"worker pid {p.pid} did not drain; SIGKILL")
                p.kill()
            p.wait()

    # -- generation + job ------------------------------------------------

    def _run_generation(self, generation: int):
        """Launch + monitor one generation. Returns (ok, trigger)."""
        cfg = self.config
        port = _free_port()
        gen_dir = self.heartbeat_dir / f"gen-{generation:03d}"
        gen_dir.mkdir(parents=True, exist_ok=True)
        t0 = time.time()
        t_gen0 = time.perf_counter()
        procs = self._spawn(generation, port, gen_dir)
        health = [
            hb.WorkerHealth(cfg.late_sec, cfg.hang_sec, cfg.startup_grace_sec, t0)
            for _ in procs
        ]
        self._log(
            f"generation {generation}: {cfg.num_workers} worker(s), "
            f"coordinator {cfg.coordinator_host}:{port}"
        )
        first_beat: Optional[float] = None
        trigger = None
        try:
            while True:
                trigger = self._poll_health(procs, health, gen_dir)
                if first_beat is None and any(
                    w.last_beat is not None for w in health
                ):
                    first_beat = time.time()
                    if self.recovery_secs and self.recovery_secs[-1] is None:
                        # close the recovery window the failure opened
                        self.recovery_secs[-1] = first_beat - self._failed_at
                if trigger is not None:
                    break
                if all(w.state == hb.DONE for w in health):
                    break
                self._sleep(cfg.poll_sec)
        finally:
            self._drain(procs, health)
            # a worker may have exited during/after drain: record it
            for p, w in zip(procs, health):
                if w.exit_code is None and p.poll() is not None:
                    w.exit_code = p.poll()
            self.generations.append(
                {
                    "generation": generation,
                    "trigger": trigger,
                    "duration_sec": time.time() - t0,
                    "workers": [w.summary() for w in health],
                }
            )
            # Fold the generation into the live trace timeline (in-proc
            # supervisors, e.g. tests/bench; waternet-trace --train-root
            # reconstructs the same view from artifacts after the fact).
            if trace.enabled():
                trace.record_span(
                    "generation", "supervisor", t_gen0,
                    time.perf_counter(),
                    args={"generation": generation, "trigger": trigger,
                          "workers": [w.state for w in health]},
                )
        return trigger is None, trigger

    def run(self) -> dict:
        """Supervise to completion; returns the job report (also written
        to ``<heartbeat-dir>/supervisor-report.json``)."""
        cfg = self.config
        self._failed_at = time.time()
        generation = 0
        while True:
            ok, trigger = self._run_generation(generation)
            if ok:
                return self._finish("completed")
            self._failed_at = time.time()
            self._log(f"generation {generation} failed: {trigger}")
            if self.restarts >= cfg.max_restarts:
                return self._finish("failed")
            self.restarts += 1
            self.recovery_secs.append(None)  # closed by the next first beat
            delay = backoff_sec(
                cfg.backoff_base_sec, cfg.backoff_cap_sec, self.restarts
            )
            self._log(
                f"restart {self.restarts}/{cfg.max_restarts} in {delay:.1f}s "
                "(resuming from the latest complete checkpoint)"
            )
            if trace.enabled():
                trace.record_instant(
                    "restart", "supervisor",
                    args={"generation": generation, "trigger": trigger,
                          "restart": self.restarts,
                          "backoff_sec": delay},
                )
            self._sleep(delay)
            generation += 1

    def _finish(self, result: str) -> dict:
        report = {
            "result": result,
            "restarts": self.restarts,
            "recovery_sec": [r for r in self.recovery_secs if r is not None],
            "generations": self.generations,
        }
        self.heartbeat_dir.mkdir(parents=True, exist_ok=True)
        (self.heartbeat_dir / "supervisor-report.json").write_text(
            json.dumps(report, indent=2)
        )
        if result != "completed":
            self._print_failure_report(report)
        else:
            self._log(
                f"job completed after {self.restarts} restart(s) "
                f"({len(self.generations)} generation(s))"
            )
        return report

    def _print_failure_report(self, report: dict) -> None:
        """The loud part of 'loud failure': a per-generation post-mortem on
        stderr, instead of a silent hang or an unbounded retry loop."""
        err = sys.stderr
        print("=" * 64, file=err)
        print(
            "[waternet-launch] RETRY BUDGET EXHAUSTED — "
            f"{report['restarts']} restart(s) used, job NOT complete",
            file=err,
        )
        for gen in report["generations"]:
            print(
                f"  generation {gen['generation']}: "
                f"{gen['trigger'] or 'completed'} "
                f"(ran {gen['duration_sec']:.1f}s)",
                file=err,
            )
            for rank, w in enumerate(gen["workers"]):
                print(
                    f"    worker {rank}: {w['state']} "
                    f"rc={w['exit_code']} last_step={w['last_step']}",
                    file=err,
                )
        print(
            f"  full report: {self.heartbeat_dir / 'supervisor-report.json'}",
            file=err,
        )
        print("=" * 64, file=err, flush=True)


def _parse_fault_arg(spec: str):
    """``"GEN:RANK:kind@K[,kind@K]"`` -> ((gen, rank), plan-spec)."""
    gen, _, rest = spec.partition(":")
    rank, _, plan = rest.partition(":")
    if not plan:
        raise ValueError(
            f"--worker-faults {spec!r}: expected GEN:RANK:SPEC "
            "(e.g. 0:1:proc_kill@3)"
        )
    return (int(gen), int(rank)), plan


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="waternet-launch",
        description="Supervised elastic multi-process training "
        "(docs/RESILIENCE.md 'Multi-process supervision'). Everything "
        "after -- is passed to each train.py worker verbatim.",
    )
    p.add_argument("--workers", type=int, default=1, metavar="N",
                   help="Worker processes to gang-launch (default 1)")
    p.add_argument("--max-restarts", type=int, default=3,
                   help="Restart budget; exhausted -> loud failure report + "
                   f"exit {EXIT_BUDGET_EXHAUSTED} (default 3)")
    p.add_argument("--backoff-sec", type=float, default=1.0,
                   help="Base of the exponential restart backoff (default 1)")
    p.add_argument("--backoff-cap-sec", type=float, default=30.0,
                   help="Backoff ceiling in seconds (default 30)")
    p.add_argument("--late-sec", type=float, default=15.0,
                   help="Heartbeat age after which a worker is logged late")
    p.add_argument("--hang-sec", type=float, default=120.0,
                   help="Heartbeat age after which a worker is presumed hung "
                   "and the gang restarts (cover your longest val epoch)")
    p.add_argument("--startup-grace-sec", type=float, default=600.0,
                   help="Time allowed before the FIRST heartbeat "
                   "(compilation + data warmup)")
    p.add_argument("--drain-grace-sec", type=float, default=30.0,
                   help="SIGTERM->SIGKILL window when tearing a gang down")
    p.add_argument("--heartbeat-sec", type=float, default=1.0,
                   help="Worker heartbeat emission throttle (default 1)")
    p.add_argument("--heartbeat-dir", type=str, default=None,
                   help="Supervision state root (heartbeats + report); "
                   "default: supervise/<pid> under the repo")
    p.add_argument("--cpu-gloo", action="store_true",
                   help="CPU rehearsal: workers run gloo collectives with 1 "
                   "forced host device + serialized dispatch (the multi-"
                   "process CPU transport constraint)")
    p.add_argument("--worker-faults", action="append", default=[],
                   metavar="GEN:RANK:SPEC",
                   help="Deterministic fire drill: inject WATERNET_FAULTS "
                   "SPEC (e.g. proc_kill@3) into worker RANK of generation "
                   "GEN only. Repeatable")
    p.add_argument("--worker-cmd", type=str, default=None,
                   help="Override the worker executable (default: "
                   "'<python> <repo>/train.py'); the -- args still apply")
    p.add_argument("train_args", nargs=argparse.REMAINDER,
                   help="Arguments after -- go to every worker")
    return p.parse_args(argv)


def main(argv=None) -> int:
    args = parse_args(argv)
    train_args = list(args.train_args)
    if train_args and train_args[0] == "--":
        train_args = train_args[1:]
    if args.worker_cmd:
        worker_cmd = args.worker_cmd.split() + train_args
    else:
        repo = Path(__file__).resolve().parents[2]
        worker_cmd = [sys.executable, str(repo / "train.py")] + train_args
    heartbeat_dir = Path(
        args.heartbeat_dir
        or Path(__file__).resolve().parents[2] / "supervise" / str(os.getpid())
    )
    cfg = SupervisorConfig(
        num_workers=args.workers,
        max_restarts=args.max_restarts,
        backoff_base_sec=args.backoff_sec,
        backoff_cap_sec=args.backoff_cap_sec,
        late_sec=args.late_sec,
        hang_sec=args.hang_sec,
        startup_grace_sec=args.startup_grace_sec,
        drain_grace_sec=args.drain_grace_sec,
        heartbeat_sec=args.heartbeat_sec,
        cpu_gloo=args.cpu_gloo,
    )
    faults = dict(_parse_fault_arg(s) for s in args.worker_faults)
    sup = Supervisor(worker_cmd, heartbeat_dir, cfg, faults=faults)
    report = sup.run()
    return 0 if report["result"] == "completed" else EXIT_BUDGET_EXHAUSTED


if __name__ == "__main__":
    raise SystemExit(main())
