"""Deterministic fault injection for the resilience test suite.

Real preemptions, NaN steps, and corrupt files are rare and nondeterministic;
this harness makes each one a reproducible event so tests (and operators
doing fire drills) can assert exact recovery behavior. A :class:`FaultPlan`
is a set of one-shot events, each keyed by a deterministic counter:

* ``nan@K`` — after the engine dispatches global step K (1-based, counted on
  the host), poison the train state's float params with NaN and report a
  NaN loss for that step: the faithful signature of a non-finite gradient.
* ``sigterm@K`` — deliver a real SIGTERM to this process after global step
  K, exercising the actual signal path of
  :class:`waternet_tpu.resilience.preemption.PreemptionGuard`.
* ``proc_kill@K`` — the process self-terminates HARD (SIGKILL to itself)
  after global step K: no drain, no checkpoint, no atexit — the faithful
  signature of an OOM kill or an unannounced VM preemption. The training
  supervisor (docs/RESILIENCE.md "Multi-process supervision") must detect
  the exit and restart the gang from the last complete checkpoint.
* ``proc_hang@K`` — the process wedges after global step K *without
  heartbeating*: the dispatch thread blocks on a release latch, so step
  progress and heartbeat emission both stop while the process stays
  alive — the faithful signature of a stuck collective or a wedged
  device. The supervisor must detect this by heartbeat timeout (never by
  waiting on the collective). Releasable like ``replica_hang``: the
  wedged thread wakes on :func:`clear` / :func:`install`, so in-process
  tests stay joinable; under the supervisor nothing clears the plan and
  the worker is SIGKILLed after the drain grace.
* ``truncate_ckpt@K`` — after the K-th (1-based) finalized checkpoint save,
  truncate its largest payload file, simulating a mid-write crash or torn
  volume that the marker protocol alone cannot see.
* ``decode@K`` — the K-th ``cv2.imread`` *attempt* (1-based, process-global,
  counted across pipeline worker threads under a lock) reports a decode
  failure, exercising :meth:`UIEBDataset._imread_retry`'s retry path — and,
  when enough consecutive attempts are armed to exhaust the retries, the
  quarantine path — exactly where production hits them: inside the input
  pipeline's workers.
* ``slow_replica@K`` — the K-th bucketed batch *launch* (1-based,
  process-global across every replica's launch thread, under a lock)
  sleeps ``WATERNET_FAULT_SLOW_SEC`` (default 0.25) before dispatching,
  simulating a replica whose device stalls mid-serve — the deterministic
  way to hold work in flight so drain, deadline-expiry, and shed paths
  are testable (serving/replicas.py calls :func:`replica_launch_fault`).
* ``replica_crash@K`` — the K-th bucketed batch launch raises, the
  faithful signature of a replica whose XLA dispatch dies mid-serve.
  The supervised pool (docs/SERVING.md "Fault isolation") must contain
  it: the batch's requests re-dispatch onto surviving replicas and the
  sick replica walks the quarantine → re-warm → reintegrate machine.
* ``replica_hang@K`` — the K-th bucketed batch launch blocks
  indefinitely (a wedged driver / stalled device), releasable: the
  wedged thread wakes when the plan is cleared or replaced
  (:func:`clear` / :func:`install`), so tests can assert the watchdog
  path and still join every thread. Until release, the launch neither
  completes nor raises — exactly what a watchdog exists to catch.
* ``nan_output@K`` — the K-th *completed* serving batch's host array is
  poisoned after D2H (float outputs → NaN, uint8 outputs → an all-zero
  canvas), exercising the replica pool's output sanity guard
  (serving/replicas.py calls :func:`poison_replica_output`).
* ``reject_admit@K`` — the K-th admission attempt at the HTTP front door
  (1-based, process-global) is force-shed with 429 regardless of queue
  depth, exercising the shed path and client retry behavior without
  having to actually saturate the queue
  (serving/server.py calls :func:`admit_should_reject`).
* ``stream_stall@K`` — the K-th stream session opened on the front door
  (1-based, process-global) behaves as a wedged consumer: every record
  delivery to that session sleeps ``WATERNET_FAULT_STALL_SEC`` (default
  0.25) before the write, the faithful signature of a client that
  stopped reading — the deterministic way to prove a stalled stream
  backpressures only itself (serving/streams.py calls
  :func:`stream_session_fault` at session open).
* ``stream_disconnect@K`` — the K-th stream session opened is
  force-disconnected server-side after reading
  ``WATERNET_FAULT_DISCONNECT_FRAMES`` (default 2) frames, simulating a
  client that vanished mid-stream with frames still queued — the
  cancellation/cleanup path without real socket timing races.
* ``frame_corrupt@K`` — the K-th stream frame decode attempt (1-based,
  process-global across sessions, under a lock) is treated as
  undecodable, exercising the per-frame quarantine path: that frame
  alone errors, its session and every other stream keep flowing
  (serving/streams.py calls :func:`frame_should_corrupt`).
* ``gateway_crash@K`` — the K-th ``/enhance`` arrival at THIS serving
  process (1-based, per-process) self-terminates it HARD (SIGKILL, no
  drain): the faithful signature of a serving worker OOM-killed with a
  request in flight. The fleet router (docs/SERVING.md "Fleet") must
  detect the exit, re-dispatch the in-flight request onto a surviving
  worker, and relaunch the gateway as a fresh generation
  (serving/server.py calls :func:`gateway_fault`).
* ``gateway_hang@K`` — the K-th ``/enhance`` arrival wedges the serving
  process's event loop on a release latch: ``/healthz`` stops
  answering, heartbeats stop, and every connection (including the
  faulted request's) freezes while the process stays alive — a wedged
  gateway. Releasable like ``proc_hang`` (:func:`clear` /
  :func:`install` wake it); under the fleet router nothing clears the
  plan and the worker is SIGKILLed past the drain grace.

Plans come from the environment (``WATERNET_FAULTS="nan@3,sigterm@10"``,
read once by :func:`install_from_env`, which train.py calls) or from tests
via :func:`install`. With no plan installed every hook is a single ``is
None`` check — zero overhead on the hot path. Events are one-shot: a replay
of the same batch after a sentinel rollback does NOT re-fire the fault
(matching reality, where the skip removes the offending batch).

File-corruption helpers (:func:`truncate_file`,
:class:`FaultInjectingCapture`) are exported for tests that corrupt PNGs
and video streams directly.
"""

from __future__ import annotations

import os
import signal
import threading
from pathlib import Path
from typing import NamedTuple

_PLAN: "FaultPlan | None" = None  # guarded-by: _SERVE_LOCK (hot-path reads are lock-free `is None` checks by design)
_IMREAD_CALLS = 0  # guarded-by: _IMREAD_LOCK
_IMREAD_LOCK = threading.Lock()
_LAUNCH_CALLS = 0  # guarded-by: _SERVE_LOCK
_ADMIT_CALLS = 0  # guarded-by: _SERVE_LOCK
_COMPLETE_CALLS = 0  # guarded-by: _SERVE_LOCK
_STREAM_SESSIONS = 0  # guarded-by: _SERVE_LOCK
_FRAME_DECODES = 0  # guarded-by: _SERVE_LOCK
_GATEWAY_CALLS = 0  # guarded-by: _SERVE_LOCK
_SERVE_LOCK = threading.Lock()
#: Release latch for armed ``replica_hang`` events: a wedged launch thread
#: waits on this, and :func:`install` / :func:`clear` set it — so a test
#: (or an operator fire drill) can un-wedge the "hung device" on cue and
#: every thread stays joinable.
_HANG_RELEASE = threading.Event()  # guarded-by: _SERVE_LOCK (rebinding; the Event itself is thread-safe)


class FaultPlan:
    """One-shot fault events keyed by (kind, ordinal)."""

    KINDS = (
        "nan", "sigterm", "proc_kill", "proc_hang", "truncate_ckpt",
        "decode",
        "slow_replica", "replica_crash", "replica_hang", "nan_output",
        "reject_admit", "stream_stall", "stream_disconnect",
        "frame_corrupt", "gateway_crash", "gateway_hang",
    )

    def __init__(self, events=()):
        self._pending = set()
        for kind, at in events:
            if kind not in self.KINDS:
                raise ValueError(f"unknown fault kind {kind!r} (have {self.KINDS})")
            self._pending.add((kind, int(at)))
        self.fired: list = []

    @classmethod
    def parse(cls, spec: str) -> "FaultPlan":
        """``"nan@3,sigterm@10"`` -> plan. Whitespace tolerated."""
        events = []
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            kind, _, at = part.partition("@")
            if not at:
                raise ValueError(f"fault {part!r} needs '@<step>'")
            events.append((kind.strip(), int(at)))
        return cls(events)

    def fire(self, kind: str, at: int) -> bool:
        """Consume the (kind, at) event if armed. One-shot."""
        key = (kind, int(at))
        if key in self._pending:
            self._pending.remove(key)
            self.fired.append(key)
            return True
        return False

    def __bool__(self):
        return bool(self._pending)


def install(plan: FaultPlan | None) -> None:
    global _PLAN, _IMREAD_CALLS, _LAUNCH_CALLS, _ADMIT_CALLS
    global _COMPLETE_CALLS, _STREAM_SESSIONS, _FRAME_DECODES
    global _GATEWAY_CALLS, _HANG_RELEASE
    with _SERVE_LOCK:
        # Release any launch thread wedged by the PREVIOUS plan's
        # replica_hang before swapping latches: hangs are releasable by
        # contract (the thread-leak guard depends on it). The swap
        # happens under the same lock that fires hang events, so a
        # thread that drew hang=True always holds the latch its plan
        # armed — it can never miss its release by racing the swap.
        _HANG_RELEASE.set()
        _PLAN = plan
        if plan is not None:
            _HANG_RELEASE = threading.Event()  # fresh latch for this plan
        _LAUNCH_CALLS = 0
        _ADMIT_CALLS = 0
        _COMPLETE_CALLS = 0
        _STREAM_SESSIONS = 0
        _FRAME_DECODES = 0
        _GATEWAY_CALLS = 0
    with _IMREAD_LOCK:
        _IMREAD_CALLS = 0


def clear() -> None:
    install(None)


def active() -> FaultPlan | None:
    return _PLAN


def install_from_env(env: str = "WATERNET_FAULTS") -> FaultPlan | None:
    spec = os.environ.get(env)
    if spec:
        install(FaultPlan.parse(spec))
    return _PLAN


# ----------------------------------------------------------------------
# Hooks — called from the trainer / checkpoint manager hot paths.
# ----------------------------------------------------------------------


def after_train_step(engine, metrics, global_step: int):
    """Hook run after each dispatched train step.

    Returns the (possibly poisoned) per-step metrics mapping. ``nan`` events
    poison the live train state's float params and override the step's
    metrics with NaN — exactly what a non-finite gradient does to Adam.
    """
    if _PLAN is None:
        return metrics
    if _PLAN.fire("nan", global_step):
        import jax.numpy as jnp
        import numpy as np

        def _poison(x):
            return x * np.float32("nan") if jnp.issubdtype(x.dtype, jnp.floating) else x

        import jax

        engine.state = engine.state.replace(
            params=jax.tree.map(_poison, engine.state.params)
        )
        metrics = {k: float("nan") for k in metrics}
    if _PLAN.fire("sigterm", global_step):
        os.kill(os.getpid(), signal.SIGTERM)
    if _PLAN.fire("proc_kill", global_step):
        # Hard self-terminate: no drain, no checkpoint, no Python teardown
        # (SIGKILL is uncatchable) — an OOM kill / unannounced preemption.
        os.kill(os.getpid(), signal.SIGKILL)
    with _SERVE_LOCK:
        hang = _HANG_RELEASE if _PLAN.fire("proc_hang", global_step) else None
    if hang is not None:
        # Wedge without heartbeating: block the dispatch thread on the
        # plan's release latch (same contract as replica_hang — clear()/
        # install() release it, so in-process tests stay joinable; under
        # the supervisor nothing does, and the heartbeat timeout reaps us).
        hang.wait()
    return metrics


def imread_should_fail() -> bool:
    """Hook run before each ``cv2.imread`` attempt in
    :meth:`waternet_tpu.data.uieb.UIEBDataset._imread_retry`.

    Returns True when this attempt should be treated as a decode failure
    (kind ``decode``, keyed by a process-global attempt counter guarded by
    a lock — pipeline workers call this concurrently). With no plan
    installed this is a single ``is None`` check.
    """
    global _IMREAD_CALLS
    if _PLAN is None:
        return False
    with _IMREAD_LOCK:
        _IMREAD_CALLS += 1
        return _PLAN.fire("decode", _IMREAD_CALLS)


class LaunchFault(NamedTuple):
    """What the K-th bucketed batch launch should do (one counter, three
    serving-side kinds — the ordinal in ``slow_replica@K`` /
    ``replica_crash@K`` / ``replica_hang@K`` is the same launch count).
    ``hang`` is None, or the release :class:`threading.Event` the armed
    plan owns — captured atomically with the fire, so the wedged thread
    always waits on the latch that :func:`clear`/:func:`install` will
    set for it."""

    delay: float
    crash: bool
    hang: "threading.Event | None"


_NO_LAUNCH_FAULT = LaunchFault(0.0, False, None)


def replica_launch_fault() -> LaunchFault:
    """Hook run before each bucketed batch launch in
    :meth:`waternet_tpu.serving.replicas._Replica._launch_loop`.

    Keyed by a process-global launch counter across every replica's (and
    every tier pool's) launch thread, under a lock. ``delay`` is the
    seconds this launch should stall (kind ``slow_replica``, from
    ``WATERNET_FAULT_SLOW_SEC``, default 0.25); ``crash`` means the
    launch must raise (kind ``replica_crash``); a non-None ``hang`` is
    the release latch the launch must block on (kind ``replica_hang`` —
    the latch is set by :func:`clear`/:func:`install`, making every
    injected wedge releasable). With no plan installed this is a single
    ``is None`` check.
    """
    global _LAUNCH_CALLS
    if _PLAN is None:
        return _NO_LAUNCH_FAULT
    with _SERVE_LOCK:
        _LAUNCH_CALLS += 1
        k = _LAUNCH_CALLS
        delay = (
            float(os.environ.get("WATERNET_FAULT_SLOW_SEC", "0.25"))
            if _PLAN.fire("slow_replica", k)
            else 0.0
        )
        crash = _PLAN.fire("replica_crash", k)
        hang = _HANG_RELEASE if _PLAN.fire("replica_hang", k) else None
    return LaunchFault(delay, crash, hang)


def replica_launch_delay() -> float:
    """Back-compat form of :func:`replica_launch_fault` for callers that
    only stall (same counter: one call = one launch ordinal)."""
    return replica_launch_fault().delay


def poison_replica_output(arr):
    """Hook run on each completed serving batch's host array, after the
    D2H sync in :meth:`waternet_tpu.serving.replicas._Replica._complete_loop`.

    Kind ``nan_output``, keyed by a process-global completed-batch
    counter. When armed for this ordinal, returns a poisoned copy —
    float arrays go non-finite, integer arrays go all-zero: the two
    signatures the pool's output sanity guard detects. Otherwise returns
    ``arr`` unchanged; with no plan installed this is a single ``is
    None`` check.
    """
    global _COMPLETE_CALLS
    if _PLAN is None:
        return arr
    with _SERVE_LOCK:
        _COMPLETE_CALLS += 1
        fired = _PLAN.fire("nan_output", _COMPLETE_CALLS)
    if not fired:
        return arr
    import numpy as np

    out = np.array(arr)
    if np.issubdtype(out.dtype, np.floating):
        out[...] = np.nan
    else:
        out[...] = 0
    return out


def admit_should_reject() -> bool:
    """Hook run at each HTTP front-door admission attempt
    (waternet_tpu/serving/server.py).

    Returns True when this admission should be force-shed with 429 (kind
    ``reject_admit``, keyed by a process-global admission counter). With
    no plan installed this is a single ``is None`` check.
    """
    global _ADMIT_CALLS
    if _PLAN is None:
        return False
    with _SERVE_LOCK:
        _ADMIT_CALLS += 1
        return _PLAN.fire("reject_admit", _ADMIT_CALLS)


class StreamSessionFault(NamedTuple):
    """What the K-th opened stream session should suffer. ``stall`` means
    the session behaves as a wedged consumer (every delivery sleeps
    ``WATERNET_FAULT_STALL_SEC`` before the write); ``disconnect_after``
    is None, or the frame count after which the session's reader must
    simulate a peer reset (kind ``stream_disconnect``)."""

    stall: bool
    disconnect_after: "int | None"


_NO_STREAM_FAULT = StreamSessionFault(False, None)


def stream_session_fault() -> StreamSessionFault:
    """Hook run once per stream session open in
    :class:`waternet_tpu.serving.streams.StreamManager`.

    Keyed by a process-global session-open counter under a lock (kinds
    ``stream_stall`` and ``stream_disconnect`` share the ordinal: the
    K-th session opened). With no plan installed this is a single ``is
    None`` check.
    """
    global _STREAM_SESSIONS
    if _PLAN is None:
        return _NO_STREAM_FAULT
    with _SERVE_LOCK:
        _STREAM_SESSIONS += 1
        k = _STREAM_SESSIONS
        stall = _PLAN.fire("stream_stall", k)
        disconnect = _PLAN.fire("stream_disconnect", k)
    after = (
        int(os.environ.get("WATERNET_FAULT_DISCONNECT_FRAMES", "2"))
        if disconnect
        else None
    )
    return StreamSessionFault(stall, after)


def stream_stall_sec() -> float:
    """How long a stalled stream session sleeps before each delivery."""
    return float(os.environ.get("WATERNET_FAULT_STALL_SEC", "0.25"))


def frame_should_corrupt() -> bool:
    """Hook run before each stream frame decode attempt
    (waternet_tpu/serving/streams.py).

    Returns True when this frame must be treated as undecodable (kind
    ``frame_corrupt``, keyed by a process-global frame-decode counter
    across every stream session, under a lock). With no plan installed
    this is a single ``is None`` check.
    """
    global _FRAME_DECODES
    if _PLAN is None:
        return False
    with _SERVE_LOCK:
        _FRAME_DECODES += 1
        return _PLAN.fire("frame_corrupt", _FRAME_DECODES)


class GatewayFault(NamedTuple):
    """What the K-th ``/enhance`` arrival at this serving process should
    do (one per-process counter, two kinds sharing the ordinal).
    ``crash`` means SIGKILL self before answering; ``hang`` is None, or
    the release :class:`threading.Event` the armed plan owns — the
    handler blocks the event loop thread on it, freezing ``/healthz``
    and heartbeats together, which is exactly the signature the fleet
    router's hang detection exists to catch."""

    crash: bool
    hang: "threading.Event | None"


_NO_GATEWAY_FAULT = GatewayFault(False, None)


def gateway_fault() -> GatewayFault:
    """Hook run once per ``/enhance`` arrival at the HTTP front door
    (waternet_tpu/serving/server.py), before admission.

    Keyed by a per-process arrival counter under a lock (kinds
    ``gateway_crash`` and ``gateway_hang`` share the ordinal: the K-th
    enhance request THIS worker sees). Arrivals 1..K-1 are answered
    normally, so a fleet bench can pin exactly which in-flight request
    the failover must re-dispatch. With no plan installed this is a
    single ``is None`` check.
    """
    global _GATEWAY_CALLS
    if _PLAN is None:
        return _NO_GATEWAY_FAULT
    with _SERVE_LOCK:
        _GATEWAY_CALLS += 1
        k = _GATEWAY_CALLS
        crash = _PLAN.fire("gateway_crash", k)
        hang = _HANG_RELEASE if _PLAN.fire("gateway_hang", k) else None
    return GatewayFault(crash, hang)


def after_checkpoint_save(path, ordinal: int) -> None:
    """Hook run (process 0 only) after the ``ordinal``-th finalized save."""
    if _PLAN is None:
        return
    if _PLAN.fire("truncate_ckpt", ordinal):
        victim = largest_file(path)
        if victim is not None:
            truncate_file(victim, keep_bytes=max(1, victim.stat().st_size // 3))


# ----------------------------------------------------------------------
# File / stream corruption helpers for tests.
# ----------------------------------------------------------------------


def largest_file(root) -> Path | None:
    files = [p for p in Path(root).rglob("*") if p.is_file()]
    return max(files, key=lambda p: p.stat().st_size, default=None)


def truncate_file(path, keep_bytes: int = 16) -> Path:
    """Truncate ``path`` in place to ``keep_bytes`` (simulated torn write)."""
    path = Path(path)
    data = path.read_bytes()[:keep_bytes]
    path.write_bytes(data)
    return path


class FaultInjectingCapture:
    """cv2.VideoCapture look-alike that fails decode at chosen frame indices.

    Mimics the backend contract :func:`waternet_tpu.data.video._read_batch`
    relies on: a mid-stream decode failure still *advances*
    ``CAP_PROP_POS_FRAMES`` (grab succeeded, retrieve failed) while EOF does
    not. Wraps either a real capture or a list of frames.
    """

    def __init__(self, frames, bad_indices=(), frame_count=None):
        self._frames = list(frames)
        self._bad = set(int(i) for i in bad_indices)
        self._pos = 0
        self._count = len(self._frames) if frame_count is None else frame_count

    def read(self):
        if self._pos >= len(self._frames):
            return False, None
        i = self._pos
        self._pos += 1  # grab advances even when retrieve (decode) fails
        if i in self._bad:
            return False, None
        return True, self._frames[i]

    def grab(self):
        if self._pos >= len(self._frames):
            return False
        self._pos += 1
        return True

    def get(self, prop):
        import cv2

        if prop == cv2.CAP_PROP_POS_FRAMES:
            return float(self._pos)
        if prop == cv2.CAP_PROP_FRAME_COUNT:
            return float(self._count)
        return 0.0
