"""Preemption handling: turn SIGTERM/SIGINT into a clean checkpoint.

GCE preemptible/spot TPU VMs get SIGTERM with a ~30 s grace window; a
400-epoch run that dies mid-epoch without one loses up to an epoch of work
*and* its exact dataloader position. :class:`PreemptionGuard` installs
handlers that only set a flag; the trainer's epoch driver checks the flag at
every step boundary and raises :class:`Preempted` carrying the position
``(next_batch, partial per-step metrics)``, which train.py turns into a
mid-epoch checkpoint. Because batch composition is a pure function of
``(seed, epoch)`` (the shared Philox stream in
:func:`waternet_tpu.data.batching.epoch_permutation`), resuming from that
position replays the interrupted epoch bit-for-bit.

Multi-host: the flag is process-local. GCE delivers the preemption signal to
every VM in the slice, so all processes reach the same boundary and the
checkpoint save stays collective; delivering a manual SIGTERM to a single
process of a multi-process job would desynchronize the fleet (documented in
docs/RESILIENCE.md).
"""

from __future__ import annotations

import signal


class Preempted(Exception):
    """Raised by the epoch driver at the first step boundary after a signal.

    ``next_batch`` is the epoch-relative index of the first batch NOT yet
    trained; ``partial`` is the ordered list of per-step metric dicts (host
    floats) for the batches that did complete — exactly the carry a resumed
    epoch needs to reproduce the uninterrupted epoch means bit-for-bit.
    """

    def __init__(self, next_batch: int, partial: list):
        super().__init__(f"preempted before batch {next_batch}")
        self.next_batch = next_batch
        self.partial = partial


class PreemptionGuard:
    """Context manager: latch SIGTERM/SIGINT into a ``requested`` flag.

    The handler does no I/O and no jax calls (it runs at an arbitrary
    bytecode boundary); all real work happens at the next step boundary in
    the training loop. A second signal restores the previous disposition and
    re-raises it, so a stuck run can still be killed.
    """

    def __init__(self, signals=(signal.SIGTERM, signal.SIGINT)):
        self.signals = tuple(signals)
        self.requested = False
        self._previous: dict = {}

    def _handle(self, signum, frame):
        if self.requested:
            # Second signal: the operator means it. Restore and re-deliver.
            self._restore()
            signal.raise_signal(signum)
            return
        self.requested = True

    def __enter__(self):
        for s in self.signals:
            self._previous[s] = signal.signal(s, self._handle)
        return self

    def _restore(self):
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous = {}

    def __exit__(self, *exc):
        self._restore()
        return False
