"""Serialized deployment artifacts for the inference forward (StableHLO).

The reference's only deployment story is "install torch and load the
checkpoint" (`/root/reference/hubconf.py:37-96`). A TPU-native framework can
do better: ``jax.export`` serializes the traced forward — weights baked in —
as a portable StableHLO artifact that any later JAX runtime (or anything
else that consumes StableHLO) can execute without this package, its Python
code, or the original checkpoint format.

Properties:

* **Shape-polymorphic**: exported with symbolic (batch, H, W), so ONE
  artifact serves every resolution — the FCN property
  (`/root/reference/waternet/net.py:84-90`) carried into the serialized
  form. 112x112 training crops and 1080p video frames run from the same
  file.
* **Self-contained**: params (float or the int8 qtree) are constants inside
  the artifact.
* **int8-exportable**: pass ``quantize=True`` to bake the statically
  calibrated int8 forward (see :mod:`waternet_tpu.models.quant`).

The artifact covers the MODEL forward ``(x, wb, ce, gc) -> out`` — the hub
triple's ``model`` leg. Preprocessing (WB/GC/CLAHE) stays a runtime choice
(host cv2 parity path vs on-device fused path), exactly as in the live API.
"""

from __future__ import annotations

from pathlib import Path

import jax
import jax.numpy as jnp
from jax import export as jexport

from waternet_tpu.models import WaterNet

_MAGIC_SUFFIX = ".stablehlo"


def export_forward(
    params,
    *,
    quantize: bool = False,
    calib_batches=None,
    dtype=jnp.float32,
    platforms=("cpu", "tpu"),
    arch: str = "waternet",
):
    """-> jax.export.Exported of the inference forward with symbolic
    (batch, height, width) and params baked in as constants.

    ``arch`` selects the serving tier's model: ``"waternet"`` (the
    quality teacher, ``(x, wb, ce, gc) -> out``) or ``"can"`` (the fast
    tier's distilled student, single-input ``(x) -> out`` — its
    width/depth are inferred AND validated from the param tree, so a
    WaterNet checkpoint exported as a student fails with a named diff).
    ``platforms`` controls which backends the artifact is lowered for
    (default: cpu AND tpu, so one file exported anywhere runs on both)."""
    if calib_batches is not None and not quantize:
        raise ValueError(
            "calib_batches given without quantize=True — the calibration "
            "data would be silently dropped from a float artifact"
        )
    if arch not in ("waternet", "can"):
        raise ValueError(f"arch must be 'waternet' or 'can', got {arch!r}")
    b, h, w = jexport.symbolic_shape("b, h, w")
    spec = jax.ShapeDtypeStruct((b, h, w, 3), jnp.float32)
    if arch == "can":
        from waternet_tpu.models import CANStudent
        from waternet_tpu.models.can import can_config_from_params

        width, depth = can_config_from_params(params)
        if quantize:
            from waternet_tpu.models.quant import (
                can_quant_forward,
                quantize_can,
            )

            qtree = quantize_can(params, calib_batches)

            def fn(x):
                return can_quant_forward(qtree, x)

        else:
            module = CANStudent(width=width, depth=depth, dtype=dtype)

            def fn(x):
                return module.apply(params, x)

        return jexport.export(jax.jit(fn), platforms=list(platforms))(spec)
    if quantize:
        from waternet_tpu.models.quant import quant_forward, quantize_waternet

        qtree = quantize_waternet(params, calib_batches)

        def fn(x, wb, ce, gc):
            return quant_forward(qtree, x, wb, ce, gc)

    else:
        module = WaterNet(dtype=dtype)

        def fn(x, wb, ce, gc):
            return module.apply(params, x, wb, ce, gc)

    return jexport.export(jax.jit(fn), platforms=list(platforms))(
        spec, spec, spec, spec
    )


def save_artifact(path, params, **kwargs) -> Path:
    """Export and serialize to ``path`` (``.stablehlo`` appended if no
    suffix). Returns the written path."""
    path = Path(path)
    if not path.suffix:
        path = path.with_suffix(_MAGIC_SUFFIX)
    exported = export_forward(params, **kwargs)
    path.write_bytes(exported.serialize())
    return path


def load_artifact(path):
    """-> callable forward from a serialized artifact: ``(x, wb, ce, gc)
    -> out`` for a WaterNet export, ``(x) -> out`` for a CAN student one
    (the arity is the artifact's own).

    The returned callable jit-executes the embedded StableHLO; it needs only
    jax at runtime (no waternet_tpu, no checkpoint file).
    """
    exported = jexport.deserialize(Path(path).read_bytes())

    def run(*args):
        return exported.call(*(jnp.asarray(a, jnp.float32) for a in args))

    return run
