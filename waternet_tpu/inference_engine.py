"""Batched inference engine: uint8 frames in, enhanced uint8 frames out.

The single object behind the inference CLI and the video pipeline. Two
preprocessing modes:

* host (default): cv2/NumPy WB+GC+CLAHE per frame — bit-exact with the
  reference (`/root/reference/inference.py:177`);
* device: the batch's WB/GC/CLAHE run inside the same jitted XLA program as
  the network (`waternet_tpu.ops.transform_batch`), so the host only decodes
  frames. On a host-CPU-starved TPU VM this is the fast path.

Compiled executables are cached per input shape by jax's jit cache; video
(fixed shape) compiles once.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from waternet_tpu.models import WaterNet
from waternet_tpu.ops import transform_batch, transform_np
from waternet_tpu.hub import resolve_weights
from waternet_tpu.utils.tensor import ten2arr


class InferenceEngine:
    def __init__(
        self,
        weights=None,
        params: Optional[dict] = None,
        device_preprocess: bool = False,
        dtype=jnp.float32,
        spatial_shards: int = 1,
        data_shards: int = 1,
        quantize: bool = False,
        calib_batches=None,
    ):
        """``spatial_shards > 1`` splits each image's height over that many
        devices with exact halo-exchange (see waternet_tpu.parallel.spatial)
        — for frames too large for one chip's HBM. Requires
        ``spatial_shards`` devices and H divisible by it with slabs >= 26
        rows.

        ``data_shards > 1`` shards the FRAME BATCH over that many devices
        (params replicated, XLA moves the shards; no collectives in the
        forward) — the throughput scale-out for video on a pod slice.
        Non-multiple batches pad transparently (last frame repeated), so
        send multiples of ``data_shards`` for full utilization. Composes
        with ``quantize`` and ``device_preprocess``; mutually exclusive
        with ``spatial_shards`` for now.

        ``quantize=True`` converts the checkpoint to static int8 at
        construction (see :mod:`waternet_tpu.models.quant`): int8 x int8
        convs ride the MXU's double-rate int8 path and halve activation HBM
        traffic — the fast path for full-resolution video. Activation
        scales calibrate on ``calib_batches`` ((x, wb, ce, gc) float tuples)
        or on synthetic frames by default; output typically agrees with the
        float forward to >40 dB PSNR."""
        from waternet_tpu.utils.platform import ensure_platform

        ensure_platform()
        self.module = WaterNet(dtype=dtype)
        if params is None:
            params = resolve_weights(weights)
        if params is None:
            raise FileNotFoundError(
                "No weights found — pass --weights, set WATERNET_TPU_WEIGHTS, "
                "or place a checkpoint in ./weights (native .npz or the "
                "reference's exported .pt, converted automatically)."
            )
        self.params = params
        self.device_preprocess = device_preprocess
        self.quantized = quantize

        self.spatial_shards = spatial_shards
        self.data_shards = data_shards
        if data_shards > 1 and spatial_shards > 1:
            raise ValueError(
                "data_shards and spatial_shards are mutually exclusive for "
                "now; pick batch scale-out OR single-frame decomposition"
            )
        if quantize:
            from waternet_tpu.models.quant import quant_forward, quantize_waternet

            # quant_forward(qtree, x, wb, ce, gc) has the same signature
            # shape as module.apply(params, ...), so the qtree simply
            # replaces the params for every downstream path.
            self.params = quantize_waternet(params, calib_batches)
            apply_fn = quant_forward
        else:
            apply_fn = self.module.apply

        if spatial_shards > 1:
            from waternet_tpu.parallel.mesh import make_mesh
            from waternet_tpu.parallel.spatial import spatial_sharded_apply

            mesh = make_mesh(n_data=1, n_spatial=spatial_shards)
            # Already jitted; do not wrap in another jax.jit layer. The
            # halo-exchange path takes the same functional forward the
            # single-device path uses (float or int8).
            _forward = spatial_sharded_apply(apply_fn, mesh)
        else:
            if data_shards > 1:
                from waternet_tpu.parallel.mesh import (
                    batch_sharding,
                    make_mesh,
                    replicated,
                )

                mesh = make_mesh(n_data=data_shards, n_spatial=1)
                bsh = batch_sharding(mesh)
                rep = replicated(mesh)
                _forward = jax.jit(
                    apply_fn,
                    in_shardings=(rep, bsh, bsh, bsh, bsh),
                    out_shardings=bsh,
                )
            else:
                _forward = jax.jit(apply_fn)

        def _fused(p, rgb_u8):
            """uint8 batch -> enhanced float batch, preprocessing on device."""
            wb, gc, he = transform_batch(rgb_u8)
            rgb = rgb_u8.astype(jnp.float32) / 255.0
            return _forward(p, rgb, wb / 255.0, he / 255.0, gc / 255.0)

        self._forward = _forward
        if data_shards > 1:
            # Shard the raw uint8 batch at the boundary so preprocessing
            # runs shard-local too (no resharding between stages).
            self._fused = jax.jit(
                _fused, in_shardings=(rep, bsh), out_shardings=bsh
            )
        else:
            self._fused = jax.jit(_fused)

    def _pad_for_shards(self, rgb_batch):
        """-> (padded_batch, n_real). Shards need equal batch slices, so a
        batch that isn't a multiple of data_shards is padded by repeating
        the last frame (throughput-optimal callers send full multiples; the
        video CLI already pads whole clips to one compile shape). Leaves
        device arrays untouched on the fast path — enhance_async must not
        force a host round-trip."""
        n = rgb_batch.shape[0]
        if self.data_shards <= 1 or n % self.data_shards == 0:
            return rgb_batch, n
        from waternet_tpu.parallel.mesh import pad_to_multiple

        return pad_to_multiple(np.asarray(rgb_batch), self.data_shards)

    def _validate_shape(self, rgb_batch) -> None:
        if self.spatial_shards <= 1:
            return
        from waternet_tpu.parallel.spatial import HALO

        h = rgb_batch.shape[1]
        if h % self.spatial_shards != 0:
            raise ValueError(
                f"image height {h} not divisible by spatial_shards="
                f"{self.spatial_shards}"
            )
        if h // self.spatial_shards < 2 * HALO:
            raise ValueError(
                f"spatial slab of {h // self.spatial_shards} rows < "
                f"2*HALO={2 * HALO}; use fewer spatial shards for this height"
            )

    def enhance(self, rgb_batch: np.ndarray) -> np.ndarray:
        """(N, H, W, 3) uint8 RGB -> (N, H, W, 3) uint8 RGB enhanced."""
        return ten2arr(self.enhance_async(rgb_batch))

    def enhance_async(self, rgb_batch: np.ndarray):
        """Launch enhancement without blocking; returns a device array future.

        JAX dispatch is async — the returned array materializes on the device
        while the host continues (used for video double-buffering). Call
        :func:`waternet_tpu.utils.tensor.ten2arr` on the result to sync.
        """
        if len(rgb_batch) == 0:
            # Without this the host-preprocess path dies in zip(*()) with
            # "not enough values to unpack" — opaque at three frames'
            # distance from the caller that built the empty batch.
            raise ValueError(
                "enhance_async got an empty batch: enhancement needs at "
                "least one (H, W, 3) frame"
            )
        self._validate_shape(rgb_batch)
        rgb_batch, n_real = self._pad_for_shards(rgb_batch)
        if self.device_preprocess:
            out = self._fused(self.params, jnp.asarray(rgb_batch))
        else:
            wb, gc, he = zip(*(transform_np(f) for f in rgb_batch))
            to_dev = lambda arrs: jnp.asarray(np.stack(arrs), jnp.float32) / 255.0
            out = self._forward(
                self.params, to_dev(list(rgb_batch)), to_dev(wb), to_dev(he),
                to_dev(gc),
            )
        return out[:n_real]

    # ------------------------------------------------------------------
    # Pad/crop-aware entry points (the shape-bucketed serving path,
    # waternet_tpu/serving/ + docs/SERVING.md)
    # ------------------------------------------------------------------

    def preprocess_padded(self, images, bucket_hw, n_slots=None):
        """Mixed-native-shape uint8 HWC images -> the network's four
        float32 input batches at one ``bucket_hw`` canvas shape.

        WB/GC/CLAHE are **always host-computed on the native image** here,
        regardless of ``device_preprocess``: they are global per-image
        statistics (quantiles, histograms), so computing them on a padded
        canvas would change every pixel, not just the seam band — the
        bucketing exactness policy (interior pixels bit-identical to the
        native forward) only holds when the pad is applied *after* the
        per-image transforms. Each of (x, wb, he, gc) is then
        bottom/right padded to ``bucket_hw`` and, when ``n_slots`` is
        given, the batch is padded to ``n_slots`` by repeating the last
        image (the conv forward is per-sample independent, so batch
        padding never changes a real sample's output).
        """
        from waternet_tpu.serving.bucketing import pad_to_bucket

        if not images:
            raise ValueError(
                "preprocess_padded got no images: serving batches are "
                "non-empty by construction"
            )
        bh, bw = bucket_hw
        quads = []
        for im in images:
            wb, gc, he = transform_np(im)
            quads.append(
                tuple(pad_to_bucket(a, bh, bw) for a in (im, wb, he, gc))
            )
        if n_slots is not None:
            if len(quads) > n_slots:
                raise ValueError(
                    f"{len(quads)} images exceed the compiled batch of "
                    f"{n_slots} slots"
                )
            quads.extend([quads[-1]] * (n_slots - len(quads)))
        x, wb, he, gc = (np.stack(arrs) for arrs in zip(*quads))
        to_dev = lambda a: jnp.asarray(a, jnp.float32) / 255.0
        return to_dev(x), to_dev(wb), to_dev(he), to_dev(gc)

    def aot_compile_padded(self, n_slots: int, bucket_hw):
        """AOT-build the forward executable for one (batch, bucket) shape
        via ``.lower().compile()`` — no dummy batch materialized, nothing
        inserted into the jit call cache. The serving warmup compiles one
        of these per bucket at startup so no request ever pays a compile;
        dispatch then calls the returned executable directly, which is
        why a mid-serve growth of ``_forward``'s jit cache is a test
        failure (tests/test_serving.py, compile_sentinel).
        """
        bh, bw = bucket_hw
        sds = jax.ShapeDtypeStruct((n_slots, bh, bw, 3), jnp.float32)
        return self._forward.lower(self.params, sds, sds, sds, sds).compile()

    def enhance_padded_async(
        self, images, bucket_hw, n_slots=None, executable=None
    ):
        """Launch the bucketed forward for ``images`` without blocking.

        Returns the device float batch at ``bucket_hw`` — callers crop
        row ``i`` back to ``images[i].shape`` (the serving batcher does;
        :func:`waternet_tpu.serving.bucketing` documents which cropped
        pixels are bit-identical to the native forward). ``executable``
        is an :meth:`aot_compile_padded` product; without one the call
        goes through the jit cache (compiling on first use per shape).
        """
        args = self.preprocess_padded(images, bucket_hw, n_slots)
        fwd = self._forward if executable is None else executable
        return fwd(self.params, *args)
