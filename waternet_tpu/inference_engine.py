"""Batched inference engine: uint8 frames in, enhanced uint8 frames out.

The single object behind the inference CLI and the video pipeline. Two
preprocessing modes:

* host (default): cv2/NumPy WB+GC+CLAHE per frame — bit-exact with the
  reference (`/root/reference/inference.py:177`);
* device: the batch's WB/GC/CLAHE run inside the same jitted XLA program as
  the network (`waternet_tpu.ops.transform_batch`), so the host only decodes
  frames. On a host-CPU-starved TPU VM this is the fast path.

Compiled executables are cached per input shape by jax's jit cache; video
(fixed shape) compiles once.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from waternet_tpu.models import WaterNet
from waternet_tpu.ops import transform_batch, transform_np
from waternet_tpu.hub import resolve_weights
from waternet_tpu.utils.tensor import ten2arr


class _ServingEngineBase:
    """Shared serving-interface plumbing for both tier engines.

    The serving layer (batcher / replica pool / warmup, docs/SERVING.md)
    talks to an *engine interface*, not to :class:`InferenceEngine`
    specifically: ``enhance_async`` (native-shape fallback),
    ``enhance_padded_async`` / ``aot_compile_padded`` (the bucketed
    path), ``replica_params`` (per-device placement), plus the
    ``data_shards`` / ``spatial_shards`` / ``device_preprocess`` /
    ``quantized`` attributes. This base holds the parts that are
    identical for the quality tier (:class:`InferenceEngine`) and the
    fast tier (:class:`StudentEngine`): sync wrappers, device placement,
    the bucket canvas padding, and the ShapeDtypeStruct builder AOT
    lowering uses.
    """

    data_shards = 1
    spatial_shards = 1
    device_preprocess = False
    quantized = False

    def enhance(self, rgb_batch: np.ndarray) -> np.ndarray:
        """(N, H, W, 3) uint8 RGB -> (N, H, W, 3) uint8 RGB enhanced."""
        return ten2arr(self.enhance_async(rgb_batch))

    def replica_params(self, device):
        """This engine's params placed on ``device`` — one copy per serving
        replica (waternet_tpu/serving/replicas.py). ``None`` returns the
        engine's own (default-device) params."""
        if device is None:
            return self.params
        return jax.device_put(self.params, device)

    def pad_raw_to_bucket(self, images, bucket_hw, n_slots=None):
        """Mixed-native-shape uint8 HWC images -> (uint8 canvas batch,
        (N, 2) int32 native shapes) at one ``bucket_hw`` canvas shape.

        Only the raw bytes are padded here (reflect, bottom/right); what
        happens to the canvas is the engine's business — the quality
        tier's device-preprocess program computes WB/GC/CLAHE statistics
        over the native region (ops/masked.py), the fast tier's student
        needs no per-image statistics at all. Batch padding repeats the
        last image (the conv forward is per-sample independent, so batch
        padding never changes a real sample's output).
        """
        from waternet_tpu.serving.bucketing import pad_to_bucket

        if not images:
            raise ValueError(
                "pad_raw_to_bucket got no images: serving batches are "
                "non-empty by construction"
            )
        bh, bw = bucket_hw
        canvases = [pad_to_bucket(im, bh, bw) for im in images]
        hw = [(im.shape[0], im.shape[1]) for im in images]
        if n_slots is not None:
            if len(canvases) > n_slots:
                raise ValueError(
                    f"{len(canvases)} images exceed the compiled batch of "
                    f"{n_slots} slots"
                )
            canvases.extend([canvases[-1]] * (n_slots - len(canvases)))
            hw.extend([hw[-1]] * (n_slots - len(hw)))
        return np.stack(canvases), np.asarray(hw, np.int32)

    def _serving_sds(self, shape, dtype, device):
        sharding = (
            None if device is None else jax.sharding.SingleDeviceSharding(device)
        )
        if sharding is None:
            return jax.ShapeDtypeStruct(shape, dtype)
        return jax.ShapeDtypeStruct(shape, dtype, sharding=sharding)


class InferenceEngine(_ServingEngineBase):
    def __init__(
        self,
        weights=None,
        params: Optional[dict] = None,
        device_preprocess: bool = False,
        dtype=jnp.float32,
        spatial_shards: int = 1,
        data_shards: int = 1,
        quantize: bool = False,
        calib_batches=None,
    ):
        """``spatial_shards > 1`` splits each image's height over that many
        devices with exact halo-exchange (see waternet_tpu.parallel.spatial)
        — for frames too large for one chip's HBM. Requires
        ``spatial_shards`` devices and H divisible by it with slabs >= 26
        rows.

        ``data_shards > 1`` shards the FRAME BATCH over that many devices
        (params replicated, XLA moves the shards; no collectives in the
        forward) — the throughput scale-out for video on a pod slice.
        Non-multiple batches pad transparently (last frame repeated), so
        send multiples of ``data_shards`` for full utilization. Composes
        with ``quantize`` and ``device_preprocess``; mutually exclusive
        with ``spatial_shards`` for now.

        ``quantize=True`` converts the checkpoint to static int8 at
        construction (see :mod:`waternet_tpu.models.quant`): int8 x int8
        convs ride the MXU's double-rate int8 path and halve activation HBM
        traffic — the fast path for full-resolution video. Activation
        scales calibrate on ``calib_batches`` ((x, wb, ce, gc) float tuples)
        or on synthetic frames by default; output typically agrees with the
        float forward to >40 dB PSNR."""
        from waternet_tpu.utils.platform import ensure_platform

        ensure_platform()
        self.module = WaterNet(dtype=dtype)
        if params is None:
            params = resolve_weights(weights)
        if params is None:
            raise FileNotFoundError(
                "No weights found — pass --weights, set WATERNET_TPU_WEIGHTS, "
                "or place a checkpoint in ./weights (native .npz or the "
                "reference's exported .pt, converted automatically)."
            )
        self.params = params
        self.device_preprocess = device_preprocess
        self.quantized = quantize

        self.spatial_shards = spatial_shards
        self.data_shards = data_shards
        if data_shards > 1 and spatial_shards > 1:
            raise ValueError(
                "data_shards and spatial_shards are mutually exclusive for "
                "now; pick batch scale-out OR single-frame decomposition"
            )
        if quantize:
            from waternet_tpu.models.quant import quant_forward, quantize_waternet

            # quant_forward(qtree, x, wb, ce, gc) has the same signature
            # shape as module.apply(params, ...), so the qtree simply
            # replaces the params for every downstream path.
            self.params = quantize_waternet(params, calib_batches)
            apply_fn = quant_forward
        else:
            apply_fn = self.module.apply

        if spatial_shards > 1:
            from waternet_tpu.parallel.mesh import make_mesh
            from waternet_tpu.parallel.spatial import spatial_sharded_apply

            mesh = make_mesh(n_data=1, n_spatial=spatial_shards)
            # Already jitted; do not wrap in another jax.jit layer. The
            # halo-exchange path takes the same functional forward the
            # single-device path uses (float or int8).
            _forward = spatial_sharded_apply(apply_fn, mesh)
        else:
            if data_shards > 1:
                from waternet_tpu.parallel.mesh import (
                    batch_sharding,
                    make_mesh,
                    replicated,
                )

                mesh = make_mesh(n_data=data_shards, n_spatial=1)
                bsh = batch_sharding(mesh)
                rep = replicated(mesh)
                _forward = jax.jit(
                    apply_fn,
                    in_shardings=(rep, bsh, bsh, bsh, bsh),
                    out_shardings=bsh,
                )
            else:
                _forward = jax.jit(apply_fn)

        def _fused(p, rgb_u8):
            """uint8 batch -> enhanced float batch, preprocessing on device."""
            wb, gc, he = transform_batch(rgb_u8)
            rgb = rgb_u8.astype(jnp.float32) / 255.0
            return _forward(p, rgb, wb / 255.0, he / 255.0, gc / 255.0)

        def _fused_padded(p, canvas_u8, hw):
            """Bucket-shaped uint8 canvases + native (h, w) -> enhanced
            float batch, preprocessing on device with native-first
            statistics (ops/masked.py) — the device-preprocess serving
            program (docs/SERVING.md)."""
            from waternet_tpu.ops.masked import transform_masked_batch

            wb, gc, he = transform_masked_batch(canvas_u8, hw[:, 0], hw[:, 1])
            rgb = canvas_u8.astype(jnp.float32) / 255.0
            return _forward(p, rgb, wb / 255.0, he / 255.0, gc / 255.0)

        self._forward = _forward
        if data_shards > 1:
            # Shard the raw uint8 batch at the boundary so preprocessing
            # runs shard-local too (no resharding between stages).
            self._fused = jax.jit(
                _fused, in_shardings=(rep, bsh), out_shardings=bsh
            )
            self._fused_padded = jax.jit(
                _fused_padded, in_shardings=(rep, bsh, bsh), out_shardings=bsh
            )
        else:
            self._fused = jax.jit(_fused)
            self._fused_padded = jax.jit(_fused_padded)

    def _pad_for_shards(self, rgb_batch):
        """-> (padded_batch, n_real). Shards need equal batch slices, so a
        batch that isn't a multiple of data_shards is padded by repeating
        the last frame (throughput-optimal callers send full multiples; the
        video CLI already pads whole clips to one compile shape). Leaves
        device arrays untouched on the fast path — enhance_async must not
        force a host round-trip."""
        n = rgb_batch.shape[0]
        if self.data_shards <= 1 or n % self.data_shards == 0:
            return rgb_batch, n
        from waternet_tpu.parallel.mesh import pad_to_multiple

        return pad_to_multiple(np.asarray(rgb_batch), self.data_shards)

    def _validate_shape(self, rgb_batch) -> None:
        if self.spatial_shards <= 1:
            return
        from waternet_tpu.parallel.spatial import HALO

        h = rgb_batch.shape[1]
        if h % self.spatial_shards != 0:
            raise ValueError(
                f"image height {h} not divisible by spatial_shards="
                f"{self.spatial_shards}"
            )
        if h // self.spatial_shards < 2 * HALO:
            raise ValueError(
                f"spatial slab of {h // self.spatial_shards} rows < "
                f"2*HALO={2 * HALO}; use fewer spatial shards for this height"
            )

    def enhance_async(self, rgb_batch: np.ndarray):
        """Launch enhancement without blocking; returns a device array future.

        JAX dispatch is async — the returned array materializes on the device
        while the host continues (used for video double-buffering). Call
        :func:`waternet_tpu.utils.tensor.ten2arr` on the result to sync.
        """
        if len(rgb_batch) == 0:
            # Without this the host-preprocess path dies in zip(*()) with
            # "not enough values to unpack" — opaque at three frames'
            # distance from the caller that built the empty batch.
            raise ValueError(
                "enhance_async got an empty batch: enhancement needs at "
                "least one (H, W, 3) frame"
            )
        self._validate_shape(rgb_batch)
        rgb_batch, n_real = self._pad_for_shards(rgb_batch)
        if self.device_preprocess:
            out = self._fused(self.params, jnp.asarray(rgb_batch))
        else:
            wb, gc, he = zip(*(transform_np(f) for f in rgb_batch))
            to_dev = lambda arrs: jnp.asarray(np.stack(arrs), jnp.float32) / 255.0
            out = self._forward(
                self.params, to_dev(list(rgb_batch)), to_dev(wb), to_dev(he),
                to_dev(gc),
            )
        return out[:n_real]

    # ------------------------------------------------------------------
    # Pad/crop-aware entry points (the shape-bucketed serving path,
    # waternet_tpu/serving/ + docs/SERVING.md)
    # ------------------------------------------------------------------

    def preprocess_padded(self, images, bucket_hw, n_slots=None, device=None):
        """Mixed-native-shape uint8 HWC images -> the network's four
        float32 input batches at one ``bucket_hw`` canvas shape.

        WB/GC/CLAHE are **always host-computed on the native image** here,
        regardless of ``device_preprocess``: they are global per-image
        statistics (quantiles, histograms), so computing them on a padded
        canvas would change every pixel, not just the seam band — the
        bucketing exactness policy (interior pixels bit-identical to the
        native forward) only holds when the pad is applied *after* the
        per-image transforms. Each of (x, wb, he, gc) is then
        bottom/right padded to ``bucket_hw`` and, when ``n_slots`` is
        given, the batch is padded to ``n_slots`` by repeating the last
        image (the conv forward is per-sample independent, so batch
        padding never changes a real sample's output).
        """
        from waternet_tpu.serving.bucketing import pad_to_bucket

        if not images:
            raise ValueError(
                "preprocess_padded got no images: serving batches are "
                "non-empty by construction"
            )
        bh, bw = bucket_hw
        quads = []
        for im in images:
            wb, gc, he = transform_np(im)
            quads.append(
                tuple(pad_to_bucket(a, bh, bw) for a in (im, wb, he, gc))
            )
        if n_slots is not None:
            if len(quads) > n_slots:
                raise ValueError(
                    f"{len(quads)} images exceed the compiled batch of "
                    f"{n_slots} slots"
                )
            quads.extend([quads[-1]] * (n_slots - len(quads)))
        x, wb, he, gc = (np.stack(arrs) for arrs in zip(*quads))
        if device is None:
            to_dev = lambda a: jnp.asarray(a, jnp.float32) / 255.0
        else:
            # Per-replica placement: commit the host batch to the replica's
            # device so the /255 (and the forward it feeds) run there.
            to_dev = (
                lambda a: jax.device_put(a.astype(np.float32), device) / 255.0
            )
        return to_dev(x), to_dev(wb), to_dev(he), to_dev(gc)

    def aot_compile_padded(self, n_slots: int, bucket_hw, device=None, params=None):
        """AOT-build the serving executable for one (batch, bucket) shape
        via ``.lower().compile()`` — no dummy batch materialized, nothing
        inserted into any jit call cache. The serving warmup compiles one
        of these per (bucket, replica) at startup so no request ever pays
        a compile; dispatch then calls the returned executable directly,
        which is why a mid-serve growth of the engine's jit caches is a
        test failure (tests/test_serving.py, compile_sentinel).

        Host-preprocess engines get the forward-only program (four float
        input planes); ``device_preprocess`` engines get the fused padded
        program (uint8 canvases + native shapes -> masked transforms ->
        forward, ops/masked.py). ``device`` pins the executable (and its
        lowering-time ``params``, a :meth:`replica_params` product) to one
        local device — the serving replica pool's placement; sharded
        engines lower through their own mesh shardings instead and must
        pass ``device=None``.
        """
        if device is not None and (self.data_shards > 1 or self.spatial_shards > 1):
            raise ValueError(
                "per-device serving executables are for unsharded engines; "
                "a sharded engine's executables span its mesh already"
            )
        p = self.params if params is None else params
        bh, bw = bucket_hw
        if self.device_preprocess:
            canvas = self._serving_sds((n_slots, bh, bw, 3), jnp.uint8, device)
            hw = self._serving_sds((n_slots, 2), jnp.int32, device)
            return self._fused_padded.lower(p, canvas, hw).compile()
        sds = self._serving_sds((n_slots, bh, bw, 3), jnp.float32, device)
        return self._forward.lower(p, sds, sds, sds, sds).compile()

    def enhance_padded_async(
        self, images, bucket_hw, n_slots=None, executable=None, params=None,
        device=None,
    ):
        """Launch the bucketed forward for ``images`` without blocking.

        Returns the device float batch at ``bucket_hw`` — callers crop
        row ``i`` back to ``images[i].shape`` (the serving batcher does;
        :func:`waternet_tpu.serving.bucketing` documents which cropped
        pixels are bit-identical to the native forward). ``executable``
        is an :meth:`aot_compile_padded` product; without one the call
        goes through the jit cache (compiling on first use per shape).
        ``params``/``device`` place the call on a specific replica
        (waternet_tpu/serving/replicas.py); by default the engine's own
        params and the platform default device are used.
        """
        p = self.params if params is None else params
        if self.device_preprocess:
            canvas, hw = self.pad_raw_to_bucket(images, bucket_hw, n_slots)
            if device is None:
                put = jnp.asarray
            else:
                put = lambda a: jax.device_put(a, device)
            fwd = self._fused_padded if executable is None else executable
            return fwd(p, put(canvas), put(hw))
        args = self.preprocess_padded(images, bucket_hw, n_slots, device=device)
        fwd = self._forward if executable is None else executable
        return fwd(p, *args)


class StudentEngine(_ServingEngineBase):
    """Fast-tier inference engine: the distilled CAN student
    (waternet_tpu/models/can.py), raw uint8 frames in, enhanced uint8
    frames out — no WB/GC/CLAHE anywhere, on host or device.

    Implements the same serving interface as :class:`InferenceEngine`
    (enhance / enhance_async / aot_compile_padded / enhance_padded_async
    / replica_params), so the dynamic batcher serves it as its own
    AOT-warmed executable grid under the existing bucket ladder and
    replica pool (``DynamicBatcher(fast_engine=...)``, per-request
    ``tier="fast"`` routing — docs/SERVING.md "Quality tiers"). The
    bucketed program is ONE fused XLA program: uint8 canvas -> /255 ->
    student forward; there is no separate preprocessing stage to fuse.

    ``quantize=True`` converts the checkpoint to static int8 at
    construction (:func:`waternet_tpu.models.quant.quantize_can`) — the
    MXU double-rate path, with the int8-vs-float error bound pinned in
    tests/test_quant.py. Sharding is out of scope for the student (its
    whole point is fitting comfortably on one chip), so the engine is
    always one-device-per-replica.
    """

    def __init__(
        self,
        weights=None,
        params: Optional[dict] = None,
        dtype=jnp.float32,
        quantize: bool = False,
        calib_batches=None,
    ):
        from waternet_tpu.models import CANStudent
        from waternet_tpu.models.can import can_config_from_params
        from waternet_tpu.utils.platform import ensure_platform

        ensure_platform()
        if params is None:
            if weights is None:
                raise FileNotFoundError(
                    "the fast tier needs explicit student weights — pass "
                    "--student-weights (a train.py --distill product); the "
                    "implicit ./weights resolution is reserved for the "
                    "quality-tier teacher checkpoint"
                )
            params = resolve_weights(weights)
        # Infers (width, depth) AND validates the tree fits CANStudent —
        # incl. the loud tier/weights-mismatch error when someone points
        # the fast tier at a WaterNet checkpoint.
        width, depth = can_config_from_params(params)
        self.width, self.depth = width, depth
        self.module = CANStudent(width=width, depth=depth, dtype=dtype)
        self.params = params
        self.quantized = quantize

        if quantize:
            from waternet_tpu.models.quant import can_quant_forward, quantize_can

            self.params = quantize_can(params, calib_batches)
            apply_fn = can_quant_forward
        else:
            apply_fn = self.module.apply

        _forward = jax.jit(apply_fn)

        def _fused(p, rgb_u8):
            """uint8 batch (native OR bucket canvas) -> enhanced float
            batch; the student consumes raw RGB only, so the native and
            padded serving programs are the same shape-generic function."""
            return _forward(p, rgb_u8.astype(jnp.float32) / 255.0)

        self._forward = _forward
        self._fused = jax.jit(_fused)

    def enhance_async(self, rgb_batch: np.ndarray):
        """Launch enhancement without blocking; returns a device array
        future (the oversize-fallback path goes through the jit cache,
        compiling once per unique shape — same contract as the quality
        engine)."""
        if len(rgb_batch) == 0:
            raise ValueError(
                "enhance_async got an empty batch: enhancement needs at "
                "least one (H, W, 3) frame"
            )
        return self._fused(self.params, jnp.asarray(rgb_batch))

    def aot_compile_padded(self, n_slots: int, bucket_hw, device=None, params=None):
        """AOT-build the fast tier's serving executable for one (batch,
        bucket) shape — same ``.lower().compile()`` discipline as the
        quality engine, so warmup builds the whole grid and no request
        ever pays a compile (the zero-mid-serve-jit-growth sentinel
        guarantee covers both tiers, tests/test_tiers.py)."""
        p = self.params if params is None else params
        bh, bw = bucket_hw
        canvas = self._serving_sds((n_slots, bh, bw, 3), jnp.uint8, device)
        return self._fused.lower(p, canvas).compile()

    def enhance_padded_async(
        self, images, bucket_hw, n_slots=None, executable=None, params=None,
        device=None,
    ):
        """Launch the bucketed student forward without blocking; returns
        the device float batch at ``bucket_hw`` (callers crop row ``i``
        back to ``images[i].shape``). Padding is reflect, bottom/right;
        the student has no global per-image statistics, so padding only
        touches the seam band within the CAN receptive radius
        (:func:`waternet_tpu.models.can.can_receptive_radius` — 64 px at
        the default depth, vs the teacher's 13)."""
        p = self.params if params is None else params
        canvas, _ = self.pad_raw_to_bucket(images, bucket_hw, n_slots)
        put = jnp.asarray if device is None else (
            lambda a: jax.device_put(a, device)
        )
        fwd = self._fused if executable is None else executable
        return fwd(p, put(canvas))
