"""8-bit RGB <-> CIELAB color conversion in pure JAX.

Needed by the on-device CLAHE path (:mod:`waternet_tpu.ops.clahe`): the
reference runs CLAHE on the L channel of an OpenCV LAB conversion
(`/root/reference/waternet/data.py:68-78`).

These functions implement the standard sRGB(D65) <-> CIELAB formulas with
OpenCV's 8-bit scaling convention (L in [0,255] via *255/100, a/b offset by
+128). OpenCV's uint8 path uses fixed-point interpolation tables, so results
can differ from this float implementation by ~1 intensity level; the host
path (cv2) remains the bit-exact-parity default, and the device path is
tolerance-tested against it.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# sRGB D65 forward matrix and whitepoint (as used by OpenCV's Lab code).
# NumPy (not jnp) on purpose: module-level jnp arrays would initialize the
# jax backend at import time, before CLIs can pick a platform.
_RGB2XYZ = np.array(
    [
        [0.412453, 0.357580, 0.180423],
        [0.212671, 0.715160, 0.072169],
        [0.019334, 0.119193, 0.950227],
    ],
    dtype=np.float32,
)
_XYZ2RGB = np.array(
    [
        [3.240479, -1.537150, -0.498535],
        [-0.969256, 1.875992, 0.041556],
        [0.055648, -0.204043, 1.057311],
    ],
    dtype=np.float32,
)
_WHITE = np.array([0.950456, 1.0, 1.088754], dtype=np.float32)
_LAB_T0 = 0.008856
_LAB_K = 7.787


def _srgb_to_linear(v):
    return jnp.where(v > 0.04045, jnp.power((v + 0.055) / 1.055, 2.4), v / 12.92)


def _linear_to_srgb(v):
    return jnp.where(
        v > 0.0031308, 1.055 * jnp.power(jnp.maximum(v, 0.0), 1.0 / 2.4) - 0.055, 12.92 * v
    )


def _lab_f(t):
    return jnp.where(t > _LAB_T0, jnp.cbrt(t), _LAB_K * t + 16.0 / 116.0)


def _lab_f_inv(f):
    t3 = f * f * f
    return jnp.where(t3 > _LAB_T0, t3, (f - 16.0 / 116.0) / _LAB_K)


def rgb_to_lab_u8(rgb: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) uint8-valued RGB -> (..., 3) float32 holding 8-bit LAB values.

    Output channels: L in [0,255] (scaled *255/100), a/b offset by +128 —
    OpenCV's 8-bit LAB convention, rounded to integers.
    """
    x = _srgb_to_linear(rgb.astype(jnp.float32) / 255.0)
    xyz = x @ _RGB2XYZ.T / _WHITE
    f = _lab_f(xyz)
    fx, fy, fz = f[..., 0], f[..., 1], f[..., 2]
    lum = 116.0 * fy - 16.0
    a = 500.0 * (fx - fy)
    b = 200.0 * (fy - fz)
    lab = jnp.stack([lum * 255.0 / 100.0, a + 128.0, b + 128.0], axis=-1)
    return jnp.clip(jnp.round(lab), 0.0, 255.0)


def lab_u8_to_rgb(lab: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) float32 8-bit LAB values -> (..., 3) float32 uint8-valued RGB."""
    lum = lab[..., 0] * 100.0 / 255.0
    a = lab[..., 1] - 128.0
    b = lab[..., 2] - 128.0
    fy = (lum + 16.0) / 116.0
    f = jnp.stack([fy + a / 500.0, fy, fy - b / 200.0], axis=-1)
    xyz = _lab_f_inv(f) * _WHITE
    rgb_lin = xyz @ _XYZ2RGB.T
    rgb = _linear_to_srgb(rgb_lin)
    return jnp.clip(jnp.round(rgb * 255.0), 0.0, 255.0)
