"""8-bit RGB <-> CIELAB color conversion in pure JAX.

Needed by the on-device CLAHE path (:mod:`waternet_tpu.ops.clahe`): the
reference runs CLAHE on the L channel of an OpenCV LAB conversion
(`/root/reference/waternet/data.py:68-78`).

The forward direction (:func:`rgb_to_lab_u8`) replicates OpenCV's uint8
fixed-point pipeline exactly (modules/imgproc/src/color_lab.cpp,
``RGB2Lab_b``): a 256-entry sRGB gamma table scaled by 8, a 12-bit
fixed-point XYZ matrix with D65 whitepoint folded in, a 3072-entry
cube-root table scaled by 2^15, and ``CV_DESCALE`` integer rounding —
**bit-exact vs cv2 over the entire 256^3 input domain** (exhaustively
verified; tables are built with float32 arithmetic because OpenCV's
``softfloat`` is IEEE binary32). The CLAHE L channel therefore matches the
host path bit-for-bit.

The inverse (:func:`lab_u8_to_rgb`) uses the standard float formulas with
OpenCV's 8-bit scaling convention (L in [0,255] via *255/100, a/b offset by
+128); cv2's integer inverse differs by at most 3 levels on <0.003% of the
full LAB-u8 cube (exhaustively characterized), and the host path remains
the bit-exact-parity default.

The inverse's linear->sRGB transfer has two device implementations,
selected at trace time by ``WATERNET_SRGB_TRANSFER``:

- ``poly`` (default): degree-10 Chebyshev-derived polynomial in
  ``t = x**0.25`` — two ``sqrt`` plus an FMA chain, no transcendentals.
  The TPU vector unit lowers ``pow`` to ``exp(log)`` (multi-cycle
  transcendentals); sqrt+FMA is the cheap path, and the CPU per-op
  breakdown (docs/RESULTS.md) showed the float inverse costing as much
  as the whole CLAHE core. Approximation error is <4e-5 of one 8-bit
  output level (fit characterized in tests), so disagreements with the
  float path can occur only for inputs within float32 roundoff of a
  rounding boundary: exhaustive LAB-cube characterization found the two
  paths bit-identical except ±1 level on 4.5e-6 of the cube, leaving the
  cv2 parity bound literally unchanged (max 3 levels, >1 level on
  1.06e-5 of the cube — identical for both transfers).
- ``float``: the literal ``1.055 * x**(1/2.4) - 0.055`` formula (the
  round-1/2 device path), kept for on-hardware A/B measurement.
"""

from __future__ import annotations

import os

import jax.numpy as jnp
import numpy as np

# sRGB D65 forward matrix and whitepoint (as used by OpenCV's Lab code).
# NumPy (not jnp) on purpose: module-level jnp arrays would initialize the
# jax backend at import time, before CLIs can pick a platform.
_RGB2XYZ = np.array(
    [
        [0.412453, 0.357580, 0.180423],
        [0.212671, 0.715160, 0.072169],
        [0.019334, 0.119193, 0.950227],
    ],
    dtype=np.float32,
)
_XYZ2RGB = np.array(
    [
        [3.240479, -1.537150, -0.498535],
        [-0.969256, 1.875992, 0.041556],
        [0.055648, -0.204043, 1.057311],
    ],
    dtype=np.float32,
)
_WHITE = np.array([0.950456, 1.0, 1.088754], dtype=np.float32)
_LAB_T0 = 0.008856
_LAB_K = 7.787


_SRGB_CUT = 0.0031308


def _build_srgb_poly():
    """Degree-10 polynomial approximation of ``t -> t**(5/3)`` on
    ``t in [cut**0.25, 1]``, power basis in the Chebyshev window variable
    ``s = (2t - (1+a)) / (1-a)`` (well-conditioned; raw-``t`` monomial
    coefficients cancel). With ``t = x**0.25``, ``p(s(t)) ~= x**(1/2.4)``:
    float32 Horner error <= 1.6e-7, i.e. <4.2e-5 of one 8-bit level after
    the 1.055/255 scaling — the same order as float32 ``pow`` itself.
    """
    a = _SRGB_CUT**0.25
    ch = np.polynomial.chebyshev.Chebyshev.interpolate(
        lambda t: t ** (5.0 / 3.0), 10, domain=[a, 1.0]
    )
    coef = np.polynomial.chebyshev.cheb2poly(ch.coef).astype(np.float32)
    scale = np.float32(2.0 / (1.0 - a))
    offset = np.float32(-(1.0 + a) / (1.0 - a))
    return coef, scale, offset


_SRGB_POLY_COEF, _SRGB_POLY_SCALE, _SRGB_POLY_OFFSET = _build_srgb_poly()


def _srgb_transfer_mode() -> str:
    """Trace-time selection of the linear->sRGB transfer implementation.

    ``poly`` (default) is the sqrt+FMA path; ``float`` is the literal
    ``pow(x, 1/2.4)`` formula kept for A/B measurement. Unknown values are
    an error: a typo must not silently change the measured path.
    """
    mode = os.environ.get("WATERNET_SRGB_TRANSFER", "poly").strip().lower()
    if mode not in ("poly", "float"):
        raise ValueError(
            f"WATERNET_SRGB_TRANSFER={mode!r}: expected 'poly' or 'float'"
        )
    return mode


def _linear_to_srgb(v):
    if _srgb_transfer_mode() == "float":
        return jnp.where(
            v > _SRGB_CUT,
            1.055 * jnp.power(jnp.maximum(v, 0.0), 1.0 / 2.4) - 0.055,
            12.92 * v,
        )
    # poly: clamp to [cut, 1] (x > 1 is out-of-gamut and clips to 255
    # downstream either way — p(1) = 1.0 exactly), substitute t = x**0.25
    # (two sqrts), Horner in the window variable.
    t = jnp.sqrt(jnp.sqrt(jnp.clip(v, _SRGB_CUT, 1.0)))
    s = t * _SRGB_POLY_SCALE + _SRGB_POLY_OFFSET
    acc = jnp.full_like(s, _SRGB_POLY_COEF[-1])
    for k in range(len(_SRGB_POLY_COEF) - 2, -1, -1):
        acc = acc * s + _SRGB_POLY_COEF[k]
    return jnp.where(v > _SRGB_CUT, 1.055 * acc - 0.055, 12.92 * v)


def _lab_f_inv(f):
    t3 = f * f * f
    return jnp.where(t3 > _LAB_T0, t3, (f - 16.0 / 116.0) / _LAB_K)


# ---------------------------------------------------------------------------
# OpenCV 8U fixed-point forward tables (built once, in NumPy, at import;
# float32 arithmetic where OpenCV uses softfloat — IEEE binary32).
# ---------------------------------------------------------------------------

_GAMMA_SHIFT = 3
_LAB_FP_SHIFT = 12
_LAB_FP_SHIFT2 = _LAB_FP_SHIFT + _GAMMA_SHIFT  # 15


def _build_u8_tables():
    i = np.arange(256, dtype=np.float32)
    x = i / np.float32(255.0)
    g = np.where(
        x <= np.float32(0.04045),
        x / np.float32(12.92),
        np.power((x + np.float32(0.055)) / np.float32(1.055), np.float32(2.4)),
    )
    gamma_tab = np.rint(
        255.0 * (1 << _GAMMA_SHIFT) * g.astype(np.float64)
    ).astype(np.int32)

    n = 256 * 3 // 2 * (1 << _GAMMA_SHIFT)  # 3072
    xx = np.arange(n, dtype=np.float32) / np.float32(255 * (1 << _GAMMA_SHIFT))
    f = np.where(
        xx < np.float32(216.0 / 24389.0),
        np.float32(841.0 / 108.0) * xx + np.float32(16.0 / 116.0),
        np.cbrt(xx),
    )
    cbrt_tab = np.rint(
        float(1 << _LAB_FP_SHIFT2) * f.astype(np.float64)
    ).astype(np.int32)

    coeffs = np.rint(
        (1 << _LAB_FP_SHIFT) * _RGB2XYZ.astype(np.float64) / _WHITE[:, None].astype(np.float64)
    ).astype(np.int32)
    return gamma_tab, cbrt_tab, coeffs


_U8_GAMMA_TAB, _U8_CBRT_TAB, _U8_XYZ_COEFFS = _build_u8_tables()
_U8_LSCALE = (116 * 255 + 50) // 100  # 296
_U8_LSHIFT = -((16 * 255 * (1 << _LAB_FP_SHIFT2) + 50) // 100)


def _descale(v, n):
    # CV_DESCALE: round-to-nearest via add-half then arithmetic shift.
    return jnp.right_shift(v + (1 << (n - 1)), n)


def rgb_to_lab_u8(rgb: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) uint8-valued RGB -> (..., 3) float32 holding 8-bit LAB values.

    Output channels: L in [0,255] (scaled *255/100), a/b offset by +128 —
    OpenCV's 8-bit LAB convention. Bit-exact vs ``cv2.cvtColor(...,
    COLOR_RGB2LAB)`` for every possible input (see module docstring); all
    intermediates fit int32.
    """
    v = rgb.astype(jnp.int32)
    gamma = jnp.asarray(_U8_GAMMA_TAB)
    cbrt = jnp.asarray(_U8_CBRT_TAB)
    c = _U8_XYZ_COEFFS  # static numpy ints -> python constants below
    r, g, b = gamma[v[..., 0]], gamma[v[..., 1]], gamma[v[..., 2]]

    def frow(i):
        acc = r * int(c[i, 0]) + g * int(c[i, 1]) + b * int(c[i, 2])
        return cbrt[_descale(acc, _LAB_FP_SHIFT)]

    fx, fy, fz = frow(0), frow(1), frow(2)
    lum = _descale(_U8_LSCALE * fy + _U8_LSHIFT, _LAB_FP_SHIFT2)
    a = _descale(500 * (fx - fy) + (128 << _LAB_FP_SHIFT2), _LAB_FP_SHIFT2)
    bb = _descale(200 * (fy - fz) + (128 << _LAB_FP_SHIFT2), _LAB_FP_SHIFT2)
    lab = jnp.stack([lum, a, bb], axis=-1)
    return jnp.clip(lab, 0, 255).astype(jnp.float32)


def lab_u8_to_rgb(lab: jnp.ndarray) -> jnp.ndarray:
    """(..., 3) float32 8-bit LAB values -> (..., 3) float32 uint8-valued RGB."""
    lum = lab[..., 0] * 100.0 / 255.0
    a = lab[..., 1] - 128.0
    b = lab[..., 2] - 128.0
    fy = (lum + 16.0) / 116.0
    f = jnp.stack([fy + a / 500.0, fy, fy - b / 200.0], axis=-1)
    xyz = _lab_f_inv(f) * _WHITE
    rgb_lin = xyz @ _XYZ2RGB.T
    rgb = _linear_to_srgb(rgb_lin)
    return jnp.clip(jnp.round(rgb * 255.0), 0.0, 255.0)
