"""The WaterNet preprocessing transform: rgb -> (wb, gc, he).

Mirrors the reference wrapper (`/root/reference/waternet/data.py:81-90`),
including its return order quirk: the wrapper returns ``(wb, gc, he)`` while
the model consumes ``(x, wb, he, gc)`` — callers are responsible for the
reordering, exactly as in the reference
(`/root/reference/train.py:108`, `/root/reference/hubconf.py:85-91`).

Host path: :func:`transform_np` (NumPy + cv2, bit-exact vs reference).
Device path: :func:`transform` (pure JAX, jittable) and
:func:`transform_batch` (vmapped over a leading batch axis) — this is what
lets preprocessing run fused with the model inside one XLA program instead of
serializing on the host CPU.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from waternet_tpu.ops.clahe import histeq, histeq_np
from waternet_tpu.ops.gamma import gamma_correction, gamma_correction_np
from waternet_tpu.ops.wb import white_balance, white_balance_np


def transform_np(rgb: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Host path. uint8 HWC RGB -> (wb, gc, he) uint8 HWC."""
    return white_balance_np(rgb), gamma_correction_np(rgb), histeq_np(rgb)


def transform(rgb: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Device path for one image.

    Args:
        rgb: (H, W, 3) uint8-valued array.
    Returns:
        (wb, gc, he): float32 (H, W, 3) arrays holding exact uint8 values
        in [0, 255] — divide by 255 to feed the network.
    """
    return white_balance(rgb), gamma_correction(rgb), histeq(rgb)


transform_batch = jax.vmap(transform)
transform_batch.__doc__ = """Batched device path: (N, H, W, 3) -> 3x (N, H, W, 3) float32."""
