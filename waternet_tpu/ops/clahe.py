"""CLAHE (contrast-limited adaptive histogram equalization) + the `histeq`
transform.

Behavioral spec from the reference (`/root/reference/waternet/data.py:68-78`):
RGB -> LAB, OpenCV CLAHE with ``clipLimit=0.1, tileGridSize=(8, 8)`` applied
to the L channel, LAB -> RGB.

Host path (:func:`histeq_np`) uses cv2 directly — bit-exact reference parity.

Device path (:func:`clahe`, :func:`histeq`) is a pure-JAX re-implementation of
OpenCV's CLAHE algorithm (modules/imgproc/src/clahe.cpp), exact in the integer
pipeline given the same L input:

1. Pad right/bottom with reflect-101 so H, W divide the tile grid.
2. Per-tile 256-bin histograms (scatter-add — avoids a (tiles, pixels, 256)
   one-hot blowup at 1080p).
3. Integer clip limit ``max(int(clipLimit * tileArea / 256), 1)`` — note with
   the reference's clipLimit=0.1 this is the minimum value 1, i.e. maximal
   clipping: the equalization mostly rank-equalizes the *distinct* gray
   levels present in each tile.
4. Excess redistribution: ``+excess//256`` to every bin, then the remaining
   ``r = excess % 256`` increments go to bins ``k * max(256//r, 1)`` for
   ``k < r`` (vectorized form of OpenCV's residual loop).
5. LUT = round(cdf * 255 / tileArea) (round-half-to-even, as cvRound).
6. Per-pixel bilinear interpolation between the 4 surrounding tile LUTs with
   OpenCV's ``(x / tile_w) - 0.5`` tile coordinates and edge clamping.

Differences vs cv2 can only come from the L channel itself (float vs
fixed-point LAB conversion, see :mod:`waternet_tpu.ops.color`): given cv2's
own L input, :func:`clahe` is bit-exact vs ``cv2.CLAHE.apply`` (tested).
End-to-end ``histeq`` differs from the host path on the ~12% of pixels whose
L value lands one level off, which the rank-equalizing LUT amplifies —
bounded by tolerance tests; the host path remains the parity path.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from waternet_tpu.ops.color import lab_u8_to_rgb, rgb_to_lab_u8

CLIP_LIMIT = 0.1  # reference `data.py:71`
TILE_GRID = (8, 8)  # reference `data.py:71`


# ---------------------------------------------------------------------------
# Host path (cv2) — reference parity.
# ---------------------------------------------------------------------------


def histeq_np(rgb: np.ndarray) -> np.ndarray:
    """uint8 HWC RGB -> uint8 HWC RGB. Bit-exact with the reference."""
    import cv2

    lab = cv2.cvtColor(rgb, cv2.COLOR_RGB2LAB)
    clahe = cv2.createCLAHE(clipLimit=CLIP_LIMIT, tileGridSize=TILE_GRID)
    out = lab.copy()
    out[:, :, 0] = clahe.apply(lab[:, :, 0])
    return cv2.cvtColor(out, cv2.COLOR_LAB2RGB)


# ---------------------------------------------------------------------------
# Device path (pure JAX).
# ---------------------------------------------------------------------------


def clahe(
    l_chan: jnp.ndarray,
    clip_limit: float = CLIP_LIMIT,
    tile_grid: tuple[int, int] = TILE_GRID,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """OpenCV-exact CLAHE on one channel.

    Args:
        l_chan: (H, W) uint8-valued array (any real dtype).
    Returns:
        (H, W) float32 holding exact uint8 values.
    """
    h, w = l_chan.shape
    ty, tx = tile_grid
    pad_h = (-h) % ty
    pad_w = (-w) % tx
    x = l_chan.astype(jnp.int32)
    if pad_h or pad_w:
        x = jnp.pad(x, ((0, pad_h), (0, pad_w)), mode="reflect")
    hp, wp = h + pad_h, w + pad_w
    th, tw = hp // ty, wp // tx
    n_tiles = ty * tx
    tile_area = th * tw

    # --- per-tile histograms ---
    tiles = x.reshape(ty, th, tx, tw).transpose(0, 2, 1, 3).reshape(n_tiles, tile_area)
    if use_pallas is None:
        from waternet_tpu.ops.pallas_kernels import pallas_enabled

        use_pallas = pallas_enabled()
    if use_pallas:
        # Dense VPU comparison-reduction kernel (scatter-free).
        from waternet_tpu.ops.pallas_kernels import tile_histogram

        hist = tile_histogram(tiles)
    else:
        # XLA path: bincount lowers to scatter-add.
        tile_ids = jnp.repeat(jnp.arange(n_tiles, dtype=jnp.int32), tile_area)
        flat_idx = tile_ids * 256 + tiles.reshape(-1)
        hist = jnp.bincount(flat_idx, length=n_tiles * 256).reshape(n_tiles, 256)

    # --- clip + redistribute (OpenCV integer semantics) ---
    clip = max(int(clip_limit * tile_area / 256.0), 1)
    excess = jnp.sum(jnp.maximum(hist - clip, 0), axis=-1)  # (T,)
    hist = jnp.minimum(hist, clip)
    hist = hist + (excess // 256)[:, None]
    residual = excess % 256  # always < 256
    step = jnp.maximum(256 // jnp.maximum(residual, 1), 1)  # (T,)
    bins = jnp.arange(256, dtype=jnp.int32)
    inc = (
        (residual[:, None] > 0)
        & (bins[None, :] % step[:, None] == 0)
        & (bins[None, :] // step[:, None] < residual[:, None])
    )
    hist = hist + inc.astype(jnp.int32)

    # --- LUTs: rounded scaled CDF ---
    lut_scale = 255.0 / tile_area
    cdf = jnp.cumsum(hist, axis=-1).astype(jnp.float32)
    luts = jnp.clip(jnp.round(cdf * lut_scale), 0.0, 255.0)  # (T, 256)
    luts = luts.reshape(ty, tx, 256)

    # --- bilinear interpolation between tile LUTs (over the original area) ---
    # OpenCV computes tile coords as x * (1/tile_size) with a float32
    # reciprocal (not a division); matching that exactly is what makes the
    # rounding ties land identically (verified bit-exact vs cv2).
    inv_th = np.float32(1.0) / np.float32(th)
    inv_tw = np.float32(1.0) / np.float32(tw)
    yy = jnp.arange(h, dtype=jnp.float32) * inv_th - np.float32(0.5)
    xx = jnp.arange(w, dtype=jnp.float32) * inv_tw - np.float32(0.5)
    y1 = jnp.floor(yy).astype(jnp.int32)
    x1 = jnp.floor(xx).astype(jnp.int32)
    ya = (yy - y1.astype(jnp.float32))[:, None]
    xa = (xx - x1.astype(jnp.float32))[None, :]
    y2 = jnp.minimum(y1 + 1, ty - 1)
    x2 = jnp.minimum(x1 + 1, tx - 1)
    y1 = jnp.maximum(y1, 0)
    x1 = jnp.maximum(x1, 0)

    v = l_chan.astype(jnp.int32)

    def look(yi, xi):
        # luts[yi[r], xi[c], v[r, c]] for every pixel.
        return luts[yi[:, None], xi[None, :], v]

    res = (look(y1, x1) * (1.0 - xa) + look(y1, x2) * xa) * (1.0 - ya) + (
        look(y2, x1) * (1.0 - xa) + look(y2, x2) * xa
    ) * ya
    return jnp.clip(jnp.round(res), 0.0, 255.0)


def histeq(rgb: jnp.ndarray) -> jnp.ndarray:
    """Device-path `histeq`: (H, W, 3) uint8-valued RGB -> float32 uint8 values.

    RGB -> LAB (float approximation of cv2), OpenCV-exact CLAHE on L,
    LAB -> RGB. Jittable; vmap for batches.
    """
    lab = rgb_to_lab_u8(rgb)
    el = clahe(lab[..., 0])
    lab = lab.at[..., 0].set(el)
    return lab_u8_to_rgb(lab)
