"""CLAHE (contrast-limited adaptive histogram equalization) + the `histeq`
transform.

Behavioral spec from the reference (`/root/reference/waternet/data.py:68-78`):
RGB -> LAB, OpenCV CLAHE with ``clipLimit=0.1, tileGridSize=(8, 8)`` applied
to the L channel, LAB -> RGB.

Host path (:func:`histeq_np`) uses cv2 directly — bit-exact reference parity.

Device path (:func:`clahe`, :func:`histeq`) is a pure-JAX re-implementation of
OpenCV's CLAHE algorithm (modules/imgproc/src/clahe.cpp), exact in the integer
pipeline given the same L input:

1. Pad right/bottom with reflect-101 so H, W divide the tile grid.
2. Per-tile 256-bin histograms, three strategies (``WATERNET_CLAHE_HIST`` /
   ``use_pallas``): XLA scatter-add (CPU default; no intermediate),
   one-hot MXU matmul (TPU default; int8 operands by default — see
   ``_onehot_dtypes`` — lax.scan-chunked so the one-hot stays under a
   64 MB cap at any frame size), or the Pallas comparison-reduction
   kernel — which, in the pallas mode, FUSES steps 2-5 into one kernel
   (``pallas_kernels.tile_lut``: histogram, clip, redistribution, CDF and
   LUT never leave VMEM; bit-identical to the lax pipeline).
3. Integer clip limit ``max(int(clipLimit * tileArea / 256), 1)`` — note with
   the reference's clipLimit=0.1 this is the minimum value 1, i.e. maximal
   clipping: the equalization mostly rank-equalizes the *distinct* gray
   levels present in each tile.
4. Excess redistribution: ``+excess//256`` to every bin, then the remaining
   ``r = excess % 256`` increments go to bins ``k * max(256//r, 1)`` for
   ``k < r`` (vectorized form of OpenCV's residual loop).
5. LUT = round(cdf * 255 / tileArea) (round-half-to-even, as cvRound).
6. Per-pixel bilinear interpolation between the 4 surrounding tile LUTs with
   OpenCV's ``(x / tile_w) - 0.5`` tile coordinates and edge clamping —
   three strategies (``WATERNET_CLAHE_INTERP`` / ``use_pallas``): gather
   (CPU default), batched one-hot MXU matmul over the cell decomposition
   (TPU default), or the fused Pallas lookup+blend kernel
   (``pallas_kernels.clahe_lut_planes``), all bit-identical.

The L channel fed to CLAHE is bit-exact vs cv2 too (the forward LAB
conversion replicates OpenCV's uint8 fixed-point pipeline — see
:mod:`waternet_tpu.ops.color`), so end-to-end ``histeq`` differs from the
host path only through the float LAB->RGB inverse: at most a few levels on
a few percent of pixels (bounded by tests); the host path remains the
strict parity path.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from waternet_tpu.ops.color import lab_u8_to_rgb, rgb_to_lab_u8

CLIP_LIMIT = 0.1  # reference `data.py:71`
TILE_GRID = (8, 8)  # reference `data.py:71`


# ---------------------------------------------------------------------------
# Host path (cv2) — reference parity.
# ---------------------------------------------------------------------------


def histeq_np(rgb: np.ndarray) -> np.ndarray:
    """uint8 HWC RGB -> uint8 HWC RGB. Bit-exact with the reference."""
    import cv2

    lab = cv2.cvtColor(rgb, cv2.COLOR_RGB2LAB)
    clahe = cv2.createCLAHE(clipLimit=CLIP_LIMIT, tileGridSize=TILE_GRID)
    out = lab.copy()
    out[:, :, 0] = clahe.apply(lab[:, :, 0])
    return cv2.cvtColor(out, cv2.COLOR_LAB2RGB)


# ---------------------------------------------------------------------------
# Device path (pure JAX).
# ---------------------------------------------------------------------------


# Per-step operand budget for the matmul paths: the histogram chunks its
# one-hot, and the interpolation sizes its cell decomposition (cell-height
# subdivision) and lax.scan row groups so that neither the bf16 one-hot nor
# the per-group LUT tables exceed this at any frame size. Tuning it trades
# scan length against peak memory; it does NOT switch gather/matmul except
# in the degenerate case where even single-pixel-cell rows can't fit
# (see clahe()).
_MATMUL_ONEHOT_CAP_BYTES = 64 * 1024 * 1024


def _matmul_cap_bytes() -> int:
    """The one-hot operand cap, trace-time tunable for chunk-sizing A/Bs
    (``WATERNET_CLAHE_MATMUL_CAP_MB``, default 64). Exactness is
    cap-invariant (tests sweep it); only scan length / peak memory move."""
    mb = os.environ.get("WATERNET_CLAHE_MATMUL_CAP_MB", "").strip()
    if not mb:
        return _MATMUL_ONEHOT_CAP_BYTES
    try:
        val = int(mb)
    except ValueError:
        val = 0
    if val <= 0:
        raise ValueError(
            f"WATERNET_CLAHE_MATMUL_CAP_MB={mb!r}: expected a positive "
            "integer (megabytes)"
        )
    return val * 1024 * 1024


def _onehot_dtypes():
    """(operand dtype, accumulator dtype) for BOTH one-hot matmul paths
    (histograms and LUT interpolation).

    ``int8`` (default) halves the dominant one-hot byte streams vs bf16
    and uses the MXU's native int8 path with int32 accumulation — exact
    for both uses: histogram products are 0/1 with tile-area sums < 2^24,
    and the interpolation stores LUT values (integers 0..255) as
    ``value - 128`` (fits int8 exactly), adding 128 back after the matmul
    — each output element is one ``1 * (v - 128)`` product, so the
    round-trip is the identity. ``WATERNET_CLAHE_ONEHOT`` selects
    bf16/f32 for hardware A/B.
    """
    mode = os.environ.get("WATERNET_CLAHE_ONEHOT", "int8").strip().lower()
    if mode == "int8":
        return jnp.int8, jnp.int32
    if mode == "bf16":
        return jnp.bfloat16, jnp.float32
    if mode == "f32":
        return jnp.float32, jnp.float32
    raise ValueError(
        f"WATERNET_CLAHE_ONEHOT={mode!r}: expected 'int8', 'bf16' or 'f32'"
    )


def _interp_mode(th: int, tw: int, use_pallas=None) -> str:
    """Resolve the LUT-interpolation strategy: 'gather', 'matmul', or
    'pallas'.

    An explicit ``use_pallas`` wins (as in :func:`_hist_mode`): True
    selects the fused Pallas lookup+blend kernel
    (:func:`waternet_tpu.ops.pallas_kernels.clahe_lut_planes`), False the
    non-Pallas auto choice. ``WATERNET_CLAHE_INTERP`` forces any mode
    (matmul still falls back per shape when the cell decomposition is
    impossible — see clahe()); ``pallas_enabled()`` (WATERNET_PALLAS=1)
    selects the kernel; otherwise auto picks the one-hot matmul on TPU
    (gathers serialize on TPU; a one-hot matmul rides the MXU). Memory is
    bounded every way: the matmul chunks itself under the env-tunable
    :func:`_matmul_cap_bytes` cap (default ``_MATMUL_ONEHOT_CAP_BYTES``),
    the Pallas kernel subdivides its cell blocks under
    ``_PALLAS_INTERP_BLOCK_CAP``, and odd tile sizes degrade the cells to
    single rows/columns (more, smaller blocks) — still MXU-shaped, so
    auto enables them too; `tools/ab_bench.py` measures whether that
    holds up against gather per config.
    """
    if use_pallas is not None:
        if use_pallas:
            return "pallas"
        from waternet_tpu.utils.platform import is_tpu_backend

        return "matmul" if is_tpu_backend() else "gather"
    forced = os.environ.get("WATERNET_CLAHE_INTERP", "").strip().lower()
    if forced in ("gather", "matmul", "pallas"):
        return forced
    from waternet_tpu.ops.pallas_kernels import pallas_enabled

    if pallas_enabled():
        return "pallas"
    from waternet_tpu.utils.platform import is_tpu_backend

    return "matmul" if is_tpu_backend() else "gather"


def _hist_mode(use_pallas) -> str:
    """Resolve the histogram strategy: 'scatter', 'matmul', or 'pallas'.

    ``use_pallas=True`` (or ``WATERNET_PALLAS=1``) selects the Pallas
    path — which, inside :func:`clahe`, is the FUSED ``tile_lut`` kernel
    (histogram + clip + CDF + LUT in one; the standalone
    ``tile_histogram`` kernel remains the pallas branch of
    :func:`_tile_hist` for histogram-only callers).
    ``WATERNET_CLAHE_HIST`` forces any mode. Auto prefers the one-hot MXU
    matmul on TPU (bincount lowers to a serialized scatter-add there);
    the matmul chunks itself under the 64 MB one-hot cap, so it handles
    any frame size. CPU keeps scatter (fast there).
    """
    # Explicit argument wins over the env override (an exported
    # WATERNET_CLAHE_HIST must not silently reroute callers — or tests —
    # that pin a path via use_pallas=...).
    if use_pallas is not None:
        return "pallas" if use_pallas else "scatter"
    forced = os.environ.get("WATERNET_CLAHE_HIST", "").strip().lower()
    if forced in ("scatter", "matmul", "pallas"):
        return forced
    from waternet_tpu.ops.pallas_kernels import pallas_enabled

    if pallas_enabled():
        return "pallas"
    from waternet_tpu.utils.platform import is_tpu_backend

    if is_tpu_backend():
        return "matmul"
    return "scatter"


def _tile_hist(tiles, use_pallas):
    """(T, A) int values in [0, 256) -> (T, 256) integer counts."""
    n_tiles, tile_area = tiles.shape
    mode = _hist_mode(use_pallas)
    if mode == "pallas":
        # Dense VPU comparison-reduction kernel (scatter-free). clahe()
        # itself never reaches this branch in pallas mode — it routes to
        # the fused tile_lut kernel before computing a bare histogram —
        # so this serves histogram-only callers (and the kernel's own
        # parity tests).
        from waternet_tpu.ops.pallas_kernels import tile_histogram

        return tile_histogram(tiles)
    if mode == "matmul":
        # hist[t, b] = ones(A) . onehot[t, :, b] — one-hot batched matmuls
        # on the MXU. Default operand dtype is int8 with int32 accumulation
        # (exact: 0/1 products, integer sums < 2^24): the one-hot is the
        # dominant byte stream of the whole CLAHE matmul path (~1 GB/frame
        # at 1080p in bf16 — tools/clahe1080_bench.py), so int8 halves it
        # and rides the v5e MXU's native int8 throughput. bf16/f32 kept
        # under WATERNET_CLAHE_ONEHOT for hardware A/B. Large tiles
        # (1080p: 32k+ px) are chunked with lax.scan so the materialized
        # one-hot stays bounded regardless of frame size — the pure-XLA
        # analog of the Pallas kernel's chunking.
        dt, acc_dt = _onehot_dtypes()
        isz = jnp.dtype(dt).itemsize
        cap = _matmul_cap_bytes()
        chunk = tile_area
        if n_tiles * tile_area * 256 * isz > cap:
            chunk = max(cap // (n_tiles * 256 * isz), 256)

        def _count(vals):  # (T, chunk) int32, -1 marks padding
            onehot = jax.nn.one_hot(vals, 256, dtype=dt)
            ones = jnp.ones((n_tiles, 1, vals.shape[1]), dt)
            counts = jax.lax.dot_general(
                ones,
                onehot,
                (((2,), (1,)), ((0,), (0,))),
                preferred_element_type=acc_dt,
            )  # (T, 1, 256)
            return counts[:, 0, :]

        if chunk >= tile_area:
            return _count(tiles).astype(jnp.int32)
        n_chunks = -(-tile_area // chunk)
        pad = n_chunks * chunk - tile_area
        vals = jnp.pad(tiles, ((0, 0), (0, pad)), constant_values=-1)
        vals = vals.reshape(n_tiles, n_chunks, chunk).transpose(1, 0, 2)

        def body(acc, v):
            return acc + _count(v), None

        hist, _ = jax.lax.scan(body, jnp.zeros((n_tiles, 256), acc_dt), vals)
        return hist.astype(jnp.int32)
    # XLA scatter path: bincount lowers to scatter-add.
    tile_ids = jnp.repeat(jnp.arange(n_tiles, dtype=jnp.int32), tile_area)
    flat_idx = tile_ids * 256 + tiles.reshape(-1)
    return jnp.bincount(flat_idx, length=n_tiles * 256).reshape(n_tiles, 256)


def _luts_from_hist(hist, clip, lut_scale) -> jnp.ndarray:
    """(T, 256) integer histograms -> (T, 256) float32 LUTs: OpenCV's
    integer clip + excess redistribution, then LUT = round(cdf * scale)
    with the single-rounded float32 ``lut_scale``. This is the ONE
    reference for that arithmetic: the lax CLAHE path calls it with
    static Python ``clip``/numpy ``lut_scale``, the serving-side masked
    variant (ops/masked.py) with traced scalars (every op broadcasts),
    and the fused Pallas kernel
    (:func:`waternet_tpu.ops.pallas_kernels.tile_lut`) must match it
    bit-for-bit (pinned in tests/test_pallas.py)."""
    excess = jnp.sum(jnp.maximum(hist - clip, 0), axis=-1)  # (T,)
    hist = jnp.minimum(hist, clip)
    hist = hist + (excess // 256)[:, None]
    residual = excess % 256  # always < 256
    step = jnp.maximum(256 // jnp.maximum(residual, 1), 1)  # (T,)
    bins = jnp.arange(256, dtype=jnp.int32)
    inc = (
        (residual[:, None] > 0)
        & (bins[None, :] % step[:, None] == 0)
        & (bins[None, :] // step[:, None] < residual[:, None])
    )
    hist = hist + inc.astype(jnp.int32)
    cdf = jnp.cumsum(hist, axis=-1).astype(jnp.float32)
    return jnp.clip(jnp.round(cdf * lut_scale), 0.0, 255.0)


# Per-block one-hot cap for the fused Pallas interpolation kernel: a cell
# block materializes a (cell_h * cell_w, 256) f32 compare matrix in VMEM,
# so giant even tiles (full-res frames) subdivide their cells to fit.
_PALLAS_INTERP_BLOCK_CAP = 4 * 1024 * 1024


def _shrink_cell(cell, cells, unit_bytes, cap=None):
    """Subdivide one cell extent until ``cell * unit_bytes`` fits the cap.

    Any divisor keeps per-cell tile-pair constancy (entries repeat), the
    same argument as :func:`_fit_cell_rows`. Returns the adjusted
    (cell, cells); a 1-pixel extent always "fits" (the cap bounds the
    per-block one-hot, whose other factor the caller passes in). ``cap``
    resolves late so tests can shrink ``_PALLAS_INTERP_BLOCK_CAP`` and
    pin that subdivision never changes bits."""
    if cap is None:
        cap = _PALLAS_INTERP_BLOCK_CAP
    d = cell
    while d > 1 and d * unit_bytes > cap:
        d = max(k for k in range(1, d) if d % k == 0)
    if d != cell:
        lo, hi = cells
        cells = (np.repeat(lo, cell // d), np.repeat(hi, cell // d))
    return d, cells


def _cell_tile_indices(n_pix, tile, n_tiles):
    """-> (cell_extent, (lo, hi)) per-cell tile indices along one axis.

    Reproduces the runtime grid arithmetic exactly — float32 multiply by the
    float32 reciprocal, minus 0.5, floor — in numpy at trace time (IEEE f32
    elementwise ops are bit-identical between numpy and XLA). Cells are
    half-tile extents when the tile size is even AND every pixel of each
    cell landed on the same tile pair under f32 rounding; otherwise single
    pixels (always valid — each pixel trivially agrees with itself). The
    caller batches one matmul per cell, so smaller cells mean more, smaller
    matmuls, never wrong answers."""
    inv = np.float32(1.0) / np.float32(tile)
    coords = np.arange(n_pix, dtype=np.float32) * inv - np.float32(0.5)
    fl = np.floor(coords).astype(np.int64)
    cell = tile // 2 if tile % 2 == 0 else 1
    if cell > 1:
        f = fl.reshape(-1, cell)
        if (f == f[:, :1]).all():
            fl = f[:, 0]
        else:
            cell = 1  # f32 rounding split a cell; degrade to single pixels
    lo = fl
    hi = np.minimum(lo + 1, n_tiles - 1)
    lo = np.maximum(lo, 0)
    return cell, (lo, hi)


def _fit_cell_rows(cell_h, cells_y, cell_w, wp):
    """Subdivide cell height until one cell-row's operands fit the cap.

    Every pixel of a cell shares its tile pair, so any divisor of cell_h
    still yields constant cells (entries repeat). Returns the adjusted
    (cell_h, cells_y), or None when even single-pixel rows can't fit —
    per-row table bytes depend only on ncx, so that's the degenerate
    all-tables case (both tiles odd at extreme widths)."""
    isz = jnp.dtype(_onehot_dtypes()[0]).itemsize
    ncx = wp // cell_w
    tables_row = ncx * 256 * 4 * isz

    def row_bytes(ch):
        return max(ncx * ch * cell_w * 256 * isz, tables_row)

    cap = _matmul_cap_bytes()
    d = cell_h
    while d > 1 and row_bytes(d) > cap:
        d = max(k for k in range(1, d) if d % k == 0)
    if row_bytes(d) > cap:
        return None
    if d != cell_h:
        lo, hi = cells_y
        cells_y = (np.repeat(lo, cell_h // d), np.repeat(hi, cell_h // d))
    return d, cells_y


def _lut_planes_matmul(luts, v_pad, cells_y, cells_x, cell_h, cell_w):
    """The four quadrant LUT lookups as batched one-hot matmuls.

    The (padded) image splits into (ncy, ncx) cells of (cell_h, cell_w)
    pixels — half-tile extents when the tile size is even, single rows/
    columns otherwise; every pixel in a cell interpolates between the SAME
    four tile LUTs (the cell index determines floor(y/th - 0.5) etc.).
    Stacking those four 256-entry LUTs per cell gives a (cells, 256, 4)
    operand, and the pixel values become a (cells, pix, 256) one-hot; a
    batched matmul then performs all four lookups per pixel on the MXU.
    Exact in every operand dtype (see :func:`_onehot_dtypes`): each output
    element is a single ``1 * value`` product — in bf16/f32 the LUT
    values (integers <= 255) are exactly representable; in int8 (the
    default, half the byte traffic) the tables store ``value - 128``
    (fits int8 exactly) and 128 is added back after the int32-accumulated
    matmul — so the result is bit-identical to the gather path. Cell rows
    are processed in lax.scan groups sized so the one-hot (and the
    per-group tables) stay under the :func:`_matmul_cap_bytes` cap at any
    frame size.

    Returns four (hp, wp) float32 planes (quadrants 11, 12, 21, 22).
    """
    hp, wp = v_pad.shape
    y1, y2 = cells_y
    x1, x2 = cells_x
    ncy, ncx = len(y1), len(x1)
    x1j, x2j = jnp.asarray(x1), jnp.asarray(x2)
    dt, acc_dt = _onehot_dtypes()
    isz = jnp.dtype(dt).itemsize
    offset = jnp.float32(128.0) if dt == jnp.int8 else jnp.float32(0.0)

    # Largest divisor of ncy for which BOTH per-group operands (one-hot and
    # LUT tables) fit the cap.
    per_row = max(ncx * cell_h * cell_w * 256 * isz, ncx * 256 * 4 * isz)
    budget = max(_matmul_cap_bytes() // per_row, 1)
    g = max(d for d in range(1, ncy + 1) if ncy % d == 0 and d <= budget)
    n_groups = ncy // g

    def group_planes(vg, y1g, y2g):
        # vg: (g*cell_h, wp); y1g/y2g: (g,) tile rows for this cell-row group
        def tab(yi, xi):  # (g, ncx, 256)
            return luts[yi[:, None], xi[None, :], :]

        tables = jnp.stack(
            [tab(y1g, x1j), tab(y1g, x2j), tab(y2g, x1j), tab(y2g, x2j)],
            axis=-1,
        ).reshape(g * ncx, 256, 4)
        tables = (tables - offset).astype(dt)
        cells = (
            vg.reshape(g, cell_h, ncx, cell_w)
            .transpose(0, 2, 1, 3)
            .reshape(g * ncx, cell_h * cell_w)
        )
        onehot = jax.nn.one_hot(cells, 256, dtype=dt)
        looked = jax.lax.dot_general(
            onehot,
            tables,
            (((2,), (1,)), ((0,), (0,))),  # contract the 256 bins, batch cells
            preferred_element_type=acc_dt,
        ).astype(jnp.float32) + offset  # (cells, pix, 4)
        return (
            looked.reshape(g, ncx, cell_h, cell_w, 4)
            .transpose(4, 0, 2, 1, 3)
            .reshape(4, g * cell_h, wp)
        )

    if n_groups == 1:
        planes = group_planes(v_pad, jnp.asarray(y1), jnp.asarray(y2))
    else:
        vg = v_pad.reshape(n_groups, g * cell_h, wp)
        y1g = jnp.asarray(y1).reshape(n_groups, g)
        y2g = jnp.asarray(y2).reshape(n_groups, g)

        def body(_, xs):
            return None, group_planes(*xs)

        _, out = jax.lax.scan(body, None, (vg, y1g, y2g))
        planes = out.transpose(1, 0, 2, 3).reshape(4, hp, wp)
    return planes[0], planes[1], planes[2], planes[3]


def clahe(
    l_chan: jnp.ndarray,
    clip_limit: float = CLIP_LIMIT,
    tile_grid: tuple[int, int] = TILE_GRID,
    use_pallas: bool | None = None,
) -> jnp.ndarray:
    """OpenCV-exact CLAHE on one channel.

    Args:
        l_chan: (H, W) uint8-valued array (any real dtype).
        tile_grid: (ty, tx) tile counts along (H, W) — note cv2's
            ``tileGridSize`` is a cv::Size, i.e. the transposed
            (tilesX, tilesY); equivalence is ``tile_grid=(gy, gx)``.
    Returns:
        (H, W) float32 holding exact uint8 values.
    """
    h, w = l_chan.shape
    ty, tx = tile_grid
    # OpenCV quirk, reproduced exactly: when EITHER axis is non-divisible,
    # copyMakeBorder pads BOTH by ``tiles - (size % tiles)`` — which is a
    # FULL extra tile-count of pixels (one per tile) on an axis that was
    # already divisible (clahe.cpp pads with tilesX_ - (width % tilesX_),
    # not modulo). Caught by single-axis-padding fuzz; padding each axis
    # independently gives different tile sizes and diverges everywhere.
    if h % ty == 0 and w % tx == 0:
        pad_h = pad_w = 0
    else:
        pad_h = ty - (h % ty)
        pad_w = tx - (w % tx)
    x = l_chan.astype(jnp.int32)
    if pad_h or pad_w:
        x = jnp.pad(x, ((0, pad_h), (0, pad_w)), mode="reflect")
    hp, wp = h + pad_h, w + pad_w
    th, tw = hp // ty, wp // tx
    n_tiles = ty * tx
    tile_area = th * tw

    # --- per-tile histograms -> clip/redistribute -> LUTs ---
    # clip: OpenCV's integer clip limit. lut_scale: single-rounded float32
    # division, exactly OpenCV's
    # ``const float lutScale = static_cast<float>(histSize - 1) / tileSizeTotal``
    # (a Python-float 255.0/area would double-round through float64 — and
    # would not be reproducible by the serving path's dynamic-shape variant,
    # ops/masked.py, which must divide in f32 on device).
    tiles = x.reshape(ty, th, tx, tw).transpose(0, 2, 1, 3).reshape(n_tiles, tile_area)
    clip = max(int(clip_limit * tile_area / 256.0), 1)
    lut_scale = np.float32(255.0) / np.float32(tile_area)
    if _hist_mode(use_pallas) == "pallas":
        # Fused kernel: histogram + clip + redistribute + CDF + LUT never
        # leave VMEM (bit-identical to the lax pipeline below).
        from waternet_tpu.ops.pallas_kernels import tile_lut

        luts = tile_lut(tiles, clip, lut_scale)
    else:
        luts = _luts_from_hist(_tile_hist(tiles, use_pallas), clip, lut_scale)
    luts = luts.reshape(ty, tx, 256)

    # --- bilinear interpolation between tile LUTs ---
    # (gather: over the original (h, w) area; matmul: over the padded
    # (hp, wp) grid, cropped to (h, w) after the blend — elementwise
    # identical on the kept region.)
    # OpenCV computes tile coords as x * (1/tile_size) with a float32
    # reciprocal (not a division); matching that exactly is what makes the
    # rounding ties land identically (verified bit-exact vs cv2).
    mode = _interp_mode(th, tw, use_pallas)
    if mode == "matmul":
        cell_h, cells_y = _cell_tile_indices(hp, th, ty)
        cell_w, cells_x = _cell_tile_indices(wp, tw, tx)
        fitted = _fit_cell_rows(cell_h, cells_y, cell_w, wp)
        if fitted is None:
            mode = "gather"  # even 1-px cell rows can't fit the cap
        else:
            cell_h, cells_y = fitted
    elif mode == "pallas":
        # Cell decomposition for the fused kernel; giant even tiles
        # subdivide so each block's (pixels, 256) one-hot fits VMEM.
        cell_h, cells_y = _cell_tile_indices(hp, th, ty)
        cell_w, cells_x = _cell_tile_indices(wp, tw, tx)
        cell_h, cells_y = _shrink_cell(cell_h, cells_y, cell_w * 256 * 4)
        cell_w, cells_x = _shrink_cell(cell_w, cells_x, cell_h * 256 * 4)
    gh, gw = (h, w) if mode == "gather" else (hp, wp)
    inv_th = np.float32(1.0) / np.float32(th)
    inv_tw = np.float32(1.0) / np.float32(tw)
    yy = jnp.arange(gh, dtype=jnp.float32) * inv_th - np.float32(0.5)
    xx = jnp.arange(gw, dtype=jnp.float32) * inv_tw - np.float32(0.5)
    y1 = jnp.floor(yy).astype(jnp.int32)
    x1 = jnp.floor(xx).astype(jnp.int32)
    ya = (yy - y1.astype(jnp.float32))[:, None]
    xa = (xx - x1.astype(jnp.float32))[None, :]

    if mode == "pallas":
        # All four lookups in ONE fused kernel over the cell decomposition
        # (bit-identical plane values; the blend stays out here where its
        # fma contraction matches the other strategies — see
        # pallas_kernels.clahe_lut_planes), computed on the padded grid
        # and cropped after the blend.
        from waternet_tpu.ops.pallas_kernels import clahe_lut_planes

        p11, p12, p21, p22 = clahe_lut_planes(
            luts, x, cells_y, cells_x, cell_h, cell_w
        )
        res = (p11 * (1.0 - xa) + p12 * xa) * (1.0 - ya) + (
            p21 * (1.0 - xa) + p22 * xa
        ) * ya
        res = res[:h, :w]
    elif mode == "matmul":
        # All four lookups as batched MXU one-hot matmuls over the cell
        # decomposition (bit-identical values; see _lut_planes_matmul),
        # computed on the padded grid and cropped after the blend.
        p11, p12, p21, p22 = _lut_planes_matmul(
            luts, x, cells_y, cells_x, cell_h, cell_w
        )
        res = (p11 * (1.0 - xa) + p12 * xa) * (1.0 - ya) + (
            p21 * (1.0 - xa) + p22 * xa
        ) * ya
        res = res[:h, :w]
    else:
        y2 = jnp.minimum(y1 + 1, ty - 1)
        x2 = jnp.minimum(x1 + 1, tx - 1)
        y1 = jnp.maximum(y1, 0)
        x1 = jnp.maximum(x1, 0)

        v = l_chan.astype(jnp.int32)

        def look(yi, xi):
            # luts[yi[r], xi[c], v[r, c]] for every pixel.
            return luts[yi[:, None], xi[None, :], v]

        res = (look(y1, x1) * (1.0 - xa) + look(y1, x2) * xa) * (1.0 - ya) + (
            look(y2, x1) * (1.0 - xa) + look(y2, x2) * xa
        ) * ya
    return jnp.clip(jnp.round(res), 0.0, 255.0)


def histeq(rgb: jnp.ndarray) -> jnp.ndarray:
    """Device-path `histeq`: (H, W, 3) uint8-valued RGB -> float32 uint8 values.

    RGB -> LAB (cv2's uint8 fixed-point path, bit-exact), OpenCV-exact
    CLAHE on L, LAB -> RGB (float inverse — the only non-bit-exact stage).
    Jittable; vmap for batches.
    """
    lab = rgb_to_lab_u8(rgb)
    el = clahe(lab[..., 0])
    lab = lab.at[..., 0].set(el)
    return lab_u8_to_rgb(lab)
