"""Gamma correction transform.

Behavioral spec from the reference (`/root/reference/waternet/data.py:61-65`):
``out = uint8(clip((im/255) ** 0.7 * 255, 0, 255))`` with numpy ``astype``
truncation.

The input domain is uint8, so the device path is an exact 256-entry lookup
table (precomputed in float64 on host at trace time) — bit-identical to the
reference and cheaper on TPU than a transcendental ``pow`` per pixel.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

GAMMA = 0.7  # reference `data.py:62`


def _lut(gamma: float) -> np.ndarray:
    levels = np.arange(256, dtype=np.float64)
    out = np.clip(255.0 * np.power(levels / 255.0, gamma), 0, 255)
    return out.astype(np.uint8).astype(np.float32)  # truncation, as reference


def gamma_correction_np(img: np.ndarray, gamma: float = GAMMA) -> np.ndarray:
    """Host path. uint8 -> uint8, any shape."""
    out = np.power(img / 255.0, gamma)
    return np.clip(255.0 * out, 0, 255).astype(np.uint8)


def gamma_correction(img: jnp.ndarray, gamma: float = GAMMA) -> jnp.ndarray:
    """Device path. uint8-valued array -> float32 exact uint8 values [0, 255]."""
    lut = jnp.asarray(_lut(gamma))
    return lut[img.astype(jnp.int32)]
