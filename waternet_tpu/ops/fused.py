"""Step-shaped fused preprocessing: the device-preprocess training entry.

The `--device-preprocess` training mode (the default) ships RAW uint8
pairs to the device — the PR-2 pipeline workers only decode and stack —
and runs everything else inside the jitted train step: paired dihedral
augmentation, the classical WB/GC/CLAHE views, and the [0, 1] scaling the
network consumes. This module is that in-step stage as a standalone,
jittable entry point, factored out of ``TrainingEngine._preprocess`` so

* the trainer, ``bench.py``'s isolated-preprocess timing, and
  ``tools/mfu_decomp.py``'s FLOP attribution all compile the SAME
  program — the decomposition can never describe a different stage than
  the step runs;
* the stage has one home in the ops layer (L1), next to the transforms
  it fuses, instead of living as a trainer method.

Exactness: identical ops in identical order to the historical trainer
inline code (augment_pair_batch then transform_batch then the five
``/255`` scalings), so factoring it out changes no bits — pinned by the
device-preprocess parity tests (tests/test_device_preprocess.py).

The CLAHE stage inside :func:`waternet_tpu.ops.transform.transform_batch`
is where the step's classical-transform time goes (BENCH_r05 measured the
in-step transforms at ~22 ms of the 47.8 ms step at 112²/batch-16); its
Pallas-fused hot spots live in :mod:`waternet_tpu.ops.pallas_kernels` and
are selected through the normal ``ops.clahe`` strategy resolution
(``WATERNET_PALLAS=1`` / ``pallas_enabled()``), so this entry needs no
kernel knowledge of its own.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax.numpy as jnp

from waternet_tpu.data.augment import augment_pair_batch
from waternet_tpu.ops.transform import transform_batch


def fused_train_preprocess(
    raw_u8: jnp.ndarray,
    ref_u8: jnp.ndarray,
    rng: Optional[jnp.ndarray],
    *,
    augment: bool = True,
) -> Tuple[jnp.ndarray, ...]:
    """uint8 (raw, ref) batch -> the five [0, 1] float32 training views.

    Args:
        raw_u8: (N, H, W, 3) uint8(-valued) raw batch.
        ref_u8: (N, H, W, 3) uint8(-valued) reference batch.
        rng: augmentation PRNG key, or None (eval: no augmentation even
            when ``augment`` is True — mirrors the trainer contract).
        augment: apply the paired dihedral augmentation.

    Returns:
        ``(x, wbn, hen, gcn, refn)`` float32 batches scaled to [0, 1], in
        the network's input order.
    """
    raw = raw_u8.astype(jnp.float32)
    ref = ref_u8.astype(jnp.float32)
    if augment and rng is not None:
        raw, ref = augment_pair_batch(rng, raw, ref)
    wb, gc, he = transform_batch(raw)
    return raw / 255.0, wb / 255.0, he / 255.0, gc / 255.0, ref / 255.0
