"""White balance (simplest color balance) transform.

Behavioral spec from the reference implementation
(`/root/reference/waternet/data.py:6-58`, itself a port of the WaterNet
authors' MATLAB ``SimplestColorBalance.m``):

For an RGB uint8 HWC image:
1. Per-channel saturation levels are *dynamic*: ``sat_c = 0.005 * maxsum /
   sum_c`` where ``sum_c`` is the channel's pixel sum and ``maxsum`` the
   largest of the three sums (dimmer channels get clipped more aggressively).
2. Each channel is clipped to its ``[quantile(sat_c), quantile(1 - sat_c)]``
   range (linear-interpolation quantiles).
3. Each channel is then min-max stretched to [0, 255] and truncated to uint8
   (numpy ``astype`` truncates toward zero, i.e. floor for non-negative).

Two implementations:
* :func:`white_balance_np` — host path, vectorized NumPy. Matches the
  reference output bit-for-bit (verified by golden tests).
* :func:`white_balance` — device path, pure JAX, jittable and vmappable.
  Returns float32 holding exact uint8 values so it can feed the network
  directly after ``/255`` without a host round-trip.

The reference also has a grayscale branch (`data.py:31-36`) that is unused by
every caller and mutates its input through a reshape view; we support the
grayscale case in the host path (without the mutation defect) and only RGB on
device.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_SAT = 0.005  # reference `data.py:22-23`


def white_balance_np(img: np.ndarray) -> np.ndarray:
    """Host-path simplest color balance. uint8 HWC (or HW) -> uint8 same shape."""
    if img.ndim == 2:
        flat = img.reshape(1, -1).astype(np.float64)
        lo_q = np.array([0.001])
        hi_q = 1.0 - np.array([0.005])
    else:
        h, w, c = img.shape
        flat = img.reshape(h * w, c).T.astype(np.float64)  # (C, H*W)
        sums = flat.sum(axis=1)
        # Guard degenerate frames (all-black channel -> 0/0; the reference
        # crashes here, but video fades make this a real input).
        sat = _SAT * (sums.max() / np.maximum(sums, 1.0))
        lo_q, hi_q = np.clip(sat, 0.0, 0.5), 1.0 - np.clip(sat, 0.0, 0.5)

    out = np.empty_like(flat)
    for ch in range(flat.shape[0]):
        lo, hi = np.quantile(flat[ch], [lo_q[ch], hi_q[ch]])
        v = np.clip(flat[ch], lo, hi)
        if hi > lo:
            out[ch] = (v - lo) * 255.0 / (hi - lo)
        else:
            out[ch] = v  # constant channel: stretch undefined, pass through

    if img.ndim == 2:
        return out.reshape(img.shape).astype(np.uint8)
    return out.T.reshape(img.shape).astype(np.uint8)


def white_balance(rgb: jnp.ndarray) -> jnp.ndarray:
    """Device-path simplest color balance for one RGB image.

    Args:
        rgb: (H, W, 3) uint8 or float32 holding uint8 values.

    Returns:
        (H, W, 3) float32 with exact uint8 values (floored), range [0, 255].

    Jittable; vmap over a leading batch axis for batched use. Quantiles are
    computed per image per channel (data-dependent values, static shapes).
    """
    x = rgb.astype(jnp.float32)
    flat = x.reshape(-1, 3)  # (P, 3)

    # Per-channel linear-interpolation quantiles at per-channel
    # probabilities — via 256-bin histogram CDFs, not a sort. Values are
    # uint8, so the k-th order statistic is exactly
    # ``#{v in 0..255 : cdf[v] < k+1}``; a full-image sort (O(P log^2 P)
    # bitonic network on TPU) would compute 2 numbers per channel the
    # expensive way. Bit-identical to the sort formulation.
    n = flat.shape[0]
    chan_offset = jnp.arange(3, dtype=jnp.int32) * 256
    idx = flat.astype(jnp.int32) + chan_offset[None, :]
    hist = jnp.bincount(idx.reshape(-1), length=3 * 256).reshape(3, 256)
    cdf = jnp.cumsum(hist, axis=1)  # (3, 256), cdf[c, v] = #pixels <= v

    # Channel sums from the exact integer histogram rather than a pixel-order
    # tree reduction: the (3, 256) weighted sum is the SAME computation at
    # every image size, which is what lets the serving path's masked variant
    # (ops/masked.py) reproduce these statistics bit-for-bit on a padded
    # canvas — a (P, 3) reduction's float32 result depends on P.
    sums = (hist.astype(jnp.float32) * jnp.arange(256, dtype=jnp.float32)).sum(
        axis=1
    )
    # Degenerate-frame guards mirror the host path (all-black channels and
    # constant channels must not emit NaN into the training batch).
    sat = jnp.clip(_SAT * (sums.max() / jnp.maximum(sums, 1.0)), 0.0, 0.5)

    def _q(p):
        pos = p * (n - 1)
        i0 = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, n - 1)
        i1 = jnp.clip(i0 + 1, 0, n - 1)
        w1 = pos - i0.astype(jnp.float32)
        a = (cdf < (i0[:, None] + 1)).sum(axis=1).astype(jnp.float32)
        b = (cdf < (i1[:, None] + 1)).sum(axis=1).astype(jnp.float32)
        return a * (1.0 - w1) + b * w1

    lo = _q(sat)
    hi = _q(1.0 - sat)
    v = jnp.clip(x, lo, hi)
    out = jnp.where(hi > lo, (v - lo) * 255.0 / jnp.maximum(hi - lo, 1e-9), v)
    # Reference truncates via uint8 astype; floor matches for non-negatives.
    return jnp.floor(out)
