"""Native-first WB/GC/CLAHE on a padded canvas — the device-preprocess
serving path (docs/SERVING.md "Replica pool").

The bucketed serving engine pads every image up to a compile bucket so one
executable serves many resolutions. Its exactness policy requires the
global per-image statistics (WB quantiles, CLAHE tile histograms) to be
computed on the NATIVE image and the pad applied afterwards — computing
them on the padded canvas would change every pixel, not just the seam
band. PR 4 therefore kept ``--device-preprocess`` engines off the
bucketed path entirely: the stock device transforms
(:mod:`waternet_tpu.ops.transform`) are shape-specialized to their input,
so running them at native shape inside a bucket-shaped program was
impossible.

This module closes that gap: each transform takes the RAW uint8 canvas
(native image reflect-padded bottom/right, :func:`waternet_tpu.serving.
bucketing.pad_to_bucket`) plus the native ``(h, w)`` as *dynamic* int32
scalars, computes its statistics over the native region only, and applies
the resulting pointwise map to the whole canvas. The exactness argument,
pinned in tests/test_serving.py:

* **WB** — the per-channel 256-bin histogram is accumulated with invalid
  pixels routed to a dump bin: integer scatter-adds are order-independent,
  so the histogram (and its CDF) is bit-identical to the native image's.
  Channel sums derive from that histogram through the same (3, 256)
  weighted reduction the native :func:`waternet_tpu.ops.wb.white_balance`
  uses (refactored for exactly this), so ``sat``/``lo``/``hi`` match
  bit-for-bit; the clip/stretch/floor that follows is pointwise.
* **GC** — a 256-entry LUT gather; pointwise, no statistics at all.
* **CLAHE** — the tile grid is *dynamic*: OpenCV's divisibility padding,
  tile extents, clip limit, and interpolation grid are all computed from
  the traced ``(h, w)``. Histograms gather through a mirror index map
  that reproduces reflect-101 padding values from inside the canvas
  (so correctness never depends on how much pad the bucket happens to
  have), tile membership is an integer division by the dynamic tile
  extent, and the scatter-add accumulation is again exact. The LUT scale
  and interpolation coordinates use the same single-rounded float32
  arithmetic as the native path (which mirrors OpenCV's own f32 ops), so
  native-region output is bit-identical to :func:`waternet_tpu.ops.
  clahe.clahe` on the native image.

For WB and GC the map is pointwise once its (native) statistics are
fixed, so their canvas pad regions come out as the transform of the
reflected content — i.e. exactly the reflect-pad of the transformed
native image that the host serving path
(`InferenceEngine.preprocess_padded`) constructs. CLAHE's map is
position-dependent (the bilinear tile-LUT blend weights follow the
canvas coordinate), so its pad region holds plausibly-equalized
reflected content rather than the host path's mirrored values — fine for
the PSNR-bounded seam band, and irrelevant to interior pixels of the
network output (beyond the 13 px receptive-field radius from the pad
seam), whose receptive fields never see pad content and which therefore
match the native device-preprocess forward (bit-exact up to CLAHE's
1-ulp blend-contraction caveat; see docs/SERVING.md).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from waternet_tpu.ops.clahe import CLIP_LIMIT, TILE_GRID, _luts_from_hist
from waternet_tpu.ops.color import lab_u8_to_rgb, rgb_to_lab_u8
from waternet_tpu.ops.gamma import gamma_correction
from waternet_tpu.ops.wb import _SAT


def _native_mask(shape_hw, h, w):
    """(H, W) bool: True inside the native top-left (h, w) region."""
    ch, cw = shape_hw
    yy = jnp.arange(ch, dtype=jnp.int32)[:, None]
    xx = jnp.arange(cw, dtype=jnp.int32)[None, :]
    return (yy < h) & (xx < w)


def white_balance_masked(canvas: jnp.ndarray, h, w) -> jnp.ndarray:
    """Simplest color balance with native-region statistics.

    ``canvas``: (CH, CW, 3) uint8-valued; ``h``/``w``: native extent
    (traced int32 scalars). Returns float32 exact uint8 values over the
    whole canvas; the native region is bit-identical to
    :func:`waternet_tpu.ops.wb.white_balance` on the native image.
    """
    x = canvas.astype(jnp.float32)
    mask = _native_mask(canvas.shape[:2], h, w)

    # Exact per-channel histogram of the native region: invalid pixels go
    # to a dump slot (integer scatter-add — order-independent, so the
    # counts equal the native image's bincount bit-for-bit).
    chan_offset = jnp.arange(3, dtype=jnp.int32) * 256
    idx = canvas.astype(jnp.int32) + chan_offset
    idx = jnp.where(mask[..., None], idx, 3 * 256)
    hist = (
        jnp.zeros(3 * 256 + 1, jnp.int32)
        .at[idx.reshape(-1)]
        .add(1)[: 3 * 256]
        .reshape(3, 256)
    )
    cdf = jnp.cumsum(hist, axis=1)
    # Same (3, 256) weighted reduction as the native path — bit-identical
    # sums from bit-identical histograms.
    sums = (hist.astype(jnp.float32) * jnp.arange(256, dtype=jnp.float32)).sum(
        axis=1
    )
    sat = jnp.clip(_SAT * (sums.max() / jnp.maximum(sums, 1.0)), 0.0, 0.5)

    n = (h * w).astype(jnp.int32)

    def _q(p):
        pos = p * (n - 1).astype(jnp.float32)
        i0 = jnp.clip(jnp.floor(pos).astype(jnp.int32), 0, n - 1)
        i1 = jnp.clip(i0 + 1, 0, n - 1)
        w1 = pos - i0.astype(jnp.float32)
        a = (cdf < (i0[:, None] + 1)).sum(axis=1).astype(jnp.float32)
        b = (cdf < (i1[:, None] + 1)).sum(axis=1).astype(jnp.float32)
        return a * (1.0 - w1) + b * w1

    lo = _q(sat)
    hi = _q(1.0 - sat)
    v = jnp.clip(x, lo, hi)
    out = jnp.where(hi > lo, (v - lo) * 255.0 / jnp.maximum(hi - lo, 1e-9), v)
    return jnp.floor(out)


def clahe_masked(l_canvas: jnp.ndarray, h, w) -> jnp.ndarray:
    """OpenCV-exact CLAHE (clipLimit=0.1, 8x8 tiles) with a dynamic native
    extent.

    ``l_canvas``: (CH, CW) uint8-valued L channel whose top-left (h, w)
    region is the native image (pad content beyond it is ignored — the
    divisibility pad is re-derived by mirror indexing *into* the native
    region). Returns float32 exact uint8 values over the whole canvas;
    the native region is bit-identical to :func:`waternet_tpu.ops.clahe.
    clahe` on the native L image (gather-path values, which every
    histogram/interp strategy matches bit-for-bit).
    """
    ch, cw = l_canvas.shape
    ty, tx = TILE_GRID
    vals = l_canvas.astype(jnp.int32)

    # OpenCV's divisibility pad, dynamic: when EITHER axis is non-divisible
    # BOTH pad by ``tiles - (size % tiles)`` (a full extra tile-count on an
    # axis that was already divisible — the clahe.cpp quirk the native path
    # reproduces).
    divisible = (h % ty == 0) & (w % tx == 0)
    pad_h = jnp.where(divisible, 0, ty - h % ty)
    pad_w = jnp.where(divisible, 0, tx - w % tx)
    hp = h + pad_h
    wp = w + pad_w
    th = hp // ty
    tw = wp // tx
    n_tiles = ty * tx
    tile_area = (th * tw).astype(jnp.int32)

    # --- per-tile histograms over the (dynamically) padded native image ---
    # The padded rows/cols are reflect-101 of the native content; rather
    # than trusting the canvas to hold enough reflect pad, gather them
    # through a mirror index map (y >= h -> 2h-2-y), on a static grid wide
    # enough for the worst-case pad (a full tile-count per axis).
    gh, gw = ch + ty, cw + tx
    ys = jnp.arange(gh, dtype=jnp.int32)
    xs = jnp.arange(gw, dtype=jnp.int32)
    sy = jnp.where(ys < h, ys, jnp.clip(2 * h - 2 - ys, 0, jnp.maximum(h - 1, 0)))
    sx = jnp.where(xs < w, xs, jnp.clip(2 * w - 2 - xs, 0, jnp.maximum(w - 1, 0)))
    sy = jnp.clip(sy, 0, ch - 1)
    sx = jnp.clip(sx, 0, cw - 1)
    grid = vals[sy[:, None], sx[None, :]]  # (gh, gw)

    in_range = (ys[:, None] < hp) & (xs[None, :] < wp)
    tile_y = jnp.clip(ys[:, None] // jnp.maximum(th, 1), 0, ty - 1)
    tile_x = jnp.clip(xs[None, :] // jnp.maximum(tw, 1), 0, tx - 1)
    tile_id = tile_y * tx + tile_x
    hidx = jnp.where(in_range, tile_id * 256 + grid, n_tiles * 256)
    hist = (
        jnp.zeros(n_tiles * 256 + 1, jnp.int32)
        .at[hidx.reshape(-1)]
        .add(1)[: n_tiles * 256]
        .reshape(n_tiles, 256)
    )

    # --- clip + redistribute + LUTs: the native path's shared reference
    # (clahe._luts_from_hist), with DYNAMIC clip/scale scalars ---
    # clip = max(int(0.1 * area / 256), 1) == max(area // 2560, 1): the f64
    # literal 0.1 is 0.1*(1+5.6e-17), an upward error far too small to push
    # int() past an integer boundary for any integer area, so the native
    # path's trace-time Python formula and this integer division agree for
    # every tile size. lut_scale is the same single-rounded f32 division as
    # OpenCV and the native path.
    denom = int(round(256.0 / CLIP_LIMIT))
    clip = jnp.maximum(tile_area // denom, 1)
    lut_scale = jnp.float32(255.0) / tile_area.astype(jnp.float32)
    luts = _luts_from_hist(hist, clip, lut_scale).reshape(ty, tx, 256)

    # --- bilinear interpolation between tile LUTs (gather formulation,
    # identical f32 reciprocal/coordinate arithmetic as the native path,
    # evaluated over the whole canvas) ---
    inv_th = jnp.float32(1.0) / th.astype(jnp.float32)
    inv_tw = jnp.float32(1.0) / tw.astype(jnp.float32)
    yy = jnp.arange(ch, dtype=jnp.float32) * inv_th - jnp.float32(0.5)
    xx = jnp.arange(cw, dtype=jnp.float32) * inv_tw - jnp.float32(0.5)
    y1 = jnp.floor(yy).astype(jnp.int32)
    x1 = jnp.floor(xx).astype(jnp.int32)
    ya = (yy - y1.astype(jnp.float32))[:, None]
    xa = (xx - x1.astype(jnp.float32))[None, :]
    y2 = jnp.minimum(y1 + 1, ty - 1)
    x2 = jnp.minimum(x1 + 1, tx - 1)
    y1 = jnp.maximum(y1, 0)
    x1 = jnp.maximum(x1, 0)

    def look(yi, xi):
        return luts[yi[:, None], xi[None, :], vals]

    res = (look(y1, x1) * (1.0 - xa) + look(y1, x2) * xa) * (1.0 - ya) + (
        look(y2, x1) * (1.0 - xa) + look(y2, x2) * xa
    ) * ya
    return jnp.clip(jnp.round(res), 0.0, 255.0)


def histeq_masked(canvas: jnp.ndarray, h, w) -> jnp.ndarray:
    """Native-statistics `histeq` on a canvas: RGB -> LAB (pointwise,
    bit-exact fixed point), :func:`clahe_masked` on L, LAB -> RGB
    (pointwise float inverse — per-pixel identical to the native path)."""
    lab = rgb_to_lab_u8(canvas)
    el = clahe_masked(lab[..., 0], h, w)
    lab = lab.at[..., 0].set(el)
    return lab_u8_to_rgb(lab)


def transform_masked(canvas: jnp.ndarray, h, w):
    """One canvas -> (wb, gc, he) float32 canvases, native-first stats.

    Mirrors :func:`waternet_tpu.ops.transform.transform`'s return-order
    quirk (callers reorder to the network's (x, wb, he, gc))."""
    return (
        white_balance_masked(canvas, h, w),
        gamma_correction(canvas),
        histeq_masked(canvas, h, w),
    )


transform_masked_batch = jax.vmap(transform_masked, in_axes=(0, 0, 0))
transform_masked_batch.__doc__ = (
    "Batched masked transform: (N, CH, CW, 3) canvases + (N,) native h/w "
    "-> 3x (N, CH, CW, 3) float32."
)
