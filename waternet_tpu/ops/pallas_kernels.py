"""Pallas TPU kernels for the preprocessing hot spots.

The only model FLOPs live in convolutions, which XLA already schedules onto
the MXU optimally — hand-writing conv kernels would be a regression. What
XLA does *not* do well on TPU is the scatter-add at the heart of CLAHE's
per-tile histograms (`waternet_tpu.ops.clahe` uses ``jnp.bincount``, which
lowers to a serialized scatter). This module replaces it with a
comparison-matrix reduction that maps onto the VPU:

    hist[t, b] = sum_over_pixels( tile[t, :] == b )

computed as a (chunk, 256) bool matrix sum per grid step — dense, regular,
8x128-lane friendly — accumulated across pixel chunks so arbitrarily large
tiles (1080p frames: 32k+ pixels/tile) never exceed VMEM.

Enabled via ``WATERNET_PALLAS=1`` (or ``use_pallas=True`` arguments); the
default stays the XLA path until the kernel is profiled on real hardware.
Tests run the kernel in interpreter mode on CPU for exactness.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Pixels per accumulation chunk. (CHUNK, 256) f32 compare matrix = 2 MB at
# 2048 — comfortable in ~16 MB VMEM alongside the value chunk.
_CHUNK = 2048
_BINS = 256


def pallas_enabled() -> bool:
    return os.environ.get("WATERNET_PALLAS", "0") == "1"


def _hist_kernel(vals_ref, out_ref):
    """Grid: (n_tiles, n_chunks). Accumulates one tile's histogram."""
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    vals = vals_ref[:]  # (1, CHUNK) int32, padded with -1 beyond the tile
    bins = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK, _BINS), 1)
    onehot = (vals.reshape(_CHUNK, 1) == bins).astype(jnp.int32)
    out_ref[:] = out_ref[:] + jnp.sum(onehot, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _tile_histogram_impl(tiles: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    t, area = tiles.shape
    n_chunks = -(-area // _CHUNK)
    pad = n_chunks * _CHUNK - area
    vals = tiles.astype(jnp.int32)
    if pad:
        vals = jnp.pad(vals, ((0, 0), (0, pad)), constant_values=-1)

    return pl.pallas_call(
        _hist_kernel,
        grid=(t, n_chunks),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((1, _BINS), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t, _BINS), jnp.int32),
        interpret=interpret,
    )(vals)


def tile_histogram(tiles: jnp.ndarray, interpret: bool | None = None) -> jnp.ndarray:
    """(T, A) uint8-valued tiles -> (T, 256) int32 histograms.

    Pallas comparison-reduction kernel; pad pixels (value -1) fall outside
    every bin so partial chunks need no masking. The Mosaic TPU kernel only
    lowers on real TPU backends (including tunnelled plugins that register
    under another platform name); everywhere else interpret mode is
    selected automatically.
    """
    if interpret is None:
        from waternet_tpu.utils.platform import is_tpu_backend

        interpret = not is_tpu_backend()
    return _tile_histogram_impl(tiles, interpret)
