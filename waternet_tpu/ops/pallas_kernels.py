"""Pallas TPU kernels for the preprocessing hot spots.

The only model FLOPs live in convolutions, which XLA already schedules onto
the MXU optimally — hand-writing conv kernels would be a regression. What
XLA does *not* do well on TPU is the scatter-add at the heart of CLAHE's
per-tile histograms (`waternet_tpu.ops.clahe` uses ``jnp.bincount``, which
lowers to a serialized scatter) and the HBM byte stream of the one-hot
LUT-interpolation matmul (~1 GB/frame at 1080p — the round-5 hog,
docs/CLAHE_1080.md). Four kernels:

* :func:`tile_histogram` — per-tile histograms as a comparison-matrix
  reduction on the VPU::

      hist[t, b] = sum_over_pixels( tile[t, :] == b )

  computed as a (chunk, 256) bool matrix sum per grid step — dense,
  regular, 8x128-lane friendly — accumulated across pixel chunks so
  arbitrarily large tiles (1080p frames: 32k+ pixels/tile) never exceed
  VMEM.
* :func:`tile_lut` — the same histogram accumulation FUSED with OpenCV's
  integer clip/redistribute and the rounded scaled CDF, emitting the
  per-tile LUTs directly: the histogram never round-trips HBM between
  the three stages. Bit-identical to the lax pipeline
  (``clahe._tile_hist`` + ``clahe._luts_from_hist``) — same integer ops,
  same single-rounded float32 LUT scale.
* :func:`clahe_lut_planes` — all four quadrant LUT lookups in one kernel
  over the cell decomposition (every pixel of a cell interpolates
  between the same four tile LUTs): the one-hot compare matrix lives
  only in VMEM, per (cell-sized) block, instead of streaming a
  (pixels, 256) operand through HBM per quadrant as the XLA matmul path
  must. Lookups are exact (each f32 dot term is one ``1 * value``
  product plus exact zeros); the cheap bilinear blend deliberately stays
  in the caller's XLA program, where its fma contraction matches the lax
  strategies — measured: an in-kernel blend contracts differently and
  flips round() ties by 1 level on ~3e-4 of pixels. Result: bit-identical
  to both lax interpolation strategies.
* :func:`dct8_dequant_idct` — the device-cache codec's decode hot loop
  (``--cache-codec dct8``, waternet_tpu/data/codec.py): dequantize the
  int8 zonal DCT coefficients and apply the inverse transform as one
  VMEM-blocked ``(blocks, Z2) @ (Z2, 64)`` matmul per grid step, the
  identical ``dot_general`` contraction as the lax fallback — decode
  stays bit-identical with the gate on or off (pinned across odd image
  sizes in tests/test_codec.py).

Enabled via ``WATERNET_PALLAS=1`` (or ``use_pallas=True`` arguments); the
default stays the XLA path until the kernels are profiled on real
hardware. Tests run every kernel in interpreter mode on CPU for
exactness (tests/test_pallas.py), including odd tile grids where the
cell decomposition degrades to single rows/columns.
"""

from __future__ import annotations

import functools
import os

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Pixels per accumulation chunk. (CHUNK, 256) f32 compare matrix = 2 MB at
# 2048 — comfortable in ~16 MB VMEM alongside the value chunk.
_CHUNK = 2048
_BINS = 256


def pallas_enabled() -> bool:
    return os.environ.get("WATERNET_PALLAS", "0") == "1"


def _hist_kernel(vals_ref, out_ref):
    """Grid: (n_tiles, n_chunks). Accumulates one tile's histogram."""
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    vals = vals_ref[:]  # (1, CHUNK) int32, padded with -1 beyond the tile
    bins = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK, _BINS), 1)
    onehot = (vals.reshape(_CHUNK, 1) == bins).astype(jnp.int32)
    out_ref[:] = out_ref[:] + jnp.sum(onehot, axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def _tile_histogram_impl(tiles: jnp.ndarray, interpret: bool) -> jnp.ndarray:
    t, area = tiles.shape
    n_chunks = -(-area // _CHUNK)
    pad = n_chunks * _CHUNK - area
    vals = tiles.astype(jnp.int32)
    if pad:
        vals = jnp.pad(vals, ((0, 0), (0, pad)), constant_values=-1)

    return pl.pallas_call(
        _hist_kernel,
        grid=(t, n_chunks),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((1, _BINS), lambda i, j: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((t, _BINS), jnp.int32),
        interpret=interpret,
    )(vals)


def tile_histogram(tiles: jnp.ndarray, interpret: bool | None = None) -> jnp.ndarray:
    """(T, A) uint8-valued tiles -> (T, 256) int32 histograms.

    Pallas comparison-reduction kernel; pad pixels (value -1) fall outside
    every bin so partial chunks need no masking. The Mosaic TPU kernel only
    lowers on real TPU backends (including tunnelled plugins that register
    under another platform name); everywhere else interpret mode is
    selected automatically.
    """
    if interpret is None:
        interpret = _auto_interpret()
    return _tile_histogram_impl(tiles, interpret)


def _auto_interpret() -> bool:
    """Interpreter mode everywhere but a real TPU backend (incl. tunnelled
    plugins registering under another platform name)."""
    from waternet_tpu.utils.platform import is_tpu_backend

    return not is_tpu_backend()


# ---------------------------------------------------------------------------
# Fused histogram -> clip -> redistribute -> CDF -> LUT
# ---------------------------------------------------------------------------


def _lut_kernel(vals_ref, hist_ref, lut_ref, *, clip, scale, n_chunks):
    """Grid: (n_tiles, n_chunks). Accumulates one tile's histogram across
    its pixel chunks; the LAST chunk applies OpenCV's integer clip +
    excess redistribution and emits LUT = round(cdf * scale) in place —
    the exact per-tile arithmetic of ``clahe._luts_from_hist``."""
    step = pl.program_id(1)

    @pl.when(step == 0)
    def _():
        hist_ref[:] = jnp.zeros_like(hist_ref)
        lut_ref[:] = jnp.zeros_like(lut_ref)

    vals = vals_ref[:]  # (1, CHUNK) int32, padded with -1 beyond the tile
    bins = jax.lax.broadcasted_iota(jnp.int32, (_CHUNK, _BINS), 1)
    onehot = (vals.reshape(_CHUNK, 1) == bins).astype(jnp.int32)
    hist_ref[:] = hist_ref[:] + jnp.sum(onehot, axis=0, keepdims=True)

    @pl.when(step == n_chunks - 1)
    def _():
        hist = hist_ref[:]  # (1, 256) accumulated counts
        excess = jnp.sum(jnp.maximum(hist - clip, 0))
        clipped = jnp.minimum(hist, clip) + excess // 256
        residual = excess % 256  # scalar < 256
        stride = jnp.maximum(256 // jnp.maximum(residual, 1), 1)
        b = jax.lax.broadcasted_iota(jnp.int32, (1, _BINS), 1)
        inc = (
            (residual > 0)
            & (b % stride == 0)
            & (b // stride < residual)
        )
        cdf = jnp.cumsum(
            clipped + inc.astype(jnp.int32), axis=-1
        ).astype(jnp.float32)
        lut_ref[:] = jnp.clip(jnp.round(cdf * scale), 0.0, 255.0)


@functools.partial(
    jax.jit, static_argnames=("clip", "scale", "interpret")
)
def _tile_lut_impl(tiles, *, clip, scale, interpret):
    t, area = tiles.shape
    n_chunks = -(-area // _CHUNK)
    pad = n_chunks * _CHUNK - area
    vals = tiles.astype(jnp.int32)
    if pad:
        vals = jnp.pad(vals, ((0, 0), (0, pad)), constant_values=-1)

    _, luts = pl.pallas_call(
        functools.partial(
            _lut_kernel, clip=clip, scale=scale, n_chunks=n_chunks
        ),
        grid=(t, n_chunks),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i, j: (i, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, _BINS), lambda i, j: (i, 0)),
            pl.BlockSpec((1, _BINS), lambda i, j: (i, 0)),
        ),
        out_shape=(
            jax.ShapeDtypeStruct((t, _BINS), jnp.int32),
            jax.ShapeDtypeStruct((t, _BINS), jnp.float32),
        ),
        interpret=interpret,
    )(vals)
    return luts


def tile_lut(
    tiles: jnp.ndarray,
    clip: int,
    lut_scale,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """(T, A) uint8-valued tiles -> (T, 256) float32 CLAHE LUTs, fused.

    One kernel runs histogram accumulation, OpenCV's integer clip limit +
    excess redistribution, the CDF, and the rounded scaled LUT — the
    histogram stays in VMEM across all four stages. ``clip`` is the
    static integer clip limit (``max(int(clip_limit * area / 256), 1)``),
    ``lut_scale`` the single-rounded float32 ``255 / area``. Bit-identical
    to the lax pipeline for any tile count/area (pinned in
    tests/test_pallas.py across odd grids).
    """
    if interpret is None:
        interpret = _auto_interpret()
    return _tile_lut_impl(
        tiles, clip=int(clip), scale=float(lut_scale), interpret=interpret
    )


# ---------------------------------------------------------------------------
# Fused four-quadrant LUT lookup + bilinear blend
# ---------------------------------------------------------------------------


def _interp_kernel(lut_ref, v_ref, out_ref):
    """Grid: (n_cells_y, n_cells_x). One cell: every pixel shares the same
    four tile LUTs, so all four quadrant lookups are ONE VMEM-local
    one-hot matmul against a (256, 4) table — each output element is a
    single exact ``1 * value`` product plus exact zeros, so the planes
    are bit-identical to gathers."""
    four, ch, cw = out_ref.shape
    pix = ch * cw
    v = v_ref[:].reshape(pix, 1)
    bins = jax.lax.broadcasted_iota(jnp.int32, (pix, _BINS), 1)
    onehot = (v == bins).astype(jnp.float32)
    tables = lut_ref[0, 0]  # (256, 4): quadrants 11, 12, 21, 22
    looked = jax.lax.dot_general(
        onehot,
        tables,
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (pix, 4)
    out_ref[:] = looked.T.reshape(4, ch, cw)


@functools.partial(
    jax.jit, static_argnames=("cell_h", "cell_w", "interpret")
)
def _lut_interp_impl(cell_luts, v_pad, *, cell_h, cell_w, interpret):
    hp, wp = v_pad.shape
    ncy, ncx = hp // cell_h, wp // cell_w
    return pl.pallas_call(
        _interp_kernel,
        grid=(ncy, ncx),
        in_specs=[
            pl.BlockSpec((1, 1, _BINS, 4), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec((cell_h, cell_w), lambda i, j: (i, j)),
        ],
        out_specs=pl.BlockSpec((4, cell_h, cell_w), lambda i, j: (0, i, j)),
        out_shape=jax.ShapeDtypeStruct((4, hp, wp), jnp.float32),
        interpret=interpret,
    )(cell_luts, v_pad.astype(jnp.int32))


def clahe_lut_planes(
    luts: jnp.ndarray,
    v_pad: jnp.ndarray,
    cells_y,
    cells_x,
    cell_h: int,
    cell_w: int,
    interpret: bool | None = None,
):
    """Fused four-quadrant CLAHE LUT lookup over the cell decomposition.

    Args:
        luts: (ty, tx, 256) float32 per-tile LUTs.
        v_pad: (hp, wp) integer-valued L channel on the padded grid;
            ``hp % cell_h == 0`` and ``wp % cell_w == 0`` (the cell
            decomposition partitions the padded grid by construction).
        cells_y / cells_x: ``(lo, hi)`` per-cell tile indices along each
            axis (from ``clahe._cell_tile_indices``, possibly subdivided).
    Returns:
        Four (hp, wp) float32 planes (quadrants 11, 12, 21, 22) holding
        exact LUT values — bit-identical to the gather/matmul lookups.

    The bilinear blend deliberately stays OUTSIDE the kernel, in the
    caller's XLA program: the blend is 1-ulp sensitive to fma contraction
    (documented in docs/SERVING.md for the serving variant), and a
    separately-compiled kernel program contracts it differently than the
    lax paths — moving only the lookups (the actual HBM byte-stream hog:
    a (pixels, 256) one-hot operand per quadrant in the XLA matmul
    formulation) into VMEM-local blocks keeps the whole CLAHE output
    bit-identical across all three interpolation strategies. The
    (cells, 256, 4) quadrant table is gathered outside the kernel — tiny
    (4 KB per cell) next to the per-pixel one-hot stream.
    """
    if interpret is None:
        interpret = _auto_interpret()
    y1, y2 = (jnp.asarray(c) for c in cells_y)
    x1, x2 = (jnp.asarray(c) for c in cells_x)

    def tab(yi, xi):  # (ncy, ncx, 256)
        return luts[yi[:, None], xi[None, :], :]

    cell_luts = jnp.stack(
        [tab(y1, x1), tab(y1, x2), tab(y2, x1), tab(y2, x2)], axis=-1
    )  # (ncy, ncx, 256, 4) — quadrant order matches the kernel unpack
    planes = _lut_interp_impl(
        cell_luts, v_pad,
        cell_h=int(cell_h), cell_w=int(cell_w), interpret=interpret,
    )
    return planes[0], planes[1], planes[2], planes[3]


# ---------------------------------------------------------------------------
# Device-cache codec: fused dct8 dequantize + inverse DCT
# ---------------------------------------------------------------------------

# Coefficient blocks per grid step. (CHUNK, 64) f32 output block = 128 KB
# at 512 — tiny next to VMEM; the (Z2, 64) IDCT matrix and (1, Z2) quant
# row are broadcast constants.
_DCT_CHUNK = 512


def _dct8_kernel(coef_ref, q_ref, m_ref, out_ref):
    """Grid: (n_chunks,). One chunk of 8x8 block-channels: dequantize and
    inverse-transform as a single dot — the same ``dot_general``
    contraction the lax fallback in waternet_tpu/data/codec.py runs, so
    the two paths stay bit-identical."""
    deq = coef_ref[:].astype(jnp.float32) * q_ref[:]
    out_ref[:] = jax.lax.dot_general(
        deq,
        m_ref[:],
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _dct8_idct_impl(coef, quant, idct_m, interpret):
    nb, z2 = coef.shape
    n_chunks = -(-nb // _DCT_CHUNK)
    pad = n_chunks * _DCT_CHUNK - nb
    if pad:
        coef = jnp.pad(coef, ((0, pad), (0, 0)))
    out = pl.pallas_call(
        _dct8_kernel,
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((_DCT_CHUNK, z2), lambda i: (i, 0)),
            pl.BlockSpec((1, z2), lambda i: (0, 0)),
            pl.BlockSpec((z2, 64), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_DCT_CHUNK, 64), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_chunks * _DCT_CHUNK, 64), jnp.float32),
        interpret=interpret,
    )(coef, quant.reshape(1, z2), idct_m)
    return out[:nb]


def dct8_dequant_idct(
    coef: jnp.ndarray,
    quant: jnp.ndarray,
    idct_m: jnp.ndarray,
    interpret: bool | None = None,
) -> jnp.ndarray:
    """(NB, Z2) int8 zonal DCT coefficients -> (NB, 64) float32 pixel
    blocks (level-shifted; the caller adds 128 and casts).

    ``quant`` is the flat (Z2,) dequantization table and ``idct_m`` the
    (Z2, 64) kept-coefficients -> pixels matrix
    (``codec.DCT8_IDCT_MATRIX``) — passed in rather than imported so this
    module stays a generic kernel library. Bit-identical to the lax
    ``dot_general`` fallback in :func:`waternet_tpu.data.codec.decode`
    (pinned in tests/test_codec.py across odd sizes).
    """
    if interpret is None:
        interpret = _auto_interpret()
    return _dct8_idct_impl(coef, quant, idct_m, interpret)
