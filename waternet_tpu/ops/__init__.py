"""Classical image ops (L1 layer): white balance, gamma, CLAHE.

Each op has a host path (`*_np`, NumPy/cv2, bit-exact vs the reference's
`waternet/data.py`) and a device path (pure JAX, jittable/vmappable, designed
to run fused with the model on TPU).
"""

from waternet_tpu.ops.clahe import clahe, histeq, histeq_np
from waternet_tpu.ops.color import lab_u8_to_rgb, rgb_to_lab_u8
from waternet_tpu.ops.fused import fused_train_preprocess
from waternet_tpu.ops.gamma import gamma_correction, gamma_correction_np
from waternet_tpu.ops.transform import transform, transform_batch, transform_np
from waternet_tpu.ops.wb import white_balance, white_balance_np

__all__ = [
    "clahe",
    "fused_train_preprocess",
    "histeq",
    "histeq_np",
    "lab_u8_to_rgb",
    "rgb_to_lab_u8",
    "gamma_correction",
    "gamma_correction_np",
    "transform",
    "transform_batch",
    "transform_np",
    "white_balance",
    "white_balance_np",
]
