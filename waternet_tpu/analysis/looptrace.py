"""looptrace: dynamic event-loop-lag watchdog (runtime companion of R201).

The static side (:mod:`waternet_tpu.analysis.rules.asynclint`, rule
R201) proves from source that no coroutine reaches known-blocking work
on the loop thread. This module watches what actually happens: a
:class:`LoopTracer` monkeypatches ``asyncio.events.Handle._run`` — the
single funnel every loop callback, task step, and reader/writer
completion goes through — and records each callback's wall time.  Any
single callback past ``threshold_ms`` is a **stall**: for that long,
every open connection, heartbeat, and timer on that loop froze
together.  At teardown :meth:`LoopTracer.assert_no_stall` fails the
test, printing the offending callback (``functools.partial`` chains
unwrapped to the underlying function's ``module.qualname``).

This mirrors the ``CompileSentinel``/``LockTracer`` mold from
docs/LINT.md: the static rule catches hazards visible in the source,
the fixture catches the ones that are not — blocking work reached
through C extensions, data-dependent slow paths, or third-party
callables the may-block fixpoint cannot see.  Usage (see
tests/conftest.py for the ``looptrace`` fixture)::

    tracer = LoopTracer(threshold_ms=500.0)
    tracer.install()
    try:
        ...  # exercise the asyncio code
    finally:
        tracer.uninstall()
    tracer.assert_no_stall()

Design notes:

* The patch is process-wide and thread-agnostic: loops running on
  background threads (``ServingServer.start_background``) are traced
  too, which is exactly where the serving stack runs them in tests.
  Recording takes a real (never-traced) lock only on the slow path.
* Install/uninstall nest LIFO like ``LockTracer``: each tracer captures
  whatever ``_run`` it saw at install time and restores it, so a
  production gauge tracer (``--obs-loop-lag``) and a test fixture can
  coexist.
* Wall-time thresholds on a loaded 1-core box are noisy — scheduler
  preemption charges *someone else's* CPU time to whatever callback was
  running. Pick thresholds well above legitimate callback cost (the
  conftest fixture defaults to 500 ms, overridable via
  ``LOOPTRACE_THRESHOLD_MS``); ``threshold_ms=float("inf")`` records
  lag without ever failing, which is what the production gauge uses.
* ``samples`` is a bounded ring (:class:`collections.deque`), so the
  p99 in :meth:`gauge` is over the most recent ``sample_limit``
  callbacks — deterministic, O(1) memory under sustained load.
"""

from __future__ import annotations

import asyncio.events
import collections
import functools
import threading
import time
from typing import Deque, List, NamedTuple, Optional

__all__ = [
    "LoopTracer",
    "Stall",
    "describe_callback",
    "empty_loop_lag_block",
]

_REAL_LOCK = threading.Lock


def empty_loop_lag_block() -> dict:
    """The all-zeros ``loop_lag`` stats block (``--obs-loop-lag`` off):
    same keys as a live gauge so schema consumers never branch."""
    return {
        "enabled": False,
        "max_ms": 0.0,
        "p99_ms": 0.0,
        "callbacks": 0,
        "stalls": 0,
    }


class Stall(NamedTuple):
    """One callback that held the loop past the threshold."""

    wall_ms: float
    callback: str
    thread: str

    def render(self) -> str:
        return f"{self.wall_ms:.1f} ms in {self.callback} (thread {self.thread!r})"


def describe_callback(handle) -> str:
    """Human name of a Handle's callback: partial chains unwrapped,
    bound methods resolved, ``module.qualname`` preferred."""
    cb = getattr(handle, "_callback", None)
    while isinstance(cb, functools.partial):
        cb = cb.func
    cb = getattr(cb, "__func__", cb)
    qual = getattr(cb, "__qualname__", None)
    if qual is None:
        return repr(cb)
    mod = getattr(cb, "__module__", None)
    return f"{mod}.{qual}" if mod else qual


class LoopTracer:
    """Record per-callback event-loop occupancy; fail on stalls."""

    def __init__(
        self, threshold_ms: float = 500.0, sample_limit: int = 2048
    ):
        self.threshold_ms = threshold_ms
        self.max_ms = 0.0
        self.max_callback: Optional[str] = None
        self.stalls: List[Stall] = []
        self.samples: Deque[float] = collections.deque(maxlen=sample_limit)
        self.callbacks = 0
        self._guts = _REAL_LOCK()
        self._orig = None
        self._installed = False

    # -- Handle._run patching ---------------------------------------------

    def install(self) -> None:
        if self._installed:
            return
        tracer = self
        orig = asyncio.events.Handle._run
        self._orig = orig

        def _run(handle):
            t0 = time.perf_counter()
            try:
                return orig(handle)
            finally:
                tracer._record(handle, (time.perf_counter() - t0) * 1000.0)

        asyncio.events.Handle._run = _run
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        asyncio.events.Handle._run = self._orig
        self._orig = None
        self._installed = False

    # -- hot path ----------------------------------------------------------

    def _record(self, handle, wall_ms: float) -> None:
        with self._guts:
            self.callbacks += 1
            self.samples.append(wall_ms)
            if wall_ms > self.max_ms:
                self.max_ms = wall_ms
                self.max_callback = describe_callback(handle)
            if wall_ms >= self.threshold_ms:
                self.stalls.append(
                    Stall(
                        wall_ms,
                        describe_callback(handle),
                        threading.current_thread().name,
                    )
                )

    # -- teardown analysis / gauge ----------------------------------------

    def p99_ms(self) -> float:
        """p99 over the retained sample window (0.0 when empty)."""
        with self._guts:
            samples = sorted(self.samples)
        if not samples:
            return 0.0
        return samples[min(len(samples) - 1, int(0.99 * (len(samples) - 1)))]

    def gauge(self) -> dict:
        """The ``loop_lag`` stats block (``/stats`` + ``/metrics``)."""
        with self._guts:
            max_ms, callbacks, stalls = (
                self.max_ms, self.callbacks, len(self.stalls)
            )
        return {
            "max_ms": round(max_ms, 3),
            "p99_ms": round(self.p99_ms(), 3),
            "callbacks": callbacks,
            "stalls": stalls,
        }

    def assert_no_stall(self) -> None:
        if not self.stalls:
            return
        lines = [
            f"looptrace: event loop blocked past {self.threshold_ms:.0f} ms "
            f"by a single callback ({len(self.stalls)} stall(s)):"
        ]
        for stall in self.stalls:
            lines.append("  " + stall.render())
        lines.append(
            "Each stall froze every connection, beat, and timer on that "
            "loop simultaneously; move the work to run_in_executor/"
            "to_thread (jaxlint R201 checks the visible cases statically "
            "— see docs/LINT.md)."
        )
        raise AssertionError("\n".join(lines))
