"""Shared AST infrastructure for the jaxlint rules (docs/LINT.md).

The linter is a pure-AST pass — no imports of the linted code, no jax at
lint time — so it can run over accelerator-only modules on any host. The
machinery here is what every rule needs:

* :class:`Finding` — one diagnostic, with suppression state;
* :func:`suppressions` — ``# jaxlint: disable=R00x`` comment parsing
  (tokenize-based, so a ``#`` inside a string literal never counts);
* :class:`ModuleModel` — a per-file semantic model: parent links, import
  alias resolution (``jnp`` -> ``jax.numpy``), and a registry of
  jit-wrapped callables with their ``donate_argnums`` / ``static_argnums``
  metadata, resolved across the idioms this repo actually uses
  (``self.step = jax.jit(fn, ...)`` in a builder method, ``@jax.jit`` and
  ``@partial(jax.jit, ...)`` decorators, module-level wrapping).

Everything is intentionally flow-light: rules prefer missing a hazard to
crying wolf, because tier-1 asserts the tree is clean and a noisy rule
would be suppressed into uselessness within a PR or two.
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, Iterator, Optional

#: Canonical dotted names that produce a jit-compiled callable.
JIT_WRAPPERS = {
    "jax.jit",
    "jax.pjit",
    "jax.experimental.pjit.pjit",
}

#: Canonical names of functools.partial (for ``@partial(jax.jit, ...)``).
PARTIAL_NAMES = {"functools.partial"}

SCOPE_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda, ast.Module)
LOOP_NODES = (ast.For, ast.AsyncFor, ast.While)


@dataclasses.dataclass
class Finding:
    """One diagnostic: rule id, location, message, suppression state."""

    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)

    def render(self) -> str:
        tag = " (suppressed)" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}{tag} {self.message}"


_SUPPRESS_RE = re.compile(
    r"jaxlint:\s*disable(?P<next>-next)?\s*=\s*(?P<rules>[A-Za-z0-9_,\s]+)"
)


def suppressions(source: str) -> dict:
    """``{line: {rule ids}}`` from ``# jaxlint: disable=R00x[,R00y]`` and
    ``# jaxlint: disable-next=R00x`` comments. ``all`` suppresses every
    rule on that line. Free-form justification text after the rule list is
    encouraged and ignored (the first token that isn't an id ends the
    list), e.g. ``# jaxlint: disable=R003 benchmark: the sync IS the
    measurement``.
    """
    out: dict = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _SUPPRESS_RE.search(tok.string)
        if not m:
            continue
        rules = set()
        for part in re.split(r"[\s,]+", m.group("rules").strip()):
            if re.fullmatch(r"[Rr]\d{3}", part):
                rules.add(part.upper())
            elif part.lower() == "all":
                rules.add("ALL")
            else:
                break  # justification text starts here
        if not rules:
            continue
        line = tok.start[0] + (1 if m.group("next") else 0)
        out.setdefault(line, set()).update(rules)
    return out


def is_suppressed(finding: Finding, supp: dict) -> bool:
    rules = supp.get(finding.line, ())
    return finding.rule in rules or "ALL" in rules


def collect_py_files(paths: Iterable) -> list:
    """Expand files/directories into a sorted list of ``.py`` paths."""
    files = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.py")))
        elif p.is_file():
            files.append(p)
        else:
            raise FileNotFoundError(f"jaxlint: no such file or directory: {p}")
    # De-dup while keeping order (a dir arg may repeat an explicit file).
    seen, out = set(), []
    for f in files:
        key = str(f)
        if key not in seen and "__pycache__" not in key:
            seen.add(key)
            out.append(f)
    return out


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------


def annotate_parents(tree: ast.Module) -> None:
    tree._jl_parent = None  # type: ignore[attr-defined]
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            child._jl_parent = node  # type: ignore[attr-defined]


def parent(node: ast.AST) -> Optional[ast.AST]:
    return getattr(node, "_jl_parent", None)


def ancestors(node: ast.AST) -> Iterator[ast.AST]:
    cur = parent(node)
    while cur is not None:
        yield cur
        cur = parent(cur)


def enclosing(node: ast.AST, types) -> Optional[ast.AST]:
    for anc in ancestors(node):
        if isinstance(anc, types):
            return anc
    return None


def enclosing_scope(node: ast.AST) -> Optional[ast.AST]:
    """Nearest enclosing function/lambda/module (skips ClassDef: class
    bodies don't form a name scope visible from methods)."""
    return enclosing(node, SCOPE_NODES)


def enclosing_class(node: ast.AST) -> Optional[ast.ClassDef]:
    return enclosing(node, ast.ClassDef)


def scope_chain(node: ast.AST) -> Iterator[ast.AST]:
    """Enclosing name scopes, innermost first, ending at the module."""
    cur = enclosing_scope(node)
    while cur is not None:
        yield cur
        if isinstance(cur, ast.Module):
            return
        cur = enclosing_scope(cur)


def statement_of(node: ast.AST) -> ast.stmt:
    """The statement a node belongs to (the nearest ``ast.stmt`` ancestor,
    or the node itself when it already is one)."""
    cur = node
    while not isinstance(cur, ast.stmt):
        nxt = parent(cur)
        if nxt is None:
            break
        cur = nxt
    return cur  # type: ignore[return-value]


def dotted_parts(node: ast.AST) -> Optional[list]:
    """``a.b.c`` attribute chain as ``["a", "b", "c"]``; None otherwise."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        parts.reverse()
        return parts
    return None


def const_tuple(node: Optional[ast.AST]) -> tuple:
    """A literal int/str or tuple/list of them as a Python tuple; ``()``
    when absent or not statically resolvable."""
    if node is None:
        return ()
    if isinstance(node, ast.Constant):
        return (node.value,)
    if isinstance(node, (ast.Tuple, ast.List)):
        vals = []
        for e in node.elts:
            if not isinstance(e, ast.Constant):
                return ()
            vals.append(e.value)
        return tuple(vals)
    return ()


def ref_key(node: ast.AST):
    """A stable key for "the same storage location": local names become
    ``("local", name)``, ``self.attr`` becomes ``("self", attr)``; anything
    deeper (``a.b.c``, subscripts) is None — not tracked."""
    if isinstance(node, ast.Name):
        return ("local", node.id)
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return ("self", node.attr)
    return None


def flatten_targets(target: ast.AST) -> Iterator[ast.AST]:
    """Assignment target(s) flattened through tuple/list/star nesting."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from flatten_targets(elt)
    elif isinstance(target, ast.Starred):
        yield from flatten_targets(target.value)
    else:
        yield target


@dataclasses.dataclass
class JitInfo:
    """Static metadata of one jit-wrapped callable."""

    node: ast.AST  # the jax.jit call / decorator expression
    donate_argnums: tuple = ()
    static_argnums: tuple = ()
    static_argnames: tuple = ()
    target: Optional[ast.AST] = None  # the wrapped FunctionDef/Lambda
    binding: Optional[str] = None  # display name of the binding, if any


class ModuleModel:
    """Semantic model of one parsed module, shared by all rules."""

    def __init__(self, path, source: str, tree: ast.Module):
        self.path = str(path)
        self.source = source
        self.tree = tree
        annotate_parents(tree)
        self.aliases: dict = {}
        self._collect_imports()
        #: binding key -> JitInfo. Keys: ("name", scope-node, name) for
        #: plain assignments/defs, ("self", class-node, attr) for
        #: ``self.attr = jax.jit(...)`` inside any method of the class.
        self.jit_bindings: dict = {}
        #: FunctionDef/Lambda node -> JitInfo for every jit target whose
        #: definition is in this module.
        self.jitted_defs: dict = {}
        self._collect_jit()

    # -- imports ---------------------------------------------------------

    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    self.aliases[a.asname or a.name.split(".")[0]] = (
                        a.name if a.asname else a.name.split(".")[0]
                    )
            elif isinstance(node, ast.ImportFrom) and node.module and not node.level:
                for a in node.names:
                    if a.name == "*":
                        continue
                    self.aliases[a.asname or a.name] = f"{node.module}.{a.name}"

    def resolve(self, node: ast.AST) -> Optional[str]:
        """Canonical dotted name of an expression through the module's
        import aliases: with ``import jax.numpy as jnp``, ``jnp.copy``
        resolves to ``"jax.numpy.copy"``. None for non-name expressions."""
        parts = dotted_parts(node)
        if not parts:
            return None
        head = self.aliases.get(parts[0], parts[0])
        return ".".join([head] + parts[1:])

    # -- jit registry ----------------------------------------------------

    def _jit_info_from_call(self, call: ast.Call) -> Optional[JitInfo]:
        if self.resolve(call.func) not in JIT_WRAPPERS:
            return None
        kw = {k.arg: k.value for k in call.keywords if k.arg}
        info = JitInfo(
            node=call,
            donate_argnums=const_tuple(kw.get("donate_argnums")),
            static_argnums=const_tuple(kw.get("static_argnums")),
            static_argnames=const_tuple(kw.get("static_argnames")),
        )
        if call.args:
            fn = call.args[0]
            if isinstance(fn, ast.Lambda):
                info.target = fn
            elif isinstance(fn, ast.Name):
                info.target = self._find_def(fn.id, call)
        return info

    def _decorator_jit_info(self, dec: ast.AST) -> Optional[JitInfo]:
        if self.resolve(dec) in JIT_WRAPPERS:
            return JitInfo(node=dec)
        if isinstance(dec, ast.Call):
            fname = self.resolve(dec.func)
            kw = {k.arg: k.value for k in dec.keywords if k.arg}
            if fname in JIT_WRAPPERS:
                pass
            elif fname in PARTIAL_NAMES or (fname or "").endswith(".partial"):
                if not dec.args or self.resolve(dec.args[0]) not in JIT_WRAPPERS:
                    return None
            else:
                return None
            return JitInfo(
                node=dec,
                donate_argnums=const_tuple(kw.get("donate_argnums")),
                static_argnums=const_tuple(kw.get("static_argnums")),
                static_argnames=const_tuple(kw.get("static_argnames")),
            )
        return None

    def _find_def(self, name: str, from_node: ast.AST) -> Optional[ast.AST]:
        """The FunctionDef named ``name`` visible from ``from_node``'s
        scope chain (nearest enclosing scope wins)."""
        for scope in scope_chain(from_node):
            for stmt in ast.walk(scope):
                if (
                    isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and stmt.name == name
                    and enclosing_scope(stmt) is scope
                ):
                    return stmt
        return None

    def _collect_jit(self) -> None:
        for node in ast.walk(self.tree):
            # name = jax.jit(fn, ...)  /  self.attr = jax.jit(fn, ...)
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                info = self._jit_info_from_call(node.value)
                if info is None:
                    continue
                for target in node.targets:
                    key = ref_key(target)
                    if key is None:
                        continue
                    if key[0] == "local":
                        scope = enclosing_scope(node)
                        info.binding = key[1]
                        self.jit_bindings[("name", scope, key[1])] = info
                    else:  # ("self", attr)
                        cls = enclosing_class(node)
                        if cls is not None:
                            info.binding = f"self.{key[1]}"
                            self.jit_bindings[("self", cls, key[1])] = info
                if info.target is not None:
                    self.jitted_defs[info.target] = info
            # bare jax.jit(lambda/fn) used inline (no binding)
            elif isinstance(node, ast.Call):
                info = self._jit_info_from_call(node)
                if info is not None and info.target is not None:
                    self.jitted_defs.setdefault(info.target, info)
            # @jax.jit / @partial(jax.jit, ...) decorated defs
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    info = self._decorator_jit_info(dec)
                    if info is not None:
                        info.target = node
                        info.binding = node.name
                        self.jitted_defs[node] = info
                        scope = enclosing_scope(node)
                        self.jit_bindings[("name", scope, node.name)] = info
                        break

    def jit_info_for_call(self, call: ast.Call) -> Optional[JitInfo]:
        """JitInfo for a call site of a known jit-wrapped callable:
        ``self.step(...)`` (class registry) or ``step(...)`` (scope-chain
        lookup). None when the callee isn't statically known to be jitted."""
        f = call.func
        if (
            isinstance(f, ast.Attribute)
            and isinstance(f.value, ast.Name)
            and f.value.id == "self"
        ):
            cls = enclosing_class(call)
            if cls is not None:
                return self.jit_bindings.get(("self", cls, f.attr))
            return None
        if isinstance(f, ast.Name):
            for scope in scope_chain(call):
                info = self.jit_bindings.get(("name", scope, f.id))
                if info is not None:
                    return info
        return None

    def static_positions(self, info: JitInfo):
        """(static argnum set, static argname set) for a jit callable,
        mapping ``static_argnames`` onto positions when the target def is
        known in this module."""
        nums = {n for n in info.static_argnums if isinstance(n, int)}
        names = {n for n in info.static_argnames if isinstance(n, str)}
        target = info.target
        if target is not None and not isinstance(target, ast.Lambda):
            params = [a.arg for a in target.args.args]
            for name in list(names):
                if name in params:
                    nums.add(params.index(name))
            for n in list(nums):
                if 0 <= n < len(params):
                    names.add(params[n])
        return nums, names
