"""Rule registry: rules self-register at import; the driver runs them.

A rule is a class with a unique ``id`` (``R00x``), a one-line ``name``,
and a ``check(model)`` generator yielding :class:`~.core.Finding`s for one
:class:`~.core.ModuleModel`. Registration is a decorator so adding a rule
is one module with one class — the CLI, the tier-1 repo gate, and the docs
table all pick it up from here.
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, Optional

from waternet_tpu.analysis.core import Finding, ModuleModel

RULES: Dict[str, "Rule"] = {}


class Rule:
    id: str = ""
    name: str = ""
    description: str = ""
    #: "module" rules see one file at a time; "project" rules (R102) see
    #: every scanned module at once via ``check_project``.
    scope: str = "module"

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        raise NotImplementedError

    def check_project(self, models) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, model: ModuleModel, node, message: str) -> Finding:
        return Finding(
            rule=self.id,
            path=model.path,
            line=getattr(node, "lineno", 0),
            col=getattr(node, "col_offset", 0),
            message=message,
        )


def register(cls):
    rule = cls()
    if not rule.id:
        raise ValueError(f"rule {cls.__name__} has no id")
    if rule.id in RULES:
        raise ValueError(f"duplicate rule id {rule.id}")
    RULES[rule.id] = rule
    return cls


def run_rules(
    model: ModuleModel, rule_ids: Optional[Iterable[str]] = None
) -> list:
    """All module-scope findings for one module, sorted by location."""
    ids = sorted(RULES) if rule_ids is None else list(rule_ids)
    findings = []
    for rid in ids:
        rule = RULES.get(rid)
        if rule is None:
            raise KeyError(f"unknown jaxlint rule: {rid}")
        if rule.scope != "module":
            continue
        findings.extend(rule.check(model))
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def run_project_rules(
    models, rule_ids: Optional[Iterable[str]] = None
) -> list:
    """All project-scope findings over a set of modules (the cross-module
    pass R102 needs: lock-order cycles only exist across files)."""
    ids = sorted(RULES) if rule_ids is None else list(rule_ids)
    findings = []
    for rid in ids:
        rule = RULES.get(rid)
        if rule is None:
            raise KeyError(f"unknown jaxlint rule: {rid}")
        if rule.scope != "project":
            continue
        findings.extend(rule.check_project(models))
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
