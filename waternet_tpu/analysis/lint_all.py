"""waternet-lint — all three rule families in one pass (docs/LINT.md).

CI and the verify recipe used to invoke jaxlint three times (JAX rules,
thread rules, asyncio rules are one registry, but each caller passed its
own path set and merged exit codes by hand). This runner is the single
entry point: one scan over the repo's lint targets, one merged report
with a per-family breakdown, one exit code.

Families are rule-id bands on the shared registry:

======  ==========  ==================================================
R0xx    jaxlint     JAX hazards (donation, RNG, host sync, recompile,
                    tracer leaks)
R1xx    threadlint  thread hazards (guarded-by, lock order, blocking
                    under locks, condition waits, unjoined threads)
R2xx    asynclint   event-loop hazards (blocking in coroutines,
                    fire-and-forget tasks, cross-thread loop access,
                    await under threading locks, swallowed cancel)
======  ==========  ==================================================

Exit codes follow linter convention: 0 clean (suppressed findings are
clean), 1 unsuppressed findings, 2 usage or parse error. ``--json``
emits the machine rendering with the family breakdown folded into the
summary.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path
from typing import Optional

from waternet_tpu.analysis import lint_models, parse_model
from waternet_tpu.analysis.core import collect_py_files
from waternet_tpu.analysis.registry import RULES
from waternet_tpu.analysis.report import summarize

#: The repo's lint surface: the package, the CLIs, and the tools — the
#: same set the tier-1 repo-clean gates pin (tests/test_*lint*.py).
DEFAULT_TARGETS = (
    "waternet_tpu",
    "train.py",
    "score.py",
    "inference.py",
    "bench.py",
    "tools",
)

_FAMILIES = (("R0", "jaxlint"), ("R1", "threadlint"), ("R2", "asynclint"))


def family_of(rule_id: str) -> str:
    for prefix, name in _FAMILIES:
        if rule_id.startswith(prefix):
            return name
    return "other"


def family_summary(findings) -> dict:
    """``{family: {"findings": n, "unsuppressed": n}}`` for every family
    that has at least one registered rule (zeroes included, so a family
    going silent is visible in CI diffs)."""
    out = {
        name: {"findings": 0, "unsuppressed": 0}
        for _prefix, name in _FAMILIES
        if any(family_of(rid) == name for rid in RULES)
    }
    for f in findings:
        fam = out.setdefault(
            family_of(f.rule), {"findings": 0, "unsuppressed": 0}
        )
        fam["findings"] += 1
        if not f.suppressed:
            fam["unsuppressed"] += 1
    return out


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="waternet-lint",
        description=(
            "Run every rule family (jaxlint R0xx, threadlint R1xx, "
            "asynclint R2xx) over the repo lint surface in one pass "
            "with a merged report and a single exit code — docs/LINT.md."
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help=(
            "Python files and/or directories; default is the repo lint "
            f"surface ({', '.join(DEFAULT_TARGETS)}) resolved against "
            "the current directory"
        ),
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p.add_argument(
        "--rules",
        type=str,
        default=None,
        metavar="R201,R102",
        help="run only these rules (default: all registered rules)",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings in the text rendering",
    )
    p.add_argument(
        "--list-rules",
        action="store_true",
        help="print the rule catalogue grouped by family",
    )
    return p.parse_args(argv)


def main(argv: Optional[list] = None) -> int:
    args = parse_args(argv)
    if args.list_rules:
        current = None
        for rid, rule in sorted(RULES.items()):
            fam = family_of(rid)
            if fam != current:
                print(f"[{fam}]")
                current = fam
            print(f"{rid}  {rule.name}: {rule.description}")
        return 0

    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(
                f"waternet-lint: unknown rule(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(RULES))})",
                file=sys.stderr,
            )
            return 2

    paths = args.paths
    if not paths:
        paths = [t for t in DEFAULT_TARGETS if Path(t).exists()]
        if not paths:
            print(
                "waternet-lint: none of the default targets exist here "
                "(run from the repo root or pass paths)",
                file=sys.stderr,
            )
            return 2
    try:
        files = collect_py_files(paths)
    except FileNotFoundError as err:
        print(str(err), file=sys.stderr)
        return 2
    models = []
    for f in files:
        try:
            models.append(parse_model(f))
        except SyntaxError as err:
            print(f"waternet-lint: cannot parse {f}: {err}", file=sys.stderr)
            return 2

    findings = lint_models(models, rules)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    summary = summarize(findings, len(files))
    summary["families"] = family_summary(findings)

    if args.json:
        payload = {
            "summary": summary,
            "rules": {
                rid: {
                    "family": family_of(rid),
                    "name": rule.name,
                    "description": rule.description,
                }
                for rid, rule in sorted(RULES.items())
            },
            "findings": [f.as_dict() for f in findings],
        }
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            if args.show_suppressed or not f.suppressed:
                print(f.render())
        for name, fam in summary["families"].items():
            print(
                f"waternet-lint [{name}]: {fam['unsuppressed']} finding(s), "
                f"{fam['findings'] - fam['unsuppressed']} suppressed"
            )
        print(
            f"waternet-lint: {summary['files_scanned']} file(s), "
            f"{summary['unsuppressed']} finding(s), "
            f"{summary['suppressed']} suppressed"
        )
    return 1 if summary["unsuppressed"] else 0


if __name__ == "__main__":
    sys.exit(main())
