"""jaxlint reporting: human text and machine ``--json`` renderings.

Both renderings carry the same facts — per-finding rule/location/message
plus the scan summary — so CI can consume ``--json`` while the terminal
output stays greppable ``path:line:col: R00x message`` lines.
"""

from __future__ import annotations

import json
from typing import Iterable

from waternet_tpu.analysis.core import Finding
from waternet_tpu.analysis.registry import RULES


def summarize(findings: Iterable[Finding], files_scanned: int) -> dict:
    findings = list(findings)
    unsuppressed = [f for f in findings if not f.suppressed]
    suppressed = [f for f in findings if f.suppressed]
    return {
        "files_scanned": files_scanned,
        "findings": len(findings),
        "unsuppressed": len(unsuppressed),
        "suppressed": len(suppressed),
    }


def render_text(
    findings: Iterable[Finding],
    files_scanned: int,
    show_suppressed: bool = False,
) -> str:
    findings = list(findings)
    lines = [
        f.render()
        for f in findings
        if show_suppressed or not f.suppressed
    ]
    s = summarize(findings, files_scanned)
    lines.append(
        f"jaxlint: {s['files_scanned']} file(s), "
        f"{s['unsuppressed']} finding(s), {s['suppressed']} suppressed"
    )
    return "\n".join(lines)


def render_json(findings: Iterable[Finding], files_scanned: int) -> str:
    findings = list(findings)
    payload = {
        "summary": summarize(findings, files_scanned),
        "rules": {
            rid: {"name": rule.name, "description": rule.description}
            for rid, rule in sorted(RULES.items())
        },
        "findings": [f.as_dict() for f in findings],
    }
    return json.dumps(payload, indent=2)
