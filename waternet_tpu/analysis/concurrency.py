"""Concurrency model shared by the threadlint rules (R101–R105).

The serving/resilience core coordinates eight threaded modules through
locks, conditions, futures, and claim protocols — discipline that PR 9's
review showed is easy to break and expensive to re-derive by hand. This
module gives the rules a semantic model of that discipline, in the same
flow-light spirit as :mod:`waternet_tpu.analysis.core`: prefer missing a
hazard to crying wolf, because tier-1 pins the tree at zero unsuppressed
findings.

Annotation convention (docs/LINT.md "Concurrency rules"):

* ``# guarded-by: self._lock`` on an attribute's declaring assignment
  (normally in ``__init__``) declares that every later write to the
  attribute must hold that lock (R101 enforces it).
* ``# guarded-by: self._lock`` on a ``def`` line declares a helper whose
  CALLERS hold the lock for the whole call — its body counts as locked
  (the ``_retire_generation``-style "caller holds the pool lock" idiom).
* Module-level globals declare the same way against module-level locks
  (``# guarded-by: _SERVE_LOCK`` in resilience/faults.py).

Lock identity is the *declaration site*: ``self._lock`` in class ``C`` of
module ``m`` is one lock for every instance of ``C`` — exactly the
granularity lock-ORDER discipline is defined at. A ``threading.Condition``
built from a known lock aliases to that lock (holding the condition IS
holding the lock).

:func:`build_lock_graph` merges per-module acquisition sites (nested
``with`` blocks, ``.acquire()`` calls, and calls made while holding a
lock, resolved through a repo-wide may-acquire fixpoint) into one static
graph; R102 flags its cycles and the CLI's ``--lock-graph`` renders it as
DOT.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterator, List, NamedTuple, Optional, Set, Tuple

from waternet_tpu.analysis.core import (
    ModuleModel,
    ancestors,
    enclosing_class,
    ref_key,
)

#: Factory callables whose result is a lock-like synchronization object.
LOCK_FACTORIES = {
    "threading.Lock": "lock",
    "threading.RLock": "rlock",
    "threading.Semaphore": "semaphore",
    "threading.BoundedSemaphore": "semaphore",
    "threading.Condition": "condition",
    "asyncio.Lock": "lock",
    "asyncio.Condition": "condition",
}

#: Factory callables that make a class "thread-bearing": its instances
#: run code on more than one thread, so its shared attributes need a
#: declared guard (R101).
THREAD_SPAWNERS = {
    "threading.Thread",
    "concurrent.futures.ThreadPoolExecutor",
}

#: Mutable-container initializers tracked for the undeclared-mutation arm
#: of R101 (queue.Queue is deliberately absent: it locks internally).
_MUTABLE_FACTORIES = {
    "dict", "list", "set", "collections.deque", "collections.defaultdict",
    "collections.OrderedDict", "collections.Counter",
}
_MUTATOR_METHODS = {
    "append", "extend", "add", "update", "insert", "remove", "discard",
    "pop", "popitem", "clear", "setdefault", "appendleft", "popleft",
}

_GUARD_RE = re.compile(r"guarded-by:\s*(?P<lock>[A-Za-z_][A-Za-z0-9_.]*)")

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
_WITH_NODES = (ast.With, ast.AsyncWith)


class LockKey(NamedTuple):
    """Identity of one lock: its declaration site (module, class, name).

    ``cls`` is ``""`` for module-level locks. ``display`` is the short
    human name used in findings and DOT output."""

    path: str
    cls: str
    name: str

    @property
    def display(self) -> str:
        stem = Path(self.path).stem
        owner = f"{stem}.{self.cls}" if self.cls else stem
        return f"{owner}.{self.name}"


def guard_comments(source: str) -> Dict[int, str]:
    """``{line: lock-expression-text}`` from ``# guarded-by: <expr>``
    comments (tokenize-based, like suppression parsing, so a ``#`` inside
    a string never counts)."""
    out: Dict[int, str] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _GUARD_RE.search(tok.string)
        if m:
            out[tok.start[0]] = m.group("lock")
    return out


class ClassInfo:
    """Per-class concurrency facts: locks it owns, guard declarations,
    mutable shared containers, and whether it bears threads."""

    def __init__(self, node: ast.ClassDef):
        self.node = node
        self.name = node.name
        self.locks: Dict[str, str] = {}  # attr -> kind
        self.cond_locks: Dict[str, LockKey] = {}  # condition attr -> lock
        self.guarded: Dict[str, LockKey] = {}  # attr -> declared lock
        self.guard_text: Dict[str, str] = {}  # attr -> declaration text
        self.mutable_attrs: Set[str] = set()
        self.thread_bearing = False
        self.spawn_reason: Optional[str] = None


class ConcurrencyModel:
    """Concurrency view of one :class:`ModuleModel` (pure AST)."""

    def __init__(self, model: ModuleModel):
        self.model = model
        self.guards = guard_comments(model.source)
        self.classes: Dict[ast.ClassDef, ClassInfo] = {}
        self.module_locks: Dict[str, str] = {}  # name -> kind
        self.module_cond_locks: Dict[str, LockKey] = {}
        self.module_guarded: Dict[str, LockKey] = {}
        self.module_guard_text: Dict[str, str] = {}
        self.fn_requires: Dict[ast.AST, Set[LockKey]] = {}
        self._collect_locks()
        self._collect_guards()

    # -- collection ------------------------------------------------------

    def _lock_kind(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            return LOCK_FACTORIES.get(self.model.resolve(value.func) or "")
        return None

    def _collect_locks(self) -> None:
        tree = self.model.tree
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                self.classes[node] = ClassInfo(node)
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            else:
                continue
            kind = self._lock_kind(node.value)
            cls = enclosing_class(node)
            info = self.classes.get(cls) if cls is not None else None
            for target in targets:
                key = ref_key(target)
                if key is None:
                    continue
                if key[0] == "self" and info is not None:
                    if kind is not None:
                        info.locks[key[1]] = kind
                        if kind == "condition":
                            under = self._condition_underlying(node.value, cls)
                            if under is not None:
                                info.cond_locks[key[1]] = under
                    elif self._is_mutable_init(node.value):
                        info.mutable_attrs.add(key[1])
                elif key[0] == "local" and cls is None and kind is not None:
                    # module-level lock (only at module scope)
                    scope = next(
                        (a for a in ancestors(node)
                         if isinstance(a, _FUNCTION_NODES + (ast.Module,))),
                        None,
                    )
                    if isinstance(scope, ast.Module):
                        self.module_locks[key[1]] = kind
                        if kind == "condition":
                            under = self._condition_underlying(node.value, None)
                            if under is not None:
                                self.module_cond_locks[key[1]] = under
        # thread-bearing: a class whose body constructs threads/executors
        # or registers cross-thread future callbacks.
        for cls, info in self.classes.items():
            for node in ast.walk(cls):
                if enclosing_class(node) is not cls:
                    continue
                if isinstance(node, ast.Call):
                    resolved = self.model.resolve(node.func)
                    if resolved in THREAD_SPAWNERS:
                        info.thread_bearing = True
                        info.spawn_reason = resolved
                        break
                    if (
                        isinstance(node.func, ast.Attribute)
                        and node.func.attr == "add_done_callback"
                    ):
                        info.thread_bearing = True
                        info.spawn_reason = "add_done_callback"
                        break

    def _condition_underlying(
        self, value: ast.Call, cls: Optional[ast.ClassDef]
    ) -> Optional[LockKey]:
        """The lock a ``threading.Condition(<lock>)`` wraps, if named."""
        if not value.args:
            return None
        return self._resolve_lock_parts(value.args[0], cls)

    def _is_mutable_init(self, value: ast.AST) -> bool:
        if isinstance(value, (ast.Dict, ast.List, ast.Set, ast.DictComp,
                              ast.ListComp, ast.SetComp)):
            return True
        if isinstance(value, ast.Call):
            return (self.model.resolve(value.func) or "") in _MUTABLE_FACTORIES
        return False

    def _collect_guards(self) -> None:
        if not self.guards:
            return
        for node in ast.walk(self.model.tree):
            lines = range(
                getattr(node, "lineno", 0),
                (getattr(node, "end_lineno", 0) or 0) + 1,
            )
            text = next(
                (self.guards[ln] for ln in lines if ln in self.guards), None
            )
            if text is None:
                continue
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # a def-line guard means: callers hold this lock for the
                # whole call — the body counts as locked.
                if node.lineno in self.guards:
                    key = self._resolve_lock_text(text, enclosing_class(node))
                    if key is not None:
                        self.fn_requires.setdefault(node, set()).add(key)
            elif isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                cls = enclosing_class(node)
                key = self._resolve_lock_text(text, cls)
                if key is None:
                    continue
                for target in targets:
                    tk = ref_key(target)
                    if tk is None:
                        continue
                    if tk[0] == "self" and cls is not None:
                        info = self.classes[cls]
                        info.guarded[tk[1]] = key
                        info.guard_text[tk[1]] = text
                    elif tk[0] == "local" and cls is None:
                        self.module_guarded[tk[1]] = key
                        self.module_guard_text[tk[1]] = text

    # -- lock resolution -------------------------------------------------

    def _class_lock_names(self, info: ClassInfo) -> Set[str]:
        """Attrs of a class that name a lock: constructed locks plus any
        lock named in a guard declaration (a lock built by a helper
        factory still counts once something declares against it)."""
        names = set(info.locks)
        for key in info.guarded.values():
            if key.cls == info.name and key.path == self.model.path:
                names.add(key.name)
        return names

    def _resolve_lock_parts(
        self, expr: ast.AST, cls: Optional[ast.ClassDef]
    ) -> Optional[LockKey]:
        """``self.X`` / bare ``X`` -> LockKey, honoring condition->lock
        aliasing. None for anything not statically known to be a lock."""
        path = self.model.path
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            info = self.classes.get(cls)
            if info is None:
                return None
            attr = expr.attr
            if attr in info.cond_locks:
                return info.cond_locks[attr]
            if attr in self._class_lock_names(info):
                return LockKey(path, info.name, attr)
            return None
        if isinstance(expr, ast.Name):
            name = expr.id
            if name in self.module_cond_locks:
                return self.module_cond_locks[name]
            if name in self.module_locks or name in {
                k.name for k in self.module_guarded.values() if not k.cls
            }:
                return LockKey(path, "", name)
        return None

    def _resolve_lock_text(
        self, text: str, cls: Optional[ast.ClassDef]
    ) -> Optional[LockKey]:
        try:
            expr = ast.parse(text, mode="eval").body
        except SyntaxError:
            return None
        # a declaration DEFINES the lock name — resolve leniently.
        if (
            isinstance(expr, ast.Attribute)
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
            and cls is not None
        ):
            info = self.classes.get(cls)
            if info is not None and expr.attr in info.cond_locks:
                return info.cond_locks[expr.attr]
            return LockKey(self.model.path, cls.name, expr.attr)
        if isinstance(expr, ast.Name):
            if expr.id in self.module_cond_locks:
                return self.module_cond_locks[expr.id]
            return LockKey(self.model.path, "", expr.id)
        return None

    def lock_key_of_expr(self, expr: ast.AST) -> Optional[LockKey]:
        return self._resolve_lock_parts(expr, enclosing_class(expr))

    # -- held-lock computation -------------------------------------------

    def held_locks(self, node: ast.AST) -> Set[LockKey]:
        """Locks lexically held at ``node``: enclosing ``with`` blocks on
        known locks, plus a def-line guard on the enclosing function.
        Stops at the function boundary — a closure defined under a lock
        does not RUN under it."""
        held: Set[LockKey] = set()
        child = node
        for anc in ancestors(node):
            if isinstance(anc, _WITH_NODES) and child in anc.body:
                for item in anc.items:
                    key = self.lock_key_of_expr(item.context_expr)
                    if key is not None:
                        held.add(key)
            elif isinstance(anc, _FUNCTION_NODES):
                held |= self.fn_requires.get(anc, set())
                break
            child = anc
        return held

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in ancestors(node):
            if isinstance(anc, _FUNCTION_NODES):
                return anc
        return None

    # -- acquisition + call events (R102 feedstock) ----------------------

    def acquisition_events(self) -> Iterator[Tuple[ast.AST, LockKey, Set[LockKey]]]:
        """Yield ``(site, acquired, held_before)`` for every static
        acquisition: ``with`` items (multi-item withs acquire left to
        right) and explicit ``.acquire()`` calls on resolvable locks."""
        for node in ast.walk(self.model.tree):
            if isinstance(node, _WITH_NODES):
                held = self.held_locks(node)
                acquired_here: Set[LockKey] = set()
                for item in node.items:
                    key = self.lock_key_of_expr(item.context_expr)
                    if key is None:
                        continue
                    yield item.context_expr, key, held | acquired_here
                    acquired_here.add(key)
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "acquire"
            ):
                key = self.lock_key_of_expr(node.func.value)
                if key is not None:
                    yield node, key, self.held_locks(node)

    def call_events(self) -> Iterator[Tuple[ast.Call, tuple, Set[LockKey]]]:
        """Yield ``(call, descriptor, held)`` for calls the project pass
        may resolve to repo functions. Descriptors:
        ``("self_method", ClassDef, name)``, ``("module_fn", name)``,
        ``("method_name", name)`` (resolved only if repo-unique)."""
        for node in ast.walk(self.model.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = self.enclosing_function(node)
            if fn is None:
                continue
            held = self.held_locks(node)
            f = node.func
            if isinstance(f, ast.Attribute):
                if (
                    isinstance(f.value, ast.Name)
                    and f.value.id == "self"
                    and enclosing_class(node) is not None
                ):
                    yield node, ("self_method", enclosing_class(node), f.attr), held
                else:
                    yield node, ("method_name", f.attr), held
            elif isinstance(f, ast.Name):
                yield node, ("module_fn", f.id), held


# ---------------------------------------------------------------------------
# Whole-repo static lock-acquisition graph (R102 / --lock-graph)
# ---------------------------------------------------------------------------


class LockGraph:
    """Directed graph over :class:`LockKey`s: an edge A -> B means some
    code path acquires B while holding A. ``sites[(A, B)]`` names one
    witness per edge."""

    def __init__(self):
        self.edges: Dict[LockKey, Set[LockKey]] = {}
        self.sites: Dict[Tuple[LockKey, LockKey], Tuple[str, int]] = {}

    def add(self, a: LockKey, b: LockKey, path: str, line: int) -> None:
        if a == b:
            return  # reentrancy is R103/R101 territory, not ordering
        self.edges.setdefault(a, set()).add(b)
        self.edges.setdefault(b, set())
        self.sites.setdefault((a, b), (path, line))

    def cycles(self) -> List[List[LockKey]]:
        """One simple cycle per strongly connected component with > 1
        node (iterative Tarjan; deterministic order)."""
        index: Dict[LockKey, int] = {}
        low: Dict[LockKey, int] = {}
        on_stack: Set[LockKey] = set()
        stack: List[LockKey] = []
        sccs: List[List[LockKey]] = []
        counter = [0]

        for root in sorted(self.edges):
            if root in index:
                continue
            work = [(root, iter(sorted(self.edges.get(root, ()))))]
            index[root] = low[root] = counter[0]
            counter[0] += 1
            stack.append(root)
            on_stack.add(root)
            while work:
                node, it = work[-1]
                advanced = False
                for nxt in it:
                    if nxt not in index:
                        index[nxt] = low[nxt] = counter[0]
                        counter[0] += 1
                        stack.append(nxt)
                        on_stack.add(nxt)
                        work.append(
                            (nxt, iter(sorted(self.edges.get(nxt, ()))))
                        )
                        advanced = True
                        break
                    if nxt in on_stack:
                        low[node] = min(low[node], index[nxt])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    comp = []
                    while True:
                        w = stack.pop()
                        on_stack.discard(w)
                        comp.append(w)
                        if w == node:
                            break
                    if len(comp) > 1:
                        sccs.append(sorted(comp))
        return [self._cycle_in(scc) for scc in sccs]

    def _cycle_in(self, scc: List[LockKey]) -> List[LockKey]:
        """A concrete simple cycle inside one SCC, for the finding."""
        members = set(scc)
        start = scc[0]
        path = [start]
        seen = {start}
        node = start
        while True:
            nxt = next(
                n for n in sorted(self.edges.get(node, ()))
                if n in members
            )
            if nxt == start:
                return path
            if nxt in seen:
                i = path.index(nxt)
                return path[i:]
            path.append(nxt)
            seen.add(nxt)
            node = nxt

    def to_dot(self) -> str:
        lines = [
            "digraph lock_order {",
            "  rankdir=LR;",
            '  node [shape=box, fontname="monospace"];',
        ]
        nodes = sorted(self.edges)
        for n in nodes:
            lines.append(f'  "{n.display}";')
        for a in nodes:
            for b in sorted(self.edges[a]):
                path, line = self.sites[(a, b)]
                label = f"{Path(path).name}:{line}"
                lines.append(
                    f'  "{a.display}" -> "{b.display}" [label="{label}"];'
                )
        lines.append("}")
        return "\n".join(lines)


def build_lock_graph(models) -> LockGraph:
    """The static lock-acquisition graph over a set of modules.

    Direct edges come from nested ``with``/``acquire`` sites; indirect
    edges from calls made while holding a lock, through a repo-wide
    may-acquire fixpoint (``self.m()`` resolves in-class, ``f()``
    in-module, and ``obj.m()`` only when the method name is unique across
    every scanned class — ambiguity resolves to nothing, by design)."""
    cms = [ConcurrencyModel(m) for m in models]
    graph = LockGraph()

    # function tables ----------------------------------------------------
    method_index: Dict[str, List[ast.AST]] = {}
    fn_direct: Dict[ast.AST, Set[LockKey]] = {}
    fn_calls: Dict[ast.AST, List[tuple]] = {}
    module_fns: Dict[int, Dict[str, ast.AST]] = {}

    for cm in cms:
        tree = cm.model.tree
        fns_by_name: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fn_direct.setdefault(node, set())
                fn_calls.setdefault(node, [])
                cls = enclosing_class(node)
                if cls is not None:
                    method_index.setdefault(node.name, []).append(node)
                else:
                    fns_by_name.setdefault(node.name, node)
        module_fns[id(cm)] = fns_by_name

    for cm in cms:
        for site, key, held in cm.acquisition_events():
            fn = cm.enclosing_function(site)
            if fn is not None and fn in fn_direct:
                fn_direct[fn].add(key)
            for h in held:
                graph.add(h, key, cm.model.path, getattr(site, "lineno", 0))
        class_methods: Dict[ast.ClassDef, Dict[str, ast.AST]] = {}
        for call, desc, held in cm.call_events():
            fn = cm.enclosing_function(call)
            if fn is None or fn not in fn_calls:
                continue
            target: Optional[ast.AST] = None
            if desc[0] == "self_method":
                _, cls, name = desc
                if cls not in class_methods:
                    class_methods[cls] = {
                        n.name: n
                        for n in ast.walk(cls)
                        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and enclosing_class(n) is cls
                    }
                target = class_methods[cls].get(name)
            elif desc[0] == "module_fn":
                target = module_fns[id(cm)].get(desc[1])
            elif desc[0] == "method_name":
                candidates = method_index.get(desc[1], [])
                if len(candidates) == 1:
                    target = candidates[0]
            if target is not None:
                fn_calls[fn].append((call, target, held, cm.model.path))

    # may-acquire fixpoint ----------------------------------------------
    may: Dict[ast.AST, Set[LockKey]] = {
        fn: set(direct) for fn, direct in fn_direct.items()
    }
    changed = True
    while changed:
        changed = False
        for fn, calls in fn_calls.items():
            acc = may[fn]
            before = len(acc)
            for _, target, _, _ in calls:
                acc |= may.get(target, set())
            if len(acc) != before:
                changed = True

    # call-propagated edges ---------------------------------------------
    for fn, calls in fn_calls.items():
        for call, target, held, path in calls:
            if not held:
                continue
            for h in held:
                for k in may.get(target, ()):
                    graph.add(h, k, path, getattr(call, "lineno", 0))
    return graph
