"""jaxlint — static analysis for JAX-specific hazards (docs/LINT.md).

Pure-AST: linting never imports the linted code, so it runs anywhere (no
accelerator, no jax session) and is safe inside the tier-1 budget. The
rules encode invariants the repo otherwise enforces only by convention
or by expensive dynamic tests:

======  =====================  ==================================================
R001    donation-after-use     donated buffer read after the call / aliases host
R002    rng-key-reuse          PRNG key consumed twice without split/fold_in
R003    host-sync-in-hot-loop  .item()/float()/np.asarray in a dispatching loop
R004    recompile-hazard       unhashable statics, jit-in-loop, traced branches
R005    tracer-leak            traced values stored into self/globals/closures
======  =====================  ==================================================

Suppress a deliberate pattern with ``# jaxlint: disable=R00x <why>`` on
the line (or ``disable-next=`` on the line above); the justification text
is free-form and strongly encouraged. ``tests/test_jaxlint.py::
test_repo_clean`` asserts zero unsuppressed findings over the package and
the CLIs, so every new hazard is either fixed or visibly argued for.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, Optional

from waternet_tpu.analysis.core import (  # noqa: F401
    Finding,
    ModuleModel,
    collect_py_files,
    is_suppressed,
    suppressions,
)
from waternet_tpu.analysis.registry import RULES, run_rules  # noqa: F401
import waternet_tpu.analysis.rules  # noqa: F401  (registers the rules)


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Iterable[str]] = None,
) -> list:
    """Lint one module's source text; returns findings with suppression
    state resolved. Raises ``SyntaxError`` when the source doesn't parse
    (the CLI maps that to exit code 2)."""
    tree = ast.parse(source, filename=str(path))
    model = ModuleModel(path, source, tree)
    findings = run_rules(model, rules)
    supp = suppressions(source)
    for f in findings:
        f.suppressed = is_suppressed(f, supp)
    return findings


def lint_file(path, rules: Optional[Iterable[str]] = None) -> list:
    return lint_source(
        Path(path).read_text(encoding="utf-8"), str(path), rules
    )


def lint_paths(paths: Iterable, rules: Optional[Iterable[str]] = None):
    """Lint files/directories; returns ``(findings, files_scanned)``."""
    files = collect_py_files(paths)
    findings = []
    for f in files:
        findings.extend(lint_file(f, rules))
    return findings, len(files)
