"""jaxlint — static analysis for JAX and concurrency hazards (docs/LINT.md).

Pure-AST: linting never imports the linted code, so it runs anywhere (no
accelerator, no jax session) and is safe inside the tier-1 budget. The
rules encode invariants the repo otherwise enforces only by convention
or by expensive dynamic tests:

======  ===============================  ==================================================
R001    donation-after-use               donated buffer read after the call / aliases host
R002    rng-key-reuse                    PRNG key consumed twice without split/fold_in
R003    host-sync-in-hot-loop            .item()/float()/np.asarray in a dispatching loop
R004    recompile-hazard                 unhashable statics, jit-in-loop, traced branches
R005    tracer-leak                      traced values stored into self/globals/closures
R101    unguarded-shared-mutation        `# guarded-by:` attr written outside its lock
R102    lock-order-inversion             cycle in the whole-repo lock-acquisition graph
R103    blocking-call-under-lock         result()/join()/get()/sleep/host-sync under a lock
R104    condition-wait-without-predicate Condition.wait() not re-checked in a while loop
R105    unjoined-thread                  non-daemon Thread started with no join/leak guard
R201    blocking-call-in-coroutine       blocking work reachable from a coroutine, no executor
R202    fire-and-forget-task             unretained create_task / bare unawaited coroutine call
R203    cross-thread-loop-access         non-threadsafe loop/future calls from off-loop code
R204    await-under-threading-lock       await while lexically holding a threading.* lock
R205    swallowed-cancellation           CancelledError caught in a coroutine, not re-raised
======  ===============================  ==================================================

Suppress a deliberate pattern with ``# jaxlint: disable=R00x <why>`` on
the line (or ``disable-next=`` on the line above); the justification text
is free-form and strongly encouraged. ``tests/test_jaxlint.py::
test_repo_clean``, ``tests/test_threadlint.py::test_repo_clean``, and
``tests/test_asynclint.py::test_repo_clean`` assert zero unsuppressed
findings over the package, the CLIs, and ``tools/``, so every new
hazard is either fixed or visibly argued for. ``waternet-lint``
(``lint_all.py``) runs all three families in one invocation.

R102 is project-scope: it builds one static lock-acquisition graph over
every scanned module (nested ``with``/``acquire`` sites plus calls made
while holding a lock) and flags its cycles. ``jaxlint --lock-graph``
renders the same graph as DOT.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Iterable, Optional

from waternet_tpu.analysis.concurrency import (  # noqa: F401
    LockGraph,
    build_lock_graph,
)
from waternet_tpu.analysis.core import (  # noqa: F401
    Finding,
    ModuleModel,
    collect_py_files,
    is_suppressed,
    suppressions,
)
from waternet_tpu.analysis.registry import (  # noqa: F401
    RULES,
    run_project_rules,
    run_rules,
)
import waternet_tpu.analysis.rules  # noqa: F401  (registers the rules)


def parse_model(path) -> ModuleModel:
    """Parse one file into a :class:`ModuleModel` (raises SyntaxError)."""
    source = Path(path).read_text(encoding="utf-8")
    tree = ast.parse(source, filename=str(path))
    return ModuleModel(str(path), source, tree)


def lint_models(models, rules: Optional[Iterable[str]] = None) -> list:
    """Module rules per model, then the project rules over all of them,
    with per-file suppression state resolved."""
    findings = []
    for model in models:
        findings.extend(run_rules(model, rules))
    findings.extend(run_project_rules(models, rules))
    supp_by_path = {m.path: suppressions(m.source) for m in models}
    for f in findings:
        f.suppressed = is_suppressed(f, supp_by_path.get(f.path, {}))
    return findings


def lint_source(
    source: str,
    path: str = "<string>",
    rules: Optional[Iterable[str]] = None,
) -> list:
    """Lint one module's source text; returns findings with suppression
    state resolved (project rules run over the one-module project).
    Raises ``SyntaxError`` when the source doesn't parse (the CLI maps
    that to exit code 2)."""
    tree = ast.parse(source, filename=str(path))
    model = ModuleModel(path, source, tree)
    findings = lint_models([model], rules)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def lint_file(path, rules: Optional[Iterable[str]] = None) -> list:
    return lint_source(
        Path(path).read_text(encoding="utf-8"), str(path), rules
    )


def lint_paths(paths: Iterable, rules: Optional[Iterable[str]] = None):
    """Lint files/directories as ONE project (R102 sees the whole set);
    returns ``(findings, files_scanned)``."""
    files = collect_py_files(paths)
    models = [parse_model(f) for f in files]
    return lint_models(models, rules), len(files)
