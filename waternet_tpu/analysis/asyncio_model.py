"""Coroutine/event-loop model shared by the asynclint rules (R201–R205).

The serving front door, stream sessions, fleet router, and reuse layer
are asyncio-based: one blocking call inside a coroutine stalls the whole
event loop and silently moves every concurrent stream's p99 — exactly
the signal the burn-rate SLO engine pages on. This module gives the
rules a semantic model of that discipline, in the same flow-light spirit
as :mod:`waternet_tpu.analysis.core` and
:mod:`waternet_tpu.analysis.concurrency`: prefer missing a hazard to
crying wolf, because tier-1 pins the tree at zero unsuppressed findings.

Annotation convention (docs/LINT.md "Asyncio rules"):

* ``# loop-blocking: <why>`` on a ``def`` line declares that the
  function does work too heavy for the event loop (a full-frame numpy
  warp, a large encode) even though its body names nothing in the
  blocking taxonomy. The may-block fixpoint treats it exactly like a
  ``time.sleep`` — any coroutine reaching it without an executor wrap
  trips R201.

What the model knows, per module (:class:`AsyncioModel`):

* the coroutine inventory (every ``async def``, including nested ones);
* lock *provenance* — which declared lock attrs were built by
  ``threading.*`` factories vs ``asyncio.*`` ones (R204 only cares
  about the former: holding an asyncio lock across an ``await`` is the
  point of asyncio locks);
* task-retention facts — names assigned from ``create_task`` /
  ``ensure_future`` (calling ``.result()`` on a reaped task is fine;
  on a ``concurrent.futures.Future`` it blocks);
* loop-future provenance — refs assigned from ``<loop>.create_future()``
  (their ``set_result`` from a worker thread is the R203 hazard).

And per project (:class:`AsyncProject`), mirroring the lock graph's
call resolution: a repo-wide may-block fixpoint over *sync* functions
(``self.m()`` resolves in-class, ``f()`` in-module, imported names
through the alias table when the target module is in the scan set, and
``obj.m()`` only when the method name is repo-unique), plus the
off-loop closure — functions reachable from ``Thread(target=...)``,
``run_in_executor`` / ``to_thread`` arguments, and
``add_done_callback`` registrations, i.e. code that must not touch the
loop without ``call_soon_threadsafe`` (R203).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterator, List, NamedTuple, Optional, Set, Tuple

from waternet_tpu.analysis.concurrency import (
    LOCK_FACTORIES,
    LockKey,
    ConcurrencyModel,
)
from waternet_tpu.analysis.core import (
    ModuleModel,
    ancestors,
    enclosing_class,
    parent,
    ref_key,
)

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: Canonical dotted names that block the calling thread — reaching one
#: of these from a coroutine without an executor wrap stalls the loop.
BLOCKING_CALLS = {
    "time.sleep": "time.sleep() suspends the loop thread",
    "jax.device_get": "jax.device_get() synchronizes with the device",
    "jax.block_until_ready": "block_until_ready() synchronizes with the device",
    "cv2.imdecode": "cv2.imdecode() is CPU-bound decode work",
    "cv2.imencode": "cv2.imencode() is CPU-bound encode work",
    "cv2.cvtColor": "cv2.cvtColor() is CPU-bound image work",
    "cv2.resize": "cv2.resize() is CPU-bound image work",
    "cv2.GaussianBlur": "cv2.GaussianBlur() is CPU-bound image work",
    "open": "open() is blocking file I/O",
    "socket.create_connection": "socket.create_connection() is blocking network I/O",
    "urllib.request.urlopen": "urlopen() is blocking network I/O",
    "requests.get": "requests.get() is blocking network I/O",
    "requests.post": "requests.post() is blocking network I/O",
    "subprocess.run": "subprocess.run() waits on a child process",
    "subprocess.call": "subprocess.call() waits on a child process",
    "subprocess.check_call": "subprocess.check_call() waits on a child process",
    "subprocess.check_output": "subprocess.check_output() waits on a child process",
}

#: Canonical names whose *argument* is scheduled, not called here —
#: ``ensure_future(coro())`` is retention, not a bare call.
ASYNC_WRAPPERS = {
    "asyncio.create_task",
    "asyncio.ensure_future",
    "asyncio.gather",
    "asyncio.wait",
    "asyncio.wait_for",
    "asyncio.shield",
    "asyncio.as_completed",
    "asyncio.run",
}

#: Loop methods that are only safe from the loop thread itself.
LOOP_ONLY_METHODS = {
    "call_soon",
    "call_later",
    "call_at",
    "create_task",
    "create_future",
    "stop",
    "close",
}

_LOOP_BLOCKING_RE = re.compile(r"loop-blocking:\s*(?P<why>.*\S)")


def loop_blocking_comments(source: str) -> Dict[int, str]:
    """``{line: why-text}`` from ``# loop-blocking: <why>`` comments
    (tokenize-based, like suppression parsing, so a ``#`` inside a
    string never counts)."""
    out: Dict[int, str] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(source).readline))
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return out
    for tok in tokens:
        if tok.type != tokenize.COMMENT:
            continue
        m = _LOOP_BLOCKING_RE.search(tok.string)
        if m:
            out[tok.start[0]] = m.group("why")
    return out


def _dotted_module(path: str) -> Optional[str]:
    """Import path of a scanned file, for cross-module def resolution:
    ``.../waternet_tpu/metrics/flicker.py`` -> ``waternet_tpu.metrics.
    flicker``; a repo-root script like ``train.py`` -> ``train``."""
    parts = Path(path).with_suffix("").parts
    if "waternet_tpu" in parts:
        parts = parts[parts.index("waternet_tpu"):]
    elif len(parts) != 1:
        return None
    if parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts) if parts else None


def _is_false(node: ast.AST) -> bool:
    return isinstance(node, ast.Constant) and node.value is False


class AsyncioModel:
    """Asyncio view of one :class:`ModuleModel` (pure AST)."""

    def __init__(self, model: ModuleModel):
        self.model = model
        self.cm = ConcurrencyModel(model)
        self.loop_blocking = loop_blocking_comments(model.source)
        #: Every ``async def`` in the module, nested ones included.
        self.coroutines: List[ast.AsyncFunctionDef] = [
            n for n in ast.walk(model.tree)
            if isinstance(n, ast.AsyncFunctionDef)
        ]
        #: LockKey -> canonical factory name ("threading.Lock", ...) for
        #: every lock declaration whose constructor is visible. R204
        #: flags only threading-built locks held across an ``await``.
        self.lock_factory: Dict[LockKey, str] = {}
        #: ("self", attr) keys assigned from ``<loop>.create_future()``
        #: anywhere in the class — class name -> key set.
        self.loop_future_attrs: Dict[str, Set[str]] = {}
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.model.tree):
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            else:
                continue
            value = node.value
            factory = None
            if isinstance(value, ast.Call):
                resolved = self.model.resolve(value.func) or ""
                if resolved in LOCK_FACTORIES:
                    factory = resolved
            cls = enclosing_class(node)
            for target in targets:
                key = ref_key(target)
                if key is None:
                    continue
                if factory is not None:
                    if key[0] == "self" and cls is not None:
                        self.lock_factory[
                            LockKey(self.model.path, cls.name, key[1])
                        ] = factory
                    elif key[0] == "local" and cls is None:
                        self.lock_factory[
                            LockKey(self.model.path, "", key[1])
                        ] = factory
                if (
                    key[0] == "self"
                    and cls is not None
                    and self._is_create_future(value)
                ):
                    self.loop_future_attrs.setdefault(cls.name, set()).add(key[1])

    @staticmethod
    def _is_create_future(value: ast.AST) -> bool:
        return (
            isinstance(value, ast.Call)
            and isinstance(value.func, ast.Attribute)
            and value.func.attr == "create_future"
        )

    # -- per-function ref provenance -------------------------------------

    def task_refs(self, fn: ast.AST) -> Set[tuple]:
        """Ref keys assigned from ``create_task`` / ``ensure_future``
        within ``fn`` — an awaited/reaped task's ``.result()`` is
        non-blocking, unlike a ``concurrent.futures.Future``'s."""
        refs: Set[tuple] = set()
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            if self.enclosing_function(node) is not fn:
                continue
            value = node.value
            if not isinstance(value, ast.Call):
                continue
            resolved = self.model.resolve(value.func) or ""
            is_spawn = resolved in {"asyncio.create_task", "asyncio.ensure_future"}
            if not is_spawn and isinstance(value.func, ast.Attribute):
                is_spawn = value.func.attr in {"create_task", "ensure_future"}
            if not is_spawn:
                continue
            for target in node.targets:
                key = ref_key(target)
                if key is not None:
                    refs.add(key)
        return refs

    def loop_future_refs(self, fn: ast.AST) -> Set[tuple]:
        """Ref keys within ``fn`` assigned from ``.create_future()``,
        plus the enclosing class's tracked ``self.X`` loop futures."""
        refs: Set[tuple] = set()
        cls = enclosing_class(fn)
        if cls is not None:
            refs |= {
                ("self", a)
                for a in self.loop_future_attrs.get(cls.name, ())
            }
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Assign)
                and self.enclosing_function(node) is fn
                and self._is_create_future(node.value)
            ):
                for target in node.targets:
                    key = ref_key(target)
                    if key is not None:
                        refs.add(key)
        return refs

    # -- structural helpers ----------------------------------------------

    def enclosing_function(self, node: ast.AST) -> Optional[ast.AST]:
        for anc in ancestors(node):
            if isinstance(anc, _FUNCTION_NODES):
                return anc
        return None

    def is_awaited(self, call: ast.Call) -> bool:
        return isinstance(parent(call), ast.Await)

    def in_async_wrapper_arg(self, call: ast.Call) -> bool:
        """True when ``call`` is a direct argument of an asyncio
        scheduling wrapper — ``ensure_future(ev.wait())`` hands the
        coroutine/awaitable to the loop; nothing blocks here."""
        p = parent(call)
        if not isinstance(p, ast.Call) or call is p.func:
            return False
        resolved = self.model.resolve(p.func) or ""
        if resolved in ASYNC_WRAPPERS:
            return True
        return (
            isinstance(p.func, ast.Attribute)
            and p.func.attr in {"create_task", "ensure_future", "run_until_complete"}
        )

    def blocking_reason(self, call: ast.Call) -> Optional[str]:
        """Why this call blocks the calling thread, or None. Direct
        taxonomy only — transitive reach is the project pass's job."""
        resolved = self.model.resolve(call.func)
        if resolved in BLOCKING_CALLS:
            return BLOCKING_CALLS[resolved]
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        kwargs = {k.arg: k.value for k in call.keywords if k.arg}
        if f.attr == "acquire":
            # lock.acquire(False) / acquire(blocking=False) polls.
            if call.args and _is_false(call.args[0]):
                return None
            if _is_false(kwargs.get("blocking", None)):
                return None
            return ".acquire() blocks until the lock is free"
        if f.attr == "wait" and not call.args and not kwargs:
            return ".wait() blocks until the event/condition fires"
        if f.attr == "join" and not call.args and not kwargs:
            # zero-arg only: str.join(it) always has an argument.
            return ".join() blocks until the thread/queue drains"
        if f.attr == "get" and not call.args:
            # dict.get() needs a key, so zero-positional .get() is a
            # queue read; block=False polls.
            if _is_false(kwargs.get("block", None)):
                return None
            return ".get() blocks until an item arrives"
        if f.attr == "result" and not call.args and not kwargs:
            return ".result() blocks until the future resolves"
        return None

    def looks_like_loop(self, expr: ast.AST) -> bool:
        """Heuristic receiver check: ``loop`` / ``self._loop`` /
        anything whose terminal name ends with ``loop``."""
        if isinstance(expr, ast.Name):
            return expr.id == "loop" or expr.id.endswith("_loop")
        if isinstance(expr, ast.Attribute):
            return expr.attr == "loop" or expr.attr.endswith("_loop")
        return False


class BlockingInfo(NamedTuple):
    """Why a function may block: the root reason and the first call hop
    (empty for a direct reason), for finding messages."""

    reason: str
    via: str


class AsyncProject:
    """Project-wide asyncio facts over a set of modules: the may-block
    fixpoint (R201) and the off-loop closure (R203), built on the same
    call-resolution scheme as :func:`build_lock_graph`."""

    def __init__(self, models):
        self.ams = [AsyncioModel(m) for m in models]
        self.am_of_fn: Dict[ast.AST, AsyncioModel] = {}
        self.fn_name: Dict[ast.AST, str] = {}
        self.fn_calls: Dict[ast.AST, List[Tuple[ast.Call, ast.AST]]] = {}
        self.may_block: Dict[ast.AST, BlockingInfo] = {}
        self.off_loop: Dict[ast.AST, str] = {}  # fn -> provenance text
        self._build()

    # -- construction ----------------------------------------------------

    def _build(self) -> None:
        method_index: Dict[str, List[ast.AST]] = {}
        module_fns: Dict[int, Dict[str, ast.AST]] = {}
        fns_by_dotted: Dict[str, ast.AST] = {}
        all_fns: List[ast.AST] = []

        for am in self.ams:
            fns_by_name: Dict[str, ast.AST] = {}
            dotted = _dotted_module(am.model.path)
            for node in ast.walk(am.model.tree):
                if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                all_fns.append(node)
                self.am_of_fn[node] = am
                self.fn_name[node] = node.name
                self.fn_calls[node] = []
                cls = enclosing_class(node)
                if cls is not None:
                    method_index.setdefault(node.name, []).append(node)
                else:
                    fns_by_name.setdefault(node.name, node)
                    if dotted is not None:
                        fns_by_dotted[f"{dotted}.{node.name}"] = node
            module_fns[id(am)] = fns_by_name

        # direct blocking facts ------------------------------------------
        for fn in all_fns:
            am = self.am_of_fn[fn]
            if fn.lineno in am.loop_blocking:
                self.may_block[fn] = BlockingInfo(
                    f"declared loop-blocking: {am.loop_blocking[fn.lineno]}", ""
                )
                continue
            if isinstance(fn, ast.AsyncFunctionDef):
                # A coroutine's own blocking calls are its own R201
                # findings; awaiting it never blocks the caller.
                continue
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                if am.enclosing_function(node) is not fn:
                    continue
                reason = am.blocking_reason(node)
                if reason is not None:
                    self.may_block.setdefault(fn, BlockingInfo(reason, ""))
                    break

        # call resolution (build_lock_graph's scheme + imported names) ---
        for am in self.ams:
            class_methods: Dict[ast.ClassDef, Dict[str, ast.AST]] = {}
            for call, desc, _held in am.cm.call_events():
                fn = am.enclosing_function(call)
                if fn not in self.fn_calls:
                    continue
                target: Optional[ast.AST] = None
                if desc[0] == "self_method":
                    _, cls, name = desc
                    if cls not in class_methods:
                        class_methods[cls] = {
                            n.name: n
                            for n in ast.walk(cls)
                            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                            and enclosing_class(n) is cls
                        }
                    target = class_methods[cls].get(name)
                elif desc[0] == "module_fn":
                    target = module_fns[id(am)].get(desc[1])
                    if target is None:
                        resolved = am.model.resolve(call.func)
                        if resolved is not None:
                            target = fns_by_dotted.get(resolved)
                elif desc[0] == "method_name":
                    candidates = method_index.get(desc[1], [])
                    if len(candidates) == 1:
                        target = candidates[0]
                if target is not None:
                    self.fn_calls[fn].append((call, target))

        # may-block fixpoint over sync functions -------------------------
        # (never *into* or *through* coroutines: calling a coroutine
        # function just builds the coroutine object.)
        changed = True
        while changed:
            changed = False
            for fn, calls in self.fn_calls.items():
                if isinstance(fn, ast.AsyncFunctionDef) or fn in self.may_block:
                    continue
                for call, target in calls:
                    if isinstance(target, ast.AsyncFunctionDef):
                        continue
                    info = self.may_block.get(target)
                    if info is not None:
                        self.may_block[fn] = BlockingInfo(
                            info.reason, info.via or f"{self.fn_name[target]}()"
                        )
                        changed = True
                        break

        # off-loop closure (R203 feedstock) ------------------------------
        roots: Dict[ast.AST, str] = {}
        for am in self.ams:
            for fn, why in self._off_loop_roots(am, module_fns[id(am)],
                                                method_index):
                roots.setdefault(fn, why)
        self.off_loop = dict(roots)
        changed = True
        while changed:
            changed = False
            for fn, why in list(self.off_loop.items()):
                for _call, target in self.fn_calls.get(fn, ()):
                    if isinstance(target, ast.AsyncFunctionDef):
                        continue
                    if target not in self.off_loop:
                        self.off_loop[target] = (
                            f"reached from {why} via {self.fn_name[fn]}()"
                        )
                        changed = True

    def _off_loop_roots(
        self,
        am: AsyncioModel,
        fns_by_name: Dict[str, ast.AST],
        method_index: Dict[str, List[ast.AST]],
    ) -> Iterator[Tuple[ast.AST, str]]:
        """Functions handed to another thread: ``Thread(target=f)``,
        ``run_in_executor(None, f, ...)``, ``to_thread(f, ...)``,
        ``fut.add_done_callback(f)`` (completion threads)."""

        def resolve_fn_expr(expr: ast.AST, site: ast.AST) -> Optional[ast.AST]:
            if isinstance(expr, ast.Name):
                target = fns_by_name.get(expr.id)
                if target is not None:
                    return target
                return am.model._find_def(expr.id, site)
            if (
                isinstance(expr, ast.Attribute)
                and isinstance(expr.value, ast.Name)
                and expr.value.id == "self"
            ):
                cls = enclosing_class(site)
                if cls is not None:
                    for n in ast.walk(cls):
                        if (
                            isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
                            and n.name == expr.attr
                            and enclosing_class(n) is cls
                        ):
                            return n
            if isinstance(expr, ast.Attribute):
                candidates = method_index.get(expr.attr, [])
                if len(candidates) == 1:
                    return candidates[0]
            return None

        for node in ast.walk(am.model.tree):
            if not isinstance(node, ast.Call):
                continue
            resolved = am.model.resolve(node.func) or ""
            fn_expr = None
            why = ""
            if resolved == "threading.Thread":
                kw = {k.arg: k.value for k in node.keywords if k.arg}
                fn_expr = kw.get("target")
                why = "a Thread target"
            elif resolved == "asyncio.to_thread" and node.args:
                fn_expr = node.args[0]
                why = "a to_thread worker"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "run_in_executor"
                and len(node.args) >= 2
            ):
                fn_expr = node.args[1]
                why = "an executor worker"
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "add_done_callback"
                and node.args
            ):
                fn_expr = node.args[0]
                why = "a done-callback (completion thread)"
            if fn_expr is None:
                continue
            target = resolve_fn_expr(fn_expr, node)
            if target is not None and not isinstance(target, ast.AsyncFunctionDef):
                yield target, why

    # -- rule feedstock ---------------------------------------------------

    def blocking_call_findings(self) -> Iterator[Tuple[str, ast.Call, str]]:
        """R201 feedstock: ``(path, call, message)`` for every call made
        directly on the loop inside a coroutine that blocks (taxonomy)
        or may block (fixpoint), with executor/await/scheduling-wrapper
        exemptions applied."""
        for am in self.ams:
            for coro in am.coroutines:
                task_refs = am.task_refs(coro)
                for node in ast.walk(coro):
                    if not isinstance(node, ast.Call):
                        continue
                    if am.enclosing_function(node) is not coro:
                        continue
                    if am.is_awaited(node) or am.in_async_wrapper_arg(node):
                        continue
                    reason = am.blocking_reason(node)
                    if reason is not None:
                        # .result() on a retained asyncio task is a
                        # post-await read, not a blocking join.
                        if (
                            isinstance(node.func, ast.Attribute)
                            and node.func.attr == "result"
                            and ref_key(node.func.value) in task_refs
                        ):
                            reason = None
                    if reason is None:
                        reason = self._transitive_reason(node)
                    if reason is None:
                        continue
                    yield am.model.path, node, (
                        f"blocking call in coroutine '{coro.name}': {reason} "
                        "— wrap it in run_in_executor/to_thread"
                    )

    def _transitive_reason(self, call: ast.Call) -> Optional[str]:
        fn = None
        for anc in ancestors(call):
            if isinstance(anc, _FUNCTION_NODES):
                fn = anc
                break
        for c, target in self.fn_calls.get(fn, ()):
            if c is call and target in self.may_block:
                info = self.may_block[target]
                hop = f" via {info.via}" if info.via else ""
                return (
                    f"{self.fn_name[target]}() may block{hop} ({info.reason})"
                )
        return None

    def off_loop_findings(self) -> Iterator[Tuple[str, ast.AST, str]]:
        """R203 feedstock: loop-only operations performed by functions in
        the off-loop closure without ``call_soon_threadsafe``."""
        for am in self.ams:
            for fn, why in self.off_loop.items():
                if self.am_of_fn.get(fn) is not am:
                    continue
                future_refs = am.loop_future_refs(fn)
                for node in ast.walk(fn):
                    if not isinstance(node, ast.Call):
                        continue
                    if am.enclosing_function(node) is not fn:
                        continue
                    f = node.func
                    if not isinstance(f, ast.Attribute):
                        continue
                    if f.attr in LOOP_ONLY_METHODS and am.looks_like_loop(f.value):
                        yield am.model.path, node, (
                            f"'{self.fn_name[fn]}' runs off the event loop "
                            f"({why}) but calls loop.{f.attr}() — only "
                            "call_soon_threadsafe() is thread-safe"
                        )
                    elif (
                        f.attr in {"set_result", "set_exception"}
                        and ref_key(f.value) in future_refs
                    ):
                        yield am.model.path, node, (
                            f"'{self.fn_name[fn]}' runs off the event loop "
                            f"({why}) but calls {f.attr}() on a loop future "
                            "— marshal through call_soon_threadsafe()"
                        )
