"""jaxlint CLI (``tools/jaxlint.py`` wrapper / ``jaxlint`` console entry).

Exit codes follow linter convention: 0 clean (suppressed findings are
clean), 1 unsuppressed findings, 2 usage or parse error. ``--json``
emits the machine rendering on stdout for CI consumption.
"""

from __future__ import annotations

import argparse
import sys
from typing import Optional

from waternet_tpu.analysis import (
    build_lock_graph,
    lint_models,
    parse_model,
)
from waternet_tpu.analysis.core import collect_py_files
from waternet_tpu.analysis.registry import RULES
from waternet_tpu.analysis.report import render_json, render_text


def parse_args(argv=None):
    p = argparse.ArgumentParser(
        prog="jaxlint",
        description=(
            "Static analysis for JAX-specific hazards (buffer donation, "
            "PRNG key reuse, host syncs in hot loops, recompile hazards, "
            "tracer leaks) and concurrency hazards (guarded-by "
            "discipline, lock-order cycles, blocking under locks) — "
            "docs/LINT.md."
        ),
    )
    p.add_argument(
        "paths",
        nargs="*",
        help="Python files and/or directories (searched recursively)",
    )
    p.add_argument(
        "--json", action="store_true", help="machine-readable output"
    )
    p.add_argument(
        "--rules",
        type=str,
        default=None,
        metavar="R001,R003",
        help="run only these rules (default: all registered rules)",
    )
    p.add_argument(
        "--show-suppressed",
        action="store_true",
        help="also print suppressed findings in the text rendering",
    )
    p.add_argument(
        "--list-rules", action="store_true", help="print the rule catalogue"
    )
    p.add_argument(
        "--lock-graph",
        action="store_true",
        help="emit the static lock-acquisition graph over the given "
        "paths as DOT (nodes = locks by declaration site, edges = "
        "acquired-while-holding; R102 flags its cycles)",
    )
    return p.parse_args(argv)


def main(argv: Optional[list] = None) -> int:
    args = parse_args(argv)
    if args.list_rules:
        for rid, rule in sorted(RULES.items()):
            print(f"{rid}  {rule.name}: {rule.description}")
        return 0
    if not args.paths:
        print("jaxlint: no paths given (see --help)", file=sys.stderr)
        return 2
    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        unknown = [r for r in rules if r not in RULES]
        if unknown:
            print(
                f"jaxlint: unknown rule(s): {', '.join(unknown)} "
                f"(known: {', '.join(sorted(RULES))})",
                file=sys.stderr,
            )
            return 2
    try:
        files = collect_py_files(args.paths)
    except FileNotFoundError as err:
        print(str(err), file=sys.stderr)
        return 2
    models = []
    for f in files:
        try:
            models.append(parse_model(f))
        except SyntaxError as err:
            print(f"jaxlint: cannot parse {f}: {err}", file=sys.stderr)
            return 2
    if args.lock_graph:
        print(build_lock_graph(models).to_dot())
        return 0
    findings = lint_models(models, rules)
    if args.json:
        print(render_json(findings, len(files)))
    else:
        print(render_text(findings, len(files), args.show_suppressed))
    return 1 if any(not f.suppressed for f in findings) else 0


if __name__ == "__main__":
    sys.exit(main())
