"""locktrace: dynamic lock-order watchdog (runtime companion of R102).

The static side (:mod:`waternet_tpu.analysis.rules.concurrency`, rule
R102) proves the *declared* lock-acquisition graph acyclic from source.
This module watches the graph that actually happens: a
:class:`LockTracer` monkeypatches the ``threading.Lock`` /
``threading.RLock`` factories so every lock created while it is
installed is wrapped in a :class:`TracedLock` that records, per thread,
the stack of locks currently held.  Whenever a thread acquires lock B
while holding lock A, the tracer records an ordered edge ``A -> B``
keyed by each lock's *creation site* (``file:line`` of the ``Lock()``
call) together with the acquiring thread's stack — the first time only,
so the hot path stays a dict lookup.  At teardown
:meth:`LockTracer.assert_acyclic` fails the test if the observed edges
contain a cycle, printing both directions' acquisition stacks.

This mirrors the ``CompileSentinel`` mold from docs/LINT.md: the static
rule catches hazards visible in the source, the fixture catches the ones
that are not — lock orders induced through callbacks, executor threads,
or data-dependent branches that static call-graph propagation cannot
see.  Usage (see tests/conftest.py for the ``locktrace`` fixture)::

    tracer = LockTracer()
    tracer.install()
    try:
        ...  # exercise the threaded code
    finally:
        tracer.uninstall()
    tracer.assert_acyclic()

Design notes:

* Lock identity is the **creation site**, not the instance: a pool that
  builds one ``threading.Lock()`` per replica on the same line is one
  node, matching R102's declaration-site :class:`LockKey` semantics (and
  keeping the graph finite under churn).  Reentrant re-acquisition of
  the same site never records an edge.
* ``threading.Condition`` built with a default lock goes through the
  patched ``RLock`` factory, so condition-protected state is traced too.
  :class:`TracedLock` delegates ``_is_owned`` / ``_release_save`` /
  ``_acquire_restore`` to the wrapped lock via ``__getattr__`` — the
  exact attributes ``Condition`` probes with ``hasattr`` — so a traced
  RLock stays a valid Condition substrate.  ``Condition.wait`` releases
  and reacquires through those *delegated* methods, bypassing the
  tracer: the lock is treated as held across the wait, which is the
  conservative (and for ordering purposes, correct) reading.
* Locks created *before* ``install()`` (module-level locks, pytest
  internals) are untraced; the fixture window means tests trace exactly
  the objects they construct.
* ``acquire(blocking=False)`` that fails records nothing — only an
  acquisition that actually succeeded can contribute to a deadlock
  order.
"""

from __future__ import annotations

import sys
import threading
import traceback
from typing import Dict, List, Optional, Tuple

__all__ = ["LockTracer", "TracedLock"]

# The tracer's own guts must never run through the tracing machinery.
_REAL_LOCK = threading.Lock
_REAL_RLOCK = threading.RLock


def _creation_site(depth: int = 2) -> str:
    """``file:line`` of the frame ``depth`` levels up (the ``Lock()`` call)."""
    f = sys._getframe(depth)
    return f"{f.f_code.co_filename}:{f.f_lineno}"


class TracedLock:
    """Wrap a real lock; report successful acquires/releases to a tracer."""

    def __init__(self, inner, site: str, tracer: "LockTracer"):
        self._inner = inner
        self._site = site
        self._tracer = tracer

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._inner.acquire(blocking, timeout)
        if got:
            self._tracer._on_acquire(self)
        return got

    def release(self) -> None:
        self._tracer._on_release(self)
        self._inner.release()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    def locked(self) -> bool:
        return self._inner.locked()

    def __getattr__(self, name: str):
        # Condition protocol (_is_owned/_release_save/_acquire_restore)
        # and anything else version-specific: present exactly when the
        # wrapped lock has it, so hasattr probes behave identically.
        return getattr(self._inner, name)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"<TracedLock site={self._site} {self._inner!r}>"


class LockTracer:
    """Record per-thread lock-acquisition order; fail on observed cycles."""

    #: frames kept per recorded edge stack (enough to find the caller,
    #: small enough that hammer tests don't balloon).
    STACK_LIMIT = 12

    def __init__(self):
        self._tls = threading.local()
        self._guts = _REAL_LOCK()  # protects edges/sites; never traced
        # (site_a, site_b) -> (thread name, formatted acquisition stack)
        self.edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        #: creation sites seen, in creation order (graph nodes)
        self.sites: List[str] = []
        self._installed = False

    # -- factory patching -------------------------------------------------

    def install(self) -> None:
        if self._installed:
            return
        tracer = self

        def make_lock():
            return TracedLock(_REAL_LOCK(), _creation_site(), tracer)

        def make_rlock():
            return TracedLock(_REAL_RLOCK(), _creation_site(), tracer)

        threading.Lock = make_lock
        threading.RLock = make_rlock
        self._installed = True

    def uninstall(self) -> None:
        if not self._installed:
            return
        threading.Lock = _REAL_LOCK
        threading.RLock = _REAL_RLOCK
        self._installed = False

    # -- hot path ----------------------------------------------------------

    def _held(self) -> List[TracedLock]:
        held = getattr(self._tls, "held", None)
        if held is None:
            held = self._tls.held = []
        return held

    def _on_acquire(self, lock: TracedLock) -> None:
        held = self._held()
        site = lock._site
        for prev in held:
            if prev._site == site:  # reentrant RLock: not an ordering edge
                continue
            key = (prev._site, site)
            if key not in self.edges:  # stack capture only for new edges
                stack = "".join(
                    traceback.format_stack(
                        sys._getframe(2), limit=self.STACK_LIMIT
                    )
                )
                with self._guts:
                    self.edges.setdefault(
                        key, (threading.current_thread().name, stack)
                    )
        if site not in self.sites:
            with self._guts:
                if site not in self.sites:
                    self.sites.append(site)
        held.append(lock)

    def _on_release(self, lock: TracedLock) -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):  # pop last occurrence:
            if held[i] is lock:  # non-LIFO release is legal
                del held[i]
                return

    # -- teardown analysis -------------------------------------------------

    def cycle(self) -> Optional[List[str]]:
        """A list of sites forming an observed cycle, or ``None``."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
        WHITE, GREY, BLACK = 0, 1, 2
        color = {s: WHITE for s in adj}
        for root in adj:
            if color.get(root, WHITE) != WHITE:
                continue
            stack = [(root, iter(adj.get(root, ())))]
            color[root] = GREY
            path = [root]
            while stack:
                node, it = stack[-1]
                advanced = False
                for nxt in it:
                    c = color.get(nxt, WHITE)
                    if c == GREY:
                        return path[path.index(nxt):] + [nxt]
                    if c == WHITE:
                        color[nxt] = GREY
                        path.append(nxt)
                        stack.append((nxt, iter(adj.get(nxt, ()))))
                        advanced = True
                        break
                if not advanced:
                    color[node] = BLACK
                    path.pop()
                    stack.pop()
        return None

    def assert_acyclic(self) -> None:
        cyc = self.cycle()
        if cyc is None:
            return
        lines = ["locktrace: observed lock-order cycle (deadlock hazard):"]
        lines.append("  " + " -> ".join(cyc))
        for a, b in zip(cyc, cyc[1:]):
            thread, stack = self.edges[(a, b)]
            lines.append(f"edge {a} -> {b} first seen on thread {thread!r}:")
            lines.append(stack.rstrip())
        lines.append(
            "Two threads taking these locks in opposite orders can "
            "deadlock; impose one global order (jaxlint R102 checks the "
            "declared order statically — see docs/LINT.md)."
        )
        raise AssertionError("\n".join(lines))
