"""R002 — rng-key-reuse.

JAX PRNG keys are values, not stateful generators: passing the same key
to two sampling sites yields *identical* (or correlated) draws, silently.
The repo's determinism guarantees (byte-identical resume and pipelined
epochs in tests/test_pipeline.py / test_resilience.py) depend on every
key being consumed exactly once between ``split`` / ``fold_in`` points.

The rule tracks, per function scope, variables that definitely hold keys:

* assigned from ``jax.random.PRNGKey`` / ``key`` / ``split`` /
  ``fold_in`` (tuple-unpacking from ``split`` included);
* parameters that the body passes as the first argument of some
  ``jax.random.*`` call (so a numpy ``Generator`` named ``rng`` is never
  mistaken for a key).

A *consumption* is the key appearing as a call argument — any
``jax.random`` sampler (``split`` included: splitting and then reusing
the original key is the classic bug) or any unknown function (passing
one key to two helpers is reuse too). ``fold_in`` is non-consuming by
design: deriving many streams from one base via ``fold_in(base, i)`` is
the intended idiom (the trainer's per-(epoch, batch) keys). Two
consumptions fire only when both can execute in one pass — sibling
``if``/``else`` arms don't conflict — and a consumption inside a loop
whose key was bound outside the loop (and never re-split inside) fires
on its own.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from waternet_tpu.analysis.core import (
    Finding,
    ModuleModel,
    SCOPE_NODES,
    enclosing_scope,
)
from waternet_tpu.analysis.registry import Rule, register

_KEY_SOURCES = {
    "jax.random.PRNGKey",
    "jax.random.key",
    "jax.random.split",
    "jax.random.fold_in",
}
_NON_CONSUMING = {
    "jax.random.fold_in",
    "jax.random.key_data",
    "jax.random.wrap_key_data",
    "print",
    "repr",
    "str",
    "len",
    "id",
    "type",
    "isinstance",
    "copy.copy",
    "copy.deepcopy",
    "jax.debug.print",
    "jax.device_get",
    "jax.block_until_ready",
}


def _is_key_source(model: ModuleModel, value: ast.AST) -> bool:
    return (
        isinstance(value, ast.Call)
        and model.resolve(value.func) in _KEY_SOURCES
    )


class _Event:
    __slots__ = ("kind", "name", "node", "branch", "loops")

    def __init__(self, kind, name, node, branch, loops):
        self.kind = kind  # "bind" | "consume"
        self.name = name
        self.node = node
        self.branch = branch  # tuple of (if-node-id, arm)
        self.loops = loops  # tuple of loop-node ids, outermost first


def _branches_compatible(a, b) -> bool:
    """False when the two branch paths take different arms of the same
    ``if`` — then the two sites cannot both execute in one pass."""
    arms = dict(a)
    return all(arms.get(nid, arm) == arm for nid, arm in b)


def _collect_events(model, fn, keys) -> list:
    """Lexically-ordered bind/consume events for the tracked key names,
    not descending into nested function scopes."""
    events: list = []

    def arg_names(call: ast.Call):
        for a in list(call.args) + [k.value for k in call.keywords]:
            inner = a.value if isinstance(a, ast.Starred) else a
            if isinstance(inner, ast.Name) and inner.id in keys:
                yield inner

    def visit(node, branch, loops):
        if isinstance(node, SCOPE_NODES) and node is not fn:
            return  # nested scope: its own analysis
        if isinstance(node, ast.Call):
            fname = model.resolve(node.func)
            consuming = fname not in _NON_CONSUMING
            for name_node in arg_names(node):
                if consuming:
                    events.append(
                        _Event("consume", name_node.id, node, branch, loops)
                    )
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            if node.id in keys:
                events.append(_Event("bind", node.id, node, branch, loops))
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            # Value before targets: in `key, sub = split(key)` the OLD
            # key is consumed before the NEW binding exists — visiting in
            # AST field order (targets first) would leave the stale
            # consume attached to the fresh binding and falsely flag the
            # carried-key idiom as reuse.
            if node.value is not None:
                visit(node.value, branch, loops)
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                visit(t, branch, loops)
            return
        if isinstance(node, ast.If):
            visit(node.test, branch, loops)
            for stmt in node.body:
                visit(stmt, branch + ((id(node), "then"),), loops)
            for stmt in node.orelse:
                visit(stmt, branch + ((id(node), "else"),), loops)
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            new_loops = loops + (id(node),)
            for child in ast.iter_child_nodes(node):
                if child in node.body or child in node.orelse:
                    visit(child, branch, new_loops)
                else:
                    visit(child, branch, loops)
            return
        for child in ast.iter_child_nodes(node):
            visit(child, branch, loops)

    body = fn.body if not isinstance(fn, ast.Lambda) else [fn.body]
    for stmt in body:
        visit(stmt, (), ())
    return events


@register
class RngKeyReuse(Rule):
    id = "R002"
    name = "rng-key-reuse"
    description = (
        "a PRNG key is consumed by two sites without an intervening "
        "split/fold_in, or consumed inside a loop without per-iteration "
        "derivation"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        for fn in ast.walk(model.tree):
            if not isinstance(fn, SCOPE_NODES) or isinstance(fn, ast.Module):
                continue
            keys = self._key_names(model, fn)
            if not keys:
                continue
            yield from self._analyze(model, fn, keys)

    def _key_names(self, model, fn) -> set:
        keys = set()
        for node in ast.walk(fn):
            if enclosing_scope(node) is not fn:
                continue
            if isinstance(node, ast.Assign) and _is_key_source(model, node.value):
                for t in node.targets:
                    elts = t.elts if isinstance(t, (ast.Tuple, ast.List)) else [t]
                    for e in elts:
                        if isinstance(e, ast.Name):
                            keys.add(e.id)
        if not isinstance(fn, ast.Lambda):
            params = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        else:
            params = {a.arg for a in fn.args.args}
        # A parameter counts as a key only when the body demonstrably
        # treats it as one (first argument of a jax.random.* call).
        for node in ast.walk(fn):
            if (
                isinstance(node, ast.Call)
                and (model.resolve(node.func) or "").startswith("jax.random.")
                and node.args
                and isinstance(node.args[0], ast.Name)
                and node.args[0].id in params
            ):
                keys.add(node.args[0].id)
        return keys

    def _analyze(self, model, fn, keys) -> Iterator[Finding]:
        events = _collect_events(model, fn, keys)
        last_bind: dict = {}
        last_consume: dict = {}
        binds_in_loop: dict = {}
        for ev in events:
            if ev.kind == "bind":
                for lid in ev.loops:
                    binds_in_loop.setdefault(ev.name, set()).add(lid)
        # Parameters bind at function entry (outside every loop).
        for ev in events:
            if ev.kind == "bind":
                last_bind[ev.name] = ev
                last_consume.pop(ev.name, None)
                continue
            prev = last_consume.get(ev.name)
            if prev is not None and _branches_compatible(prev.branch, ev.branch):
                yield self.finding(
                    model,
                    ev.node,
                    f"PRNG key `{ev.name}` is consumed again here (already "
                    f"consumed at line {prev.node.lineno}) without an "
                    "intervening split/fold_in — both sites draw from the "
                    "same stream",
                )
                continue  # don't cascade one reuse into N findings
            bound = last_bind.get(ev.name)
            bound_loops = set(bound.loops) if bound is not None else set()
            rebinds = binds_in_loop.get(ev.name, set())
            for lid in ev.loops:
                if lid not in bound_loops and lid not in rebinds:
                    yield self.finding(
                        model,
                        ev.node,
                        f"PRNG key `{ev.name}` is consumed inside a loop "
                        "but bound outside it and never re-derived per "
                        "iteration — every iteration draws identical "
                        "values; derive with jax.random.fold_in(key, i) "
                        "or split per step",
                    )
                    break
            last_consume[ev.name] = ev
