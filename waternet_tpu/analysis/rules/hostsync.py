"""R003 — host-sync-in-hot-loop.

The engine's throughput rests on the deferred-metrics-fetch discipline:
a loop that dispatches jitted device work must not also force a
device->host sync (``.item()``, ``float()``, ``np.asarray``,
``block_until_ready``, ``jax.device_get``) — each sync drains the device
queue and serializes host and device, exactly the reference trainer's 8+
syncs/step pathology the engine was built to remove. The sanctioned
pattern (collect device metric dicts, fetch once after the loop) is what
``TrainingEngine._drive_train_epoch`` does; until this rule it was
convention only.

The rule fires on a sync call inside a ``for``/``while`` body that also
calls a statically-known jit-compiled callable (the module/class jit
registry — ``self.train_step``, a ``@jax.jit`` nested def, ...). Loops
that only *fetch* (the epoch-end ``for m in pending: float(...)`` loop)
dispatch nothing and stay clean by construction, which is precisely the
discipline the rule encodes.
"""

from __future__ import annotations

import ast
from typing import Iterator

from waternet_tpu.analysis.core import (
    Finding,
    LOOP_NODES,
    ModuleModel,
    SCOPE_NODES,
    enclosing_scope,
    flatten_targets,
)
from waternet_tpu.analysis.registry import Rule, register

_SYNC_CALLS = {
    "jax.device_get": "jax.device_get() forces a device->host transfer",
    "jax.block_until_ready": "jax.block_until_ready() drains the device queue",
    "numpy.asarray": "np.asarray() on a device value copies it to host synchronously",
    "numpy.array": "np.array() on a device value copies it to host synchronously",
}
_SYNC_METHODS = {
    "item": ".item() blocks on the device value",
    "tolist": ".tolist() blocks on the device value",
    "block_until_ready": ".block_until_ready() drains the device queue",
}
_SYNC_BUILTINS = {"float", "int", "bool"}


def _iter_loop_body(loop) -> Iterator[ast.AST]:
    """All nodes in a loop's body/orelse, not descending into nested
    function definitions (defining a closure executes nothing)."""
    stack = list(loop.body) + list(loop.orelse)
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, SCOPE_NODES):
            continue
        stack.extend(ast.iter_child_nodes(node))


def _root_name(node: ast.AST):
    """The base Name of a Name/Attribute/Subscript chain, else None."""
    while isinstance(node, (ast.Attribute, ast.Subscript)):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


def _device_names(model: ModuleModel, scope) -> set:
    """Names in ``scope`` bound (possibly via tuple unpack) from a call to
    a statically-known jitted callable — i.e. names that definitely hold
    device values. Gates the builtin-cast check: ``float(i)`` on a loop
    counter is a plain host cast, ``float(m["loss"])`` on a step result
    is a sync."""
    names: set = set()
    for node in ast.walk(scope):
        if not isinstance(node, ast.Assign):
            continue
        calls = [node.value] if isinstance(node.value, ast.Call) else [
            e for e in getattr(node.value, "elts", []) if isinstance(e, ast.Call)
        ]
        if not any(model.jit_info_for_call(c) is not None for c in calls):
            continue
        for t in node.targets:
            for leaf in flatten_targets(t):
                if isinstance(leaf, ast.Name):
                    names.add(leaf.id)
    return names


def _sync_reason(model: ModuleModel, node: ast.AST, device_names: set):
    if not isinstance(node, ast.Call):
        return None
    fname = model.resolve(node.func)
    if fname in _SYNC_CALLS:
        return _SYNC_CALLS[fname]
    if fname in _SYNC_BUILTINS and "." not in fname:
        if (
            len(node.args) == 1
            and not isinstance(node.args[0], ast.Constant)
            and _root_name(node.args[0]) in device_names
        ):
            return (
                f"{fname}() on a device value blocks until the value is "
                "computed and transferred"
            )
        return None
    if isinstance(node.func, ast.Attribute) and node.func.attr in _SYNC_METHODS:
        if not node.args and not node.keywords:
            return _SYNC_METHODS[node.func.attr]
    return None


@register
class HostSyncInHotLoop(Rule):
    id = "R003"
    name = "host-sync-in-hot-loop"
    description = (
        "a loop that dispatches jitted device work also forces a "
        "device->host sync, serializing host and device per iteration"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        if not model.jit_bindings:
            return
        reported: set = set()
        device_cache: dict = {}
        for loop in ast.walk(model.tree):
            if not isinstance(loop, LOOP_NODES):
                continue
            dispatch = None
            for node in _iter_loop_body(loop):
                if isinstance(node, ast.Call):
                    info = model.jit_info_for_call(node)
                    if info is not None:
                        dispatch = info.binding or "a jitted callable"
                        break
            if dispatch is None:
                continue
            scope = enclosing_scope(loop) or model.tree
            if scope not in device_cache:
                device_cache[scope] = _device_names(model, scope)
            for node in _iter_loop_body(loop):
                reason = _sync_reason(model, node, device_cache[scope])
                if reason is None or id(node) in reported:
                    continue
                reported.add(id(node))
                yield self.finding(
                    model,
                    node,
                    f"host sync inside the hot loop at line {loop.lineno} "
                    f"(which dispatches `{dispatch}`): {reason}. Defer the "
                    "fetch past the loop (collect device values, read them "
                    "once per epoch) to keep the device queue full",
                )
