"""R101–R105 — the threadlint concurrency rule family (docs/LINT.md).

Five rules over the :mod:`waternet_tpu.analysis.concurrency` model:

* **R101 unguarded-shared-mutation** — a write to an attribute declared
  ``# guarded-by: <lock>`` outside a ``with`` on that lock, or an
  undeclared read-modify-write / container mutation of shared state in a
  thread-bearing class with no lock held.
* **R102 lock-order-inversion** — a cycle in the whole-repo static
  lock-acquisition graph (project-scope: it sees every scanned module).
* **R103 blocking-call-under-lock** — ``Future.result()``,
  ``Thread.join()``, ``queue.get()``, host syncs, and ``sleep`` inside a
  held lock: every contending thread stalls for the blocked one.
* **R104 condition-wait-without-predicate** — ``Condition.wait()`` whose
  predicate is not re-checked in a ``while`` loop (spurious/missed
  wakeups are part of the condition contract).
* **R105 unjoined-thread** — a non-daemon ``Thread`` started with no
  ``join``, later ``daemon`` set, or leak-guard registration in sight.

Same precision-first stance as R001–R005: unresolvable receivers are
skipped, not guessed, because tier-1 pins the tree at zero unsuppressed
findings and a noisy rule would be suppressed into uselessness.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional, Set

from waternet_tpu.analysis.concurrency import (
    ConcurrencyModel,
    LockKey,
    _MUTATOR_METHODS,
    build_lock_graph,
)
from waternet_tpu.analysis.core import (
    Finding,
    ModuleModel,
    ancestors,
    enclosing_class,
    flatten_targets,
    ref_key,
)
from waternet_tpu.analysis.registry import Rule, register


def _nearest_function(node: ast.AST):
    for anc in ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)):
            return anc
    return None


def _in_init_of(node: ast.AST, cls: ast.ClassDef) -> bool:
    """True when the nearest enclosing function is ``cls.__init__`` —
    construction happens-before any thread the object spawns, so
    declaring writes there are exempt."""
    fn = _nearest_function(node)
    return (
        isinstance(fn, ast.FunctionDef)
        and fn.name == "__init__"
        and enclosing_class(fn) is cls
    )


def _self_attr_base(node: ast.AST) -> Optional[str]:
    """``self.X`` -> ``X`` (exactly one attribute deep), else None."""
    key = ref_key(node)
    return key[1] if key is not None and key[0] == "self" else None


@register
class UnguardedSharedMutation(Rule):
    id = "R101"
    name = "unguarded-shared-mutation"
    description = (
        "a `# guarded-by:` declared attribute is written outside its "
        "lock, or shared mutable state in a thread-bearing class is "
        "mutated with no lock held and no declaration"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        cm = ConcurrencyModel(model)
        yield from self._check_classes(model, cm)
        yield from self._check_module_globals(model, cm)

    # -- class attributes -----------------------------------------------

    def _mutations(self, cm: ConcurrencyModel):
        """Yield ``(node, attr, how)`` for every self-attribute mutation:
        how in {"write", "augmented write", "item write", "mutating
        call"}."""
        for node in ast.walk(cm.model.tree):
            if isinstance(node, (ast.Assign, ast.AnnAssign)):
                targets = (
                    node.targets if isinstance(node, ast.Assign)
                    else [node.target]
                )
                for t in targets:
                    for leaf in flatten_targets(t):
                        attr = _self_attr_base(leaf)
                        if attr is not None:
                            yield node, attr, "write"
                        elif isinstance(leaf, ast.Subscript):
                            attr = _self_attr_base(leaf.value)
                            if attr is not None:
                                yield node, attr, "item write"
            elif isinstance(node, ast.AugAssign):
                attr = _self_attr_base(node.target)
                if attr is not None:
                    yield node, attr, "augmented write"
                elif isinstance(node.target, ast.Subscript):
                    attr = _self_attr_base(node.target.value)
                    if attr is not None:
                        yield node, attr, "item write"
            elif isinstance(node, ast.Delete):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        attr = _self_attr_base(t.value)
                        if attr is not None:
                            yield node, attr, "item write"
            elif (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _MUTATOR_METHODS
            ):
                attr = _self_attr_base(node.func.value)
                if attr is not None:
                    yield node, attr, "mutating call"

    def _check_classes(
        self, model: ModuleModel, cm: ConcurrencyModel
    ) -> Iterator[Finding]:
        for node, attr, how in self._mutations(cm):
            cls = enclosing_class(node)
            info = cm.classes.get(cls) if cls is not None else None
            if info is None or _in_init_of(node, cls):
                continue
            held = cm.held_locks(node)
            if attr in info.guarded:
                want = info.guarded[attr]
                if want not in held:
                    yield self.finding(
                        model,
                        node,
                        f"self.{attr} is declared `# guarded-by: "
                        f"{info.guard_text[attr]}` but this {how} does not "
                        f"hold {want.display}; wrap it in `with "
                        f"{info.guard_text[attr]}:` (or mark the enclosing "
                        f"def `# guarded-by: {info.guard_text[attr]}` if "
                        "callers hold it)",
                    )
                continue
            if not info.thread_bearing or attr in info.locks:
                continue
            # Undeclared shared mutation: read-modify-writes always count;
            # item writes / mutating calls only on known mutable containers
            # (a queue.Queue attr locks internally and stays exempt).
            if how == "augmented write" or (
                how in ("item write", "mutating call")
                and attr in info.mutable_attrs
            ):
                if not held:
                    yield self.finding(
                        model,
                        node,
                        f"unguarded {how} of shared self.{attr}: class "
                        f"{info.name} runs threads ({info.spawn_reason}) "
                        "and no lock is held here; guard the mutation and "
                        "declare the attribute `# guarded-by: <lock>` "
                        "(docs/LINT.md 'Concurrency rules')",
                    )

    # -- module-level globals --------------------------------------------

    def _check_module_globals(
        self, model: ModuleModel, cm: ConcurrencyModel
    ) -> Iterator[Finding]:
        if not cm.module_guarded:
            return
        for fn in ast.walk(model.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            declared_global: Set[str] = set()
            for stmt in ast.walk(fn):
                if isinstance(stmt, ast.Global):
                    declared_global.update(stmt.names)
            watched = declared_global & set(cm.module_guarded)
            if not watched:
                continue
            for node in ast.walk(fn):
                if _nearest_function(node) is not fn:
                    continue
                names = []
                if isinstance(node, ast.Assign):
                    names = [
                        leaf.id
                        for t in node.targets
                        for leaf in flatten_targets(t)
                        if isinstance(leaf, ast.Name)
                    ]
                elif isinstance(node, ast.AugAssign) and isinstance(
                    node.target, ast.Name
                ):
                    names = [node.target.id]
                for name in names:
                    if name not in watched:
                        continue
                    want = cm.module_guarded[name]
                    if want not in cm.held_locks(node):
                        yield self.finding(
                            model,
                            node,
                            f"global {name} is declared `# guarded-by: "
                            f"{cm.module_guard_text[name]}` but this write "
                            f"does not hold {want.display}",
                        )


@register
class LockOrderInversion(Rule):
    id = "R102"
    name = "lock-order-inversion"
    description = (
        "the static lock-acquisition graph (nested with/acquire sites "
        "plus calls made under a lock) contains a cycle: two threads "
        "taking the locks in opposite order can deadlock"
    )
    scope = "project"

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        yield from self.check_project([model])

    def check_project(self, models) -> Iterator[Finding]:
        graph = build_lock_graph(models)
        for cycle in graph.cycles():
            ring = cycle + [cycle[0]]
            hops = []
            first_site = None
            for a, b in zip(ring, ring[1:]):
                path, line = graph.sites.get((a, b), (cycle[0].path, 0))
                hops.append(f"{a.display} -> {b.display} at {path}:{line}")
                if first_site is None:
                    first_site = (path, line)
            yield Finding(
                rule=self.id,
                path=first_site[0],
                line=first_site[1],
                col=0,
                message=(
                    "lock-order inversion: "
                    + "; ".join(hops)
                    + " — impose one global order (or drop to a single "
                    "lock) so no two threads can hold these in opposite "
                    "order"
                ),
            )


#: Blocking attribute calls and the exemption shapes that keep dict.get /
#: str.join quiet: see _blocking_reason.
_BLOCKING_RESOLVED = {
    "time.sleep": "time.sleep() parks the thread",
    "jax.device_get": "jax.device_get() forces a device->host transfer",
    "jax.block_until_ready": "jax.block_until_ready() drains the device queue",
}


def _is_timeoutish(call: ast.Call) -> bool:
    """Zero positional args, or a single numeric constant, plus only
    block/timeout keywords — the Thread.join()/queue.get() shapes (and
    never str.join(iterable) / dict.get(key))."""
    if any(k.arg not in ("timeout", "block") for k in call.keywords):
        return False
    if not call.args:
        return True
    if len(call.args) == 1 and isinstance(call.args[0], ast.Constant):
        return isinstance(call.args[0].value, (int, float))
    return False


def _blocking_reason(cm: ConcurrencyModel, call: ast.Call) -> Optional[str]:
    resolved = cm.model.resolve(call.func)
    if resolved in _BLOCKING_RESOLVED:
        return _BLOCKING_RESOLVED[resolved]
    if not isinstance(call.func, ast.Attribute):
        return None
    attr = call.func.attr
    if attr == "result":
        if not call.args and all(k.arg == "timeout" for k in call.keywords):
            return "Future.result() blocks until the worker resolves it"
    elif attr == "join" and _is_timeoutish(call):
        return "Thread.join() blocks until the thread exits"
    elif attr == "get" and _is_timeoutish(call):
        for k in call.keywords:
            if (
                k.arg == "block"
                and isinstance(k.value, ast.Constant)
                and not k.value.value
            ):
                return None
        return "queue get() blocks until an item arrives"
    elif attr == "wait" and _is_timeoutish(call):
        return "wait() parks the thread until another thread signals"
    elif attr == "block_until_ready" and not call.args:
        return ".block_until_ready() drains the device queue"
    return None


@register
class BlockingCallUnderLock(Rule):
    id = "R103"
    name = "blocking-call-under-lock"
    description = (
        "a blocking call (Future.result, Thread.join, queue get, "
        "host sync, sleep, wait) runs while a lock is held, stalling "
        "every thread that contends for it"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        cm = ConcurrencyModel(model)
        for node in ast.walk(model.tree):
            if not isinstance(node, ast.Call):
                continue
            reason = _blocking_reason(cm, node)
            if reason is None:
                continue
            held = cm.held_locks(node)
            if not held:
                continue
            # Condition.wait under its own condition's `with` is THE
            # sanctioned pattern (wait releases the lock): exempt.
            if (
                isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"
            ):
                receiver = cm.lock_key_of_expr(node.func.value)
                if receiver is not None and receiver in held:
                    continue
            names = ", ".join(sorted(k.display for k in held))
            yield self.finding(
                model,
                node,
                f"blocking call while holding {names}: {reason}. Move the "
                "blocking step outside the locked region (snapshot under "
                "the lock, block after releasing)",
            )


@register
class ConditionWaitWithoutPredicate(Rule):
    id = "R104"
    name = "condition-wait-without-predicate"
    description = (
        "Condition.wait() whose predicate is not re-checked in a while "
        "loop: spurious and missed wakeups are part of the condition "
        "contract, so an if (or no check) loses signals"
    )

    def _condition_receiver(
        self, cm: ConcurrencyModel, expr: ast.AST
    ) -> bool:
        """True when ``expr`` statically names a Condition: a class/module
        attr constructed via threading/asyncio.Condition, or a local
        assigned one in the same function."""
        cls = enclosing_class(expr)
        attr = _self_attr_base(expr)
        if attr is not None and cls is not None:
            info = cm.classes.get(cls)
            return info is not None and info.locks.get(attr) == "condition"
        if isinstance(expr, ast.Name):
            if cm.module_locks.get(expr.id) == "condition":
                return True
            fn = _nearest_function(expr)
            if fn is not None:
                for node in ast.walk(fn):
                    if (
                        isinstance(node, ast.Assign)
                        and cm._lock_kind(node.value) == "condition"
                        and any(
                            isinstance(leaf, ast.Name) and leaf.id == expr.id
                            for t in node.targets
                            for leaf in flatten_targets(t)
                        )
                    ):
                        return True
        return False

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        cm = ConcurrencyModel(model)
        for node in ast.walk(model.tree):
            if not (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "wait"
            ):
                continue
            if not self._condition_receiver(cm, node.func.value):
                continue
            in_while = False
            for anc in ancestors(node):
                if isinstance(anc, ast.While):
                    in_while = True
                    break
                if isinstance(
                    anc, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
                ):
                    break
            if not in_while:
                yield self.finding(
                    model,
                    node,
                    "Condition.wait() outside a while loop: re-check the "
                    "predicate in `while not <pred>: cond.wait()` (or use "
                    "cond.wait_for(pred)) — wakeups can be spurious and "
                    "signals sent before the wait are lost",
                )


@register
class UnjoinedThread(Rule):
    id = "R105"
    name = "unjoined-thread"
    description = (
        "a non-daemon Thread is started with no join, daemon flag, or "
        "leak-guard registration anywhere in the module: process exit "
        "hangs on it and tests leak it"
    )

    _REGISTER_CALLS = {"append", "extend", "add", "register"}

    def _daemon_kw(self, call: ast.Call) -> Optional[bool]:
        for k in call.keywords:
            if k.arg == "daemon" and isinstance(k.value, ast.Constant):
                return bool(k.value.value)
        return None

    def _handled_elsewhere(self, root: ast.AST, key) -> bool:
        """Is this thread ref joined, daemonized, or registered anywhere
        under ``root``? ``self.attr`` refs search the whole module
        (close() joining what __init__ spawned is the normal shape);
        local refs search only their own function."""
        for node in ast.walk(root):
            if isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if (
                    node.func.attr == "join"
                    and ref_key(node.func.value) == key
                ):
                    return True
                if node.func.attr == "setDaemon" and ref_key(
                    node.func.value
                ) == key:
                    return True
                if node.func.attr in self._REGISTER_CALLS and any(
                    ref_key(a) == key
                    or (
                        isinstance(a, (ast.List, ast.Tuple))
                        and any(ref_key(e) == key for e in a.elts)
                    )
                    for a in node.args
                ):
                    return True
            elif isinstance(node, ast.Assign):
                for t in node.targets:
                    if (
                        isinstance(t, ast.Attribute)
                        and t.attr == "daemon"
                        and ref_key(t.value) == key
                    ):
                        return True
        return False

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        for node in ast.walk(model.tree):
            if not (
                isinstance(node, ast.Call)
                and model.resolve(node.func) == "threading.Thread"
            ):
                continue
            daemon = self._daemon_kw(node)
            if daemon:
                continue
            parent = getattr(node, "_jl_parent", None)
            key = None
            if isinstance(parent, ast.Assign):
                for t in parent.targets:
                    key = ref_key(t)
                    if key is not None:
                        break
            if key is not None:
                root = model.tree
                if key[0] == "local":
                    root = _nearest_function(node) or model.tree
                if self._handled_elsewhere(root, key):
                    continue
            where = (
                "bound but never joined"
                if key is not None
                else "not bound to anything, so it can never be joined"
            )
            yield self.finding(
                model,
                node,
                f"non-daemon Thread {where}: join it on the shutdown "
                "path, register it with a leak guard, or mark it "
                "daemon=True if abandonment is really intended",
            )
