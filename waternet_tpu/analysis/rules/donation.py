"""R001 — donation-after-use.

``donate_argnums`` lets XLA write a step's outputs into its inputs'
buffers. Two ways that goes wrong, both of which this repo has met:

* the caller keeps using the donated reference after the call — the
  classic read-after-free, which jax only reports lazily (and only when
  the runtime notices);
* the donated argument merely *borrows* host memory: on CPU,
  ``jax.device_put`` zero-copies aligned numpy arrays, so donating such a
  buffer frees pages the host still owns — the PR-1
  ``TrainingEngine._own_device_state`` corruption class, observed as
  nondeterministic garbage in param leaves after a checkpoint restore.

The rule therefore checks every statically-resolvable call site of a
jit-with-donation callable (see ``ModuleModel.jit_bindings``):

1. a donated argument that is a plain name or ``self.attr`` must be
   rebound by the same statement (``state, m = step(state, ...)``) or
   never read again afterwards in the same function;
2. a donated argument must not be the direct result of
   ``jax.device_put(...)``;
3. within a class, a donated ``self.attr`` must not be assigned from a
   method that returns a bare ``jax.device_put`` result (no ``jnp.copy``
   ownership copy) — the cross-method form of (2).
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from waternet_tpu.analysis.core import (
    Finding,
    ModuleModel,
    enclosing_class,
    enclosing_scope,
    flatten_targets,
    ref_key,
    statement_of,
)
from waternet_tpu.analysis.registry import Rule, register

_COPY_NAMES = {
    "jax.numpy.copy",
    "jax.numpy.array",
    "numpy.array",
}
_TREE_MAP_NAMES = {"jax.tree.map", "jax.tree_util.tree_map", "jax.tree_map"}


def _returns_borrowed(model: ModuleModel, fn: ast.FunctionDef) -> bool:
    """True when some ``return`` of ``fn`` resolves (through simple local
    assignments) to a bare ``jax.device_put(...)`` call — i.e. the method
    hands out buffers that may alias host numpy memory."""
    env: dict = {}
    for node in ast.walk(fn):
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            t = node.targets[0]
            if isinstance(t, ast.Name) and enclosing_scope(node) is fn:
                env.setdefault(t.id, []).append(node)
    for ret in ast.walk(fn):
        if not isinstance(ret, ast.Return) or ret.value is None:
            continue
        if enclosing_scope(ret) is not fn:
            continue
        expr: Optional[ast.AST] = ret.value
        for _ in range(8):  # follow a short local assignment chain
            if isinstance(expr, ast.Name):
                assigns = [
                    a for a in env.get(expr.id, []) if a.lineno <= ret.lineno
                ]
                if not assigns:
                    break
                expr = assigns[-1].value
                continue
            break
        if isinstance(expr, ast.Call):
            name = model.resolve(expr.func)
            if name == "jax.device_put":
                return True
            if name in _COPY_NAMES:
                continue
            if name in _TREE_MAP_NAMES:
                continue
    return False


def _borrowed_attrs(model: ModuleModel, cls: ast.ClassDef) -> dict:
    """``{attr: description}`` for self attributes assigned from borrowed
    sources anywhere in the class."""
    borrowed_methods = {}
    for stmt in cls.body:
        if isinstance(stmt, ast.FunctionDef) and _returns_borrowed(model, stmt):
            borrowed_methods[stmt.name] = stmt
    out: dict = {}
    for node in ast.walk(cls):
        if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
            continue
        key = ref_key(node.targets[0])
        if key is None or key[0] != "self":
            continue
        value = node.value
        if isinstance(value, ast.Call):
            name = model.resolve(value.func)
            if name == "jax.device_put":
                out[key[1]] = "assigned directly from jax.device_put"
                continue
            f = value.func
            if (
                isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id == "self"
                and f.attr in borrowed_methods
            ):
                out[key[1]] = (
                    f"assigned from self.{f.attr}(), which returns a bare "
                    "jax.device_put result (no jnp.copy ownership copy)"
                )
    return out


def _display(node: ast.AST) -> str:
    try:
        return ast.unparse(node)
    except Exception:  # pragma: no cover - unparse is total on valid ASTs
        return "<expr>"


@register
class DonationAfterUse(Rule):
    id = "R001"
    name = "donation-after-use"
    description = (
        "an argument donated via donate_argnums is read after the jitted "
        "call, or aliases a host NumPy buffer (zero-copy device_put)"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        if not any(i.donate_argnums for i in model.jit_bindings.values()):
            return
        borrowed_cache: dict = {}
        for call in ast.walk(model.tree):
            if not isinstance(call, ast.Call):
                continue
            info = model.jit_info_for_call(call)
            if info is None or not info.donate_argnums:
                continue
            if any(isinstance(a, ast.Starred) for a in call.args):
                continue  # positions not statically known
            callee = info.binding or _display(call.func)
            for argnum in info.donate_argnums:
                if not isinstance(argnum, int) or argnum >= len(call.args):
                    continue
                arg = call.args[argnum]
                if (
                    isinstance(arg, ast.Call)
                    and model.resolve(arg.func) == "jax.device_put"
                ):
                    yield self.finding(
                        model,
                        arg,
                        f"argument {argnum} of `{callee}` is donated but is "
                        "a bare jax.device_put result — on CPU device_put "
                        "zero-copies host numpy buffers, so donation frees "
                        "memory the host still owns; materialize with "
                        "jnp.copy first",
                    )
                    continue
                key = ref_key(arg)
                if key is None:
                    continue
                if key[0] == "self":
                    cls = enclosing_class(call)
                    if cls is not None:
                        if cls not in borrowed_cache:
                            borrowed_cache[cls] = _borrowed_attrs(model, cls)
                        why = borrowed_cache[cls].get(key[1])
                        if why:
                            yield self.finding(
                                model,
                                arg,
                                f"`self.{key[1]}` is donated (argument "
                                f"{argnum} of `{callee}`) but may alias a "
                                f"host numpy buffer: {why}. Donating a "
                                "borrowed buffer frees pages the host "
                                "still owns (the PR-1 _own_device_state "
                                "corruption class)",
                            )
                yield from self._read_after(model, call, arg, key, callee, argnum)

    def _read_after(self, model, call, arg, key, callee, argnum):
        stmt = statement_of(call)
        # Rebound by the same statement (the canonical
        # ``state, m = step(state, ...)`` idiom)?
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for leaf in flatten_targets(t):
                    if ref_key(leaf) == key:
                        return
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            if ref_key(stmt.target) == key:
                return
        fn = enclosing_scope(call)
        if fn is None or isinstance(fn, ast.Module):
            return
        stmt_end = getattr(stmt, "end_lineno", stmt.lineno)
        rebind_line = None
        for node in ast.walk(fn):
            k = None
            if isinstance(node, (ast.Name, ast.Attribute)):
                k = ref_key(node)
            if k != key or node.lineno <= stmt_end:
                continue
            if isinstance(getattr(node, "ctx", None), ast.Store):
                if rebind_line is None or node.lineno < rebind_line:
                    rebind_line = node.lineno
        for node in ast.walk(fn):
            k = None
            if isinstance(node, (ast.Name, ast.Attribute)):
                k = ref_key(node)
            if k != key or node.lineno <= stmt_end:
                continue
            if not isinstance(getattr(node, "ctx", None), ast.Load):
                continue
            # Attribute loads that are just the base of a store
            # (``x.y = ...`` loads ``x``) still count as uses of x only,
            # and ref_key already separates the two.
            if rebind_line is not None and node.lineno >= rebind_line:
                continue
            name = key[1] if key[0] == "local" else f"self.{key[1]}"
            yield self.finding(
                model,
                node,
                f"`{name}` is read here after being donated to `{callee}` "
                f"(argument {argnum} at line {call.lineno}) — donated "
                "buffers are invalidated by the call; rebind the result "
                "to the same name or copy before donating",
            )
            return  # one finding per donation site is enough
