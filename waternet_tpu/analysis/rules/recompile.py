"""R004 — recompile-hazard.

XLA compilation is the single most expensive host-side event in this
codebase (the tier-1 suite ships a persistent compile cache just to
contain it), and a step function that silently recompiles mid-epoch
erases every throughput number the benches report. Three statically
detectable ways to cause that:

* **unhashable static argument** — a call site passes a list/dict/set
  literal at a ``static_argnums``/``static_argnames`` position; jax
  raises at best, and at worst (pre-0.4 semantics, wrapper layers) the
  cache misses on every call;
* **jit under a loop** — ``jax.jit(fn)`` evaluated inside a ``for``/
  ``while`` body builds a *fresh* callable (fresh cache) each iteration,
  recompiling every time;
* **Python branch on a traced value** — ``if x > 0:`` inside a jitted
  function where ``x`` is a traced (non-static) parameter raises a
  ``TracerBoolConversionError`` at trace time, or — when the branch sits
  behind a shape-dependent guard — forces one compile per taken path.
  ``is None`` checks and attribute accesses (``x.shape``, ``x.ndim``,
  ``x.dtype``) are static under tracing and stay exempt.
"""

from __future__ import annotations

import ast
from typing import Iterator

from waternet_tpu.analysis.core import (
    Finding,
    JIT_WRAPPERS,
    LOOP_NODES,
    ModuleModel,
    SCOPE_NODES,
    parent,
)
from waternet_tpu.analysis.registry import Rule, register

_UNHASHABLE = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.SetComp)
#: Builtins whose result on a traced array is static (safe to branch on).
_STATIC_FUNCS = {"len", "isinstance", "hasattr", "getattr", "callable"}


def _is_none_check(test: ast.AST) -> bool:
    return isinstance(test, ast.Compare) and all(
        isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
    )


def _traced_name_in(test: ast.AST, traced: set):
    """A bare traced-parameter Name (or subscript of one) inside a branch
    test, skipping static contexts: attribute roots (``x.shape``),
    ``len(x)``-style static builtins, and ``is None`` comparisons."""
    if _is_none_check(test):
        return None
    for node in ast.walk(test):
        if not (isinstance(node, ast.Name) and node.id in traced):
            continue
        p = parent(node)
        if isinstance(p, ast.Attribute) and p.value is node:
            continue  # x.shape / x.ndim / x.dtype are static
        if (
            isinstance(p, ast.Call)
            and isinstance(p.func, ast.Name)
            and p.func.id in _STATIC_FUNCS
        ):
            continue
        if isinstance(p, ast.Compare) and all(
            isinstance(op, (ast.Is, ast.IsNot)) for op in p.ops
        ):
            continue
        return node
    return None


@register
class RecompileHazard(Rule):
    id = "R004"
    name = "recompile-hazard"
    description = (
        "jitted callables whose static args are unhashable, jit applied "
        "inside a loop, or Python control flow branching on traced values"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        yield from self._unhashable_static(model)
        yield from self._jit_in_loop(model)
        yield from self._traced_branch(model)

    def _unhashable_static(self, model) -> Iterator[Finding]:
        for call in ast.walk(model.tree):
            if not isinstance(call, ast.Call):
                continue
            info = model.jit_info_for_call(call)
            if info is None:
                continue
            nums, names = model.static_positions(info)
            if not nums and not names:
                continue
            callee = info.binding or "jitted callable"
            for pos in nums:
                if pos < len(call.args) and isinstance(call.args[pos], _UNHASHABLE):
                    yield self.finding(
                        model,
                        call.args[pos],
                        f"static argument {pos} of `{callee}` is an "
                        "unhashable literal — static args are cache keys "
                        "and must be hashable; pass a tuple (or mark the "
                        "arg non-static)",
                    )
            for kwarg in call.keywords:
                if kwarg.arg in names and isinstance(kwarg.value, _UNHASHABLE):
                    yield self.finding(
                        model,
                        kwarg.value,
                        f"static argument `{kwarg.arg}` of `{callee}` is an "
                        "unhashable literal — static args are cache keys "
                        "and must be hashable; pass a tuple (or mark the "
                        "arg non-static)",
                    )

    def _jit_in_loop(self, model) -> Iterator[Finding]:
        for call in ast.walk(model.tree):
            if not (
                isinstance(call, ast.Call)
                and model.resolve(call.func) in JIT_WRAPPERS
            ):
                continue
            node = call
            while True:
                anc = parent(node)
                if anc is None or isinstance(anc, SCOPE_NODES):
                    break
                if isinstance(anc, LOOP_NODES) and node not in (
                    getattr(anc, "iter", None),
                    getattr(anc, "test", None),
                ):
                    yield self.finding(
                        model,
                        call,
                        "jax.jit applied inside a loop builds a fresh "
                        "callable (and compile cache) every iteration — "
                        "hoist the jit out of the loop",
                    )
                    break
                node = anc

    def _traced_branch(self, model) -> Iterator[Finding]:
        for fn, info in model.jitted_defs.items():
            if isinstance(fn, ast.Lambda):
                continue  # lambdas can't contain statements
            params = [a.arg for a in fn.args.args]
            nums, names = model.static_positions(info)
            traced = {
                p
                for i, p in enumerate(params)
                if i not in nums and p not in names and p != "self"
            }
            if not traced:
                continue
            for node in ast.walk(fn):
                if isinstance(node, (ast.If, ast.While, ast.IfExp, ast.Assert)):
                    test = node.test
                    hit = _traced_name_in(test, traced)
                    if hit is None:
                        continue
                    kind = type(node).__name__.lower()
                    yield self.finding(
                        model,
                        test,
                        f"`{kind}` branches on traced parameter "
                        f"`{hit.id}` inside jitted "
                        f"`{info.binding or fn.name}` — Python control "
                        "flow on traced values fails at trace time or "
                        "recompiles per branch; use jnp.where / "
                        "lax.cond, or mark the argument static",
                    )
