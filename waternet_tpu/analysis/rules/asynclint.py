"""R201–R205 — the asynclint event-loop rule family (docs/LINT.md).

Five rules over the :mod:`waternet_tpu.analysis.asyncio_model` model:

* **R201 blocking-call-in-coroutine** — a call reached on the event
  loop inside an ``async def`` that blocks the thread (``time.sleep``,
  cv2 codec work, lock ``.acquire()``, ``queue.get()``, file/socket
  I/O, ``Future.result()``, host syncs) or may block transitively
  through the repo-wide may-block fixpoint — without an executor wrap
  (project-scope: the fixpoint crosses modules).
* **R202 fire-and-forget-task** — a ``create_task``/``ensure_future``
  whose result is neither stored nor awaited (the loop holds only a
  weak reference: GC can cancel it mid-flight), plus a bare un-awaited
  call of a known coroutine function.
* **R203 cross-thread-loop-access** — loop-only methods or loop-future
  ``set_result`` reached from the off-loop closure (thread targets,
  executor workers, done-callbacks) without ``call_soon_threadsafe``
  (project-scope: the closure crosses modules).
* **R204 await-under-threading-lock** — an ``await`` while lexically
  holding a ``threading.Lock``/``RLock``/etc.: the suspension point
  keeps the lock held for an unbounded time, stalling every thread
  contending for it and inverting against the R102 lock graph.
  ``asyncio`` locks are exempt — suspending under them is their point.
* **R205 swallowed-cancellation** — an ``except`` inside a coroutine
  catching ``CancelledError`` / ``BaseException`` / everything (bare)
  without re-raising: cancellation is how disconnect cleanup and drain
  propagate, and eating it leaves the task running. The cancel-and-reap
  idiom (``t.cancel()`` then ``try: await t except CancelledError:
  pass``) is recognized and exempt.

Same precision-first stance as R001–R105: unresolvable receivers are
skipped, not guessed, because tier-1 pins the tree at zero unsuppressed
findings and a noisy rule would be suppressed into uselessness.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from waternet_tpu.analysis.asyncio_model import AsyncioModel, AsyncProject
from waternet_tpu.analysis.core import (
    Finding,
    ModuleModel,
    ancestors,
    parent,
)
from waternet_tpu.analysis.registry import Rule, register

_FUNCTION_NODES = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)

#: Exception names that catch cancellation when named in an ``except``.
_CANCEL_CATCHERS = {
    "asyncio.CancelledError",
    "concurrent.futures.CancelledError",
    "CancelledError",
    "BaseException",
}


def _nearest_function(node: ast.AST):
    for anc in ancestors(node):
        if isinstance(anc, _FUNCTION_NODES):
            return anc
    return None


@register
class BlockingCallInCoroutine(Rule):
    id = "R201"
    name = "blocking-call-in-coroutine"
    description = (
        "a coroutine calls something that blocks the loop thread "
        "(sleep, codec work, lock acquire, queue get, file/socket I/O, "
        "Future.result, host sync — directly or through the may-block "
        "fixpoint) without an executor wrap"
    )
    scope = "project"

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        yield from self.check_project([model])

    def check_project(self, models) -> Iterator[Finding]:
        project = AsyncProject(models)
        for path, node, message in project.blocking_call_findings():
            yield Finding(
                rule=self.id,
                path=path,
                line=node.lineno,
                col=node.col_offset,
                message=message,
            )


@register
class FireAndForgetTask(Rule):
    id = "R202"
    name = "fire-and-forget-task"
    description = (
        "create_task/ensure_future result neither stored nor awaited "
        "(the loop keeps only a weak ref — GC can cancel the task), or "
        "a coroutine function called bare without await"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        am = AsyncioModel(model)
        coro_names = {c.name for c in am.coroutines}
        for node in ast.walk(model.tree):
            if not isinstance(node, ast.Call):
                continue
            if not isinstance(parent(node), ast.Expr):
                continue  # stored, awaited, or part of an expression
            resolved = model.resolve(node.func) or ""
            is_spawn = resolved in {"asyncio.create_task", "asyncio.ensure_future"}
            if not is_spawn and isinstance(node.func, ast.Attribute):
                is_spawn = (
                    node.func.attr in {"create_task", "ensure_future"}
                    and am.looks_like_loop(node.func.value)
                )
            if is_spawn:
                yield self.finding(
                    model, node,
                    "task is neither stored nor awaited — the loop holds "
                    "only a weak reference, so GC can cancel it mid-flight; "
                    "keep the handle and reap it",
                )
                continue
            # bare un-awaited coroutine call: `self.flush()` where flush
            # is an async def builds a coroutine object and drops it.
            name: Optional[str] = None
            if isinstance(node.func, ast.Name):
                name = node.func.id
            elif isinstance(node.func, ast.Attribute):
                name = node.func.attr
            if name in coro_names and _nearest_function(node) is not None:
                yield self.finding(
                    model, node,
                    f"'{name}' is a coroutine function: calling it bare "
                    "builds a coroutine object and drops it — await it or "
                    "hand it to create_task",
                )


@register
class CrossThreadLoopAccess(Rule):
    id = "R203"
    name = "cross-thread-loop-access"
    description = (
        "a function in the off-loop closure (Thread target, executor "
        "worker, done-callback) touches the loop or a loop future "
        "without call_soon_threadsafe"
    )
    scope = "project"

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        yield from self.check_project([model])

    def check_project(self, models) -> Iterator[Finding]:
        project = AsyncProject(models)
        for path, node, message in project.off_loop_findings():
            yield Finding(
                rule=self.id,
                path=path,
                line=node.lineno,
                col=node.col_offset,
                message=message,
            )


@register
class AwaitUnderThreadingLock(Rule):
    id = "R204"
    name = "await-under-threading-lock"
    description = (
        "an await suspends while holding a threading.* lock — the lock "
        "stays held for an unbounded suspension, stalling every "
        "contending thread (asyncio locks are exempt)"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        am = AsyncioModel(model)
        for node in ast.walk(model.tree):
            if not isinstance(node, ast.Await):
                continue
            for key in sorted(am.cm.held_locks(node)):
                factory = am.lock_factory.get(key)
                if factory is None or not factory.startswith("threading."):
                    continue  # asyncio lock, or provenance unknown: skip
                yield self.finding(
                    model, node,
                    f"await while holding {key.display} (built by "
                    f"{factory}): the suspension keeps the lock held for "
                    "an unbounded time — release before awaiting, or use "
                    "asyncio.Lock",
                )


@register
class SwallowedCancellation(Rule):
    id = "R205"
    name = "swallowed-cancellation"
    description = (
        "an except inside a coroutine catches CancelledError/"
        "BaseException (or everything, bare) without re-raising — "
        "cancellation is how disconnect cleanup and drain propagate"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        for node in ast.walk(model.tree):
            if not isinstance(node, ast.ExceptHandler):
                continue
            if not isinstance(_nearest_function(node), ast.AsyncFunctionDef):
                continue
            caught = self._catches_cancellation(model, node)
            if caught is None:
                continue
            if self._reraises(node):
                continue
            if self._is_cancel_and_reap(node):
                continue
            yield self.finding(
                model, node,
                f"'except {caught}' in a coroutine swallows cancellation "
                "— re-raise CancelledError (or narrow the except) so "
                "disconnect cleanup and drain can propagate",
            )

    def _catches_cancellation(
        self, model: ModuleModel, handler: ast.ExceptHandler
    ) -> Optional[str]:
        """The display name of the cancellation-catching clause, or None."""
        if handler.type is None:
            return ""  # bare except — rendered as plain 'except'
        exprs = (
            handler.type.elts
            if isinstance(handler.type, ast.Tuple)
            else [handler.type]
        )
        for expr in exprs:
            resolved = model.resolve(expr)
            if resolved in _CANCEL_CATCHERS:
                return resolved
        return None

    def _reraises(self, handler: ast.ExceptHandler) -> bool:
        """Any ``raise`` in the handler body (not inside a nested def)."""
        todo = list(handler.body)
        while todo:
            node = todo.pop()
            if isinstance(node, ast.Raise):
                return True
            if isinstance(node, _FUNCTION_NODES):
                continue
            todo.extend(ast.iter_child_nodes(node))
        return False

    def _is_cancel_and_reap(self, handler: ast.ExceptHandler) -> bool:
        """The sanctioned reap idiom, exempt by shape::

            t.cancel()
            try:
                await t
            except (asyncio.CancelledError, ...):
                pass

        The coroutine cancelled its own child and awaits it purely to
        reap — swallowing the child's CancelledError is the contract.
        Requires exactly that shape: the statement before the try
        cancels the same name the try body awaits."""
        try_stmt = parent(handler)
        if not isinstance(try_stmt, ast.Try) or len(try_stmt.body) != 1:
            return False
        body_stmt = try_stmt.body[0]
        if not (
            isinstance(body_stmt, ast.Expr)
            and isinstance(body_stmt.value, ast.Await)
            and isinstance(body_stmt.value.value, ast.Name)
        ):
            return False
        awaited = body_stmt.value.value.id
        holder = parent(try_stmt)
        for field in ("body", "orelse", "finalbody"):
            stmts = getattr(holder, field, None)
            if stmts and try_stmt in stmts:
                i = stmts.index(try_stmt)
                if i == 0:
                    return False
                prev = stmts[i - 1]
                return (
                    isinstance(prev, ast.Expr)
                    and isinstance(prev.value, ast.Call)
                    and isinstance(prev.value.func, ast.Attribute)
                    and prev.value.func.attr == "cancel"
                    and isinstance(prev.value.func.value, ast.Name)
                    and prev.value.func.value.id == awaited
                )
        return False
