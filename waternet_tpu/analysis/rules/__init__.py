"""jaxlint rule modules — importing this package registers every rule.

One module per rule, one class per module; see docs/LINT.md for the rule
catalogue and waternet_tpu/analysis/registry.py for the registration
contract.
"""

from waternet_tpu.analysis.rules import (  # noqa: F401
    asynclint,
    concurrency,
    donation,
    hostsync,
    recompile,
    rng,
    tracerleak,
)
