"""R005 — tracer-leak.

Code inside a jit-compiled function runs once, at trace time, with
abstract tracers in place of arrays. Writing a value to anything that
outlives the trace — ``self``, a global, a closure-captured container —
leaks a tracer: at best ``jax`` raises ``UnexpectedTracerError`` at the
later use; at worst the stored object silently holds a stale trace-time
value while every cached call skips the store entirely (the side effect
replays only on recompile). Both failure modes are nondeterministic from
the caller's point of view, which is what makes them worth a static rule.

The rule scans every function this module statically knows to be jitted
(decorated, or wrapped via ``jax.jit(fn)`` / ``self.step = jax.jit(fn)``)
and flags:

* assignments to any attribute (``self.x = ...``, ``obj.attr = ...``);
* assignments through ``global`` / ``nonlocal`` declarations;
* mutation of names not bound locally: subscript stores
  (``cache[k] = v``) and mutating method calls (``.append``, ``.add``,
  ``.update``, ...) on closure or module-level objects.

Locally-created containers are fine — building a dict of metrics inside
the step and returning it is the engine's own idiom.
"""

from __future__ import annotations

import ast
from typing import Iterator

from waternet_tpu.analysis.core import (
    Finding,
    ModuleModel,
    SCOPE_NODES,
    flatten_targets,
)
from waternet_tpu.analysis.registry import Rule, register

_MUTATORS = {
    "append",
    "extend",
    "insert",
    "add",
    "update",
    "setdefault",
    "pop",
    "remove",
    "clear",
    "__setitem__",
}


def _local_names(fn) -> set:
    """Names bound in ``fn``'s own scope (params, assignments, loop and
    with targets, comprehension targets) — stores to these are trace-local
    and safe."""
    names = set()
    if not isinstance(fn, ast.Lambda):
        args = fn.args
        for a in (
            args.args + args.posonlyargs + args.kwonlyargs
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        ):
            names.add(a.arg)
    for node in ast.walk(fn):
        if isinstance(node, ast.Name) and isinstance(
            node.ctx, (ast.Store, ast.Del)
        ):
            names.add(node.id)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            names.add(node.name)
    return names


@register
class TracerLeak(Rule):
    id = "R005"
    name = "tracer-leak"
    description = (
        "a traced value is stored into self/globals/closures that "
        "outlive the trace"
    )

    def check(self, model: ModuleModel) -> Iterator[Finding]:
        for fn, info in model.jitted_defs.items():
            if isinstance(fn, ast.Lambda):
                continue
            name = info.binding or fn.name
            locals_ = _local_names(fn)
            declared = set()  # global/nonlocal names in any nested block
            for node in ast.walk(fn):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    declared.update(node.names)
            for node in ast.walk(fn):
                yield from self._check_node(model, fn, name, locals_, declared, node)

    def _check_node(self, model, fn, name, locals_, declared, node):
        # Assignments: attribute targets always leak; Name targets leak
        # when routed through global/nonlocal; subscript stores leak when
        # the base container isn't a local.
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                yield from self._check_target(model, name, locals_, declared, t)
        elif isinstance(node, ast.Call):
            f = node.func
            if (
                isinstance(f, ast.Attribute)
                and f.attr in _MUTATORS
                and isinstance(f.value, ast.Name)
                and (f.value.id not in locals_ or f.value.id in declared)
            ):
                yield self.finding(
                    model,
                    node,
                    f"`.{f.attr}()` mutates `{f.value.id}`, which is not "
                    f"local to jitted `{name}` — the mutation happens at "
                    "trace time only (skipped on cached calls) and can "
                    "leak a tracer into an object that outlives the "
                    "trace; return the value instead",
                )

    def _check_target(self, model, name, locals_, declared, target):
        for leaf in flatten_targets(target):
            if isinstance(leaf, ast.Attribute):
                yield self.finding(
                    model,
                    leaf,
                    f"assignment to attribute `{ast.unparse(leaf)}` inside "
                    f"jitted `{name}` stores a trace-time value on an "
                    "object that outlives the trace (runs only when "
                    "tracing, leaks a tracer) — return the value and "
                    "store it outside the jitted function",
                )
            elif isinstance(leaf, ast.Subscript):
                base = leaf.value
                if isinstance(base, ast.Name) and (
                    base.id not in locals_ or base.id in declared
                ):
                    yield self.finding(
                        model,
                        leaf,
                        f"subscript store into non-local `{base.id}` inside "
                        f"jitted `{name}` mutates state that outlives the "
                        "trace — the write happens at trace time only and "
                        "can leak a tracer",
                    )
            elif isinstance(leaf, ast.Name) and leaf.id in declared:
                yield self.finding(
                    model,
                    leaf,
                    f"assignment to global/nonlocal `{leaf.id}` inside "
                    f"jitted `{name}` stores a trace-time value beyond the "
                    "trace (and is skipped entirely on cached calls)",
                )
