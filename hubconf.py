"""torch.hub entry point — API-compatible with the reference's hubconf
(`/root/reference/hubconf.py:37-96`), backed by the JAX implementation.

    preprocess, postprocess, model = torch.hub.load(
        "<this-repo>", "waternet", source="github")   # or source="local"

Returns the same ``(preprocess, postprocess, model)`` triple with the same
``(rgb, wb, he, gc)`` ordering; arrays are NHWC jax arrays rather than NCHW
torch tensors (postprocess still yields NHWC uint8 numpy). torch.hub is only
the loader here — the dependency list is jax, not torch.
"""

dependencies = ["jax", "flax", "numpy", "cv2"]


def waternet(pretrained: bool = True, weights=None, device=None, download=False):
    """Build WaterNet. ``device`` is accepted for signature compatibility
    with the reference and ignored (jax manages placement). ``download=True``
    opts in to the reference's hash-verified pretrained fetch when no local
    weights resolve (the reference downloads implicitly; here egress is
    opt-in)."""
    import sys
    from pathlib import Path

    # torch.hub puts this dir on sys.path only for the entry-point call;
    # make the package importable without permanently shadowing user modules
    # (the repo root holds generically named CLIs like inference.py).
    repo = str(Path(__file__).resolve().parent)
    added = repo not in sys.path
    if added:
        sys.path.insert(0, repo)
    try:
        from waternet_tpu.hub import waternet as _waternet
    finally:
        if added and repo in sys.path:
            sys.path.remove(repo)

    return _waternet(pretrained=pretrained, weights=weights, download=download)


def waternet_student(weights, device=None):
    """Build the fast-tier CAN student (docs/SERVING.md "Quality tiers"):
    returns ``(preprocess, postprocess, model)`` where ``model(x)`` takes
    the raw RGB tensor alone — the student consumes no enhanced variants.
    ``weights`` must name a distilled student checkpoint (a ``train.py
    --distill`` product; WaterNet weights are refused with a named
    tier-mismatch error). ``device`` is accepted for signature symmetry
    with :func:`waternet` and ignored."""
    import sys
    from pathlib import Path

    repo = str(Path(__file__).resolve().parent)
    added = repo not in sys.path
    if added:
        sys.path.insert(0, repo)
    try:
        from waternet_tpu.hub import waternet_student as _student
    finally:
        if added and repo in sys.path:
            sys.path.remove(repo)

    return _student(weights)
