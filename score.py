"""Scoring CLI: evaluate a checkpoint on the UIEB validation split.

Behavior parity with the reference scorer (`/root/reference/score.py:84-177`):
same seed-0 800/90 split (reproduced exactly via the torch RNG stream — see
:func:`waternet_tpu.data.uieb.reference_split`), same 112x112 default eval
resolution, required ``--weights``, pprinted metric dict (mse / ssim / psnr /
perceptual_loss averaged equal-weight over val minibatches).

Notes carried over from the survey of the reference:
* it scores only the 90-image validation split, despite the README calling
  it "the UIEB dataset" — we keep that but make it explicit via ``--split``;
* the reference's eval accumulates perceptual_loss with ``=`` instead of
  ``+=`` (`/root/reference/score.py` copy of `train.py:71`), i.e. it reports
  only the last batch's value divided by the batch count. That defect is
  fixed here; pass ``--bug-compat-perceptual`` to reproduce the reference
  number exactly.
* host (cv2) preprocessing is the default for parity-grade numbers; use
  ``--device-preprocess`` for speed.
* **deliberate deviation**: the reference's val dataloader inherits
  UIEBDataset's default *random* flip/rot90 augmentation during eval
  (default ``A.Compose`` at `/root/reference/waternet/training_utils.py:72-78`,
  applied at `:109-111`, inherited by `score.py:135-143`'s val loader), so
  its reported numbers are stochastic under a
  fixed checkpoint. This scorer evaluates unaugmented — deterministic and
  the standard practice — so values will differ slightly from a reference
  run even on identical weights; expect agreement in distribution, not
  run-for-run.
"""

from __future__ import annotations

import argparse
import json
import time
from pathlib import Path
from pprint import pprint

import numpy as np


# Shared with the serving layer's bucket auto-derivation
# (waternet_tpu/serving/bucketing.py); kept under its historical private
# name here — this CLI is the parser's original home and its tests live
# in tests/test_score.py.
from waternet_tpu.utils.imagemeta import image_shape as _image_shape  # noqa: E402


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="Score WaterNet weights on UIEB")
    p.add_argument("--weights", type=str, required=True, help="Checkpoint (.npz native or reference .pt)")
    p.add_argument("--batch-size", type=int, default=16)
    p.add_argument("--height", type=int, default=112)
    p.add_argument("--width", type=int, default=112)
    p.add_argument("--data-root", type=str, default="data")
    p.add_argument("--val-size", type=int, default=90)
    p.add_argument("--split", type=str, default="val", choices=["val", "train", "all"],
                   help="Which part of the seed-0 split to score (reference: val)")
    p.add_argument("--allow-nonreference-split", action="store_true",
                   help="Proceed even when the reference torch seed-0 split "
                        "cannot be reproduced (non-890 dataset without torch); "
                        "scores are then NOT comparable to the reference")
    p.add_argument("--vgg-weights", type=str, help="VGG19 weights for perceptual metric")
    p.add_argument("--precision", type=str, default="fp32", choices=["bf16", "fp32"])
    p.add_argument("--device-preprocess", action="store_true")
    p.add_argument("--workers", type=int, default=2, metavar="N",
                   help="Overlapped input pipeline (docs/PIPELINE.md): N "
                        "worker threads load + preprocess eval batches ahead "
                        "of the device. 0 = synchronous; metric values are "
                        "identical either way")
    p.add_argument("--bug-compat-perceptual", action="store_true",
                   help="Reproduce the reference's perceptual_loss accumulation bug")
    p.add_argument("--json-out", type=str, help="Also write metrics to this JSON file")
    p.add_argument(
        "--epochs", type=int, default=None,
        help="(Compat) accepted and ignored: the reference scorer inherited "
             "this flag from train.py and never uses it (`score.py:99-100`)",
    )
    p.add_argument(
        "--seed", type=int, default=None,
        help="(Compat) in the reference, a non-None seed reseeds torch's "
             "global RNG before random_split, silently changing WHICH 90 "
             "images count as val (`score.py:132-133,141`); this scorer "
             "always evaluates the canonical seed-0 split and warns if a "
             "different seed is requested",
    )
    p.add_argument(
        "--raw-dir", type=str,
        help="Score a directory of raw images with NO references (e.g. UIEB "
        "challenging-60) using no-reference metrics (UCIQE/UIQM), before and "
        "after enhancement, at native resolution (images batched by shape). "
        "Paired metrics are skipped in this mode.",
    )
    p.add_argument(
        "--nr-resize", action="store_true",
        help="(with --raw-dir) resize raw images to --height x --width "
        "before scoring instead of native resolution. UCIQE/UIQM are "
        "resolution-sensitive (UISM/UIConM are block-based), so resized "
        "values are NOT comparable to native-resolution literature numbers "
        "— use only to compare two checkpoints at a fixed size cheaply.",
    )
    return p.parse_args(argv)


def score_no_reference(args):
    """Challenging-60-style scoring: no ground truth exists, so report
    UCIQE/UIQM on the raw inputs and on the enhanced outputs.

    Default is NATIVE resolution, images grouped by shape so each distinct
    shape compiles one executable and same-shaped images run in device
    batches: UCIQE/UIQM are resolution-sensitive (block-based UISM/UIConM),
    so numbers at a forced resize are not comparable to native-resolution
    literature values. ``--nr-resize`` restores the fixed-size behavior
    (and its caveat) for cheap checkpoint-to-checkpoint comparison.
    """
    import sys
    from pathlib import Path

    import cv2
    import jax.numpy as jnp
    import numpy as np

    from waternet_tpu.inference_engine import InferenceEngine
    from waternet_tpu.parallel.mesh import pad_to_multiple
    from waternet_tpu.training.metrics_nr import uciqe_batch, uiqm_batch

    files = sorted(
        p for p in Path(args.raw_dir).glob("*")
        if p.suffix.lower() in (".png", ".jpg", ".jpeg", ".bmp")
    )
    if not files:
        raise FileNotFoundError(f"no images found in {args.raw_dir}")
    engine = InferenceEngine(
        weights=args.weights,
        device_preprocess=args.device_preprocess,
        dtype=jnp.bfloat16 if args.precision == "bf16" else jnp.float32,
    )

    # Pass 1: group file PATHS by shape so each distinct shape compiles one
    # executable (host memory stays bounded at one decoded batch — raw-890
    # at native resolution would be gigabytes if held at once). Shapes come
    # from _image_shape's header-only read, NOT a full decode: the old
    # cv2.imread here decoded every pixel twice per run. Insertion-ordered,
    # so output order is deterministic; with --nr-resize everything lands
    # in one group and no file is opened at all in this pass.
    groups: dict = {}
    for f in files:
        if args.nr_resize:
            shape = (args.height, args.width, 3)
        else:
            shape = _image_shape(f)
            if shape is None:  # unknown container/corrupt header: decode
                bgr = cv2.imread(str(f))
                if bgr is None:
                    print(f"Skipping unreadable image: {f}", file=sys.stderr)
                    continue
                shape = bgr.shape
        groups.setdefault(shape, []).append(f)

    sums = {"uciqe_raw": 0.0, "uiqm_raw": 0.0, "uciqe_enhanced": 0.0, "uiqm_enhanced": 0.0}
    n_scored = 0
    # Worklist so header/decoder shape disagreements (cv2.imread applies
    # EXIF orientation, rotating some JPEGs relative to their SOF header)
    # can be re-queued under the DECODED shape and scored in a second
    # sweep; decoded shapes are deterministic, so the re-queue converges.
    work = list(groups.items())
    regrouped: dict = {}
    while work:
        shape, paths = work.pop(0)
        for start in range(0, len(paths), args.batch_size):
            chunk = paths[start : start + args.batch_size]
            raws = []
            for f in chunk:
                bgr = cv2.imread(str(f))
                if bgr is None:  # header parsed but pixels don't decode
                    print(f"Skipping unreadable image: {f}", file=sys.stderr)
                    continue
                if args.nr_resize:
                    bgr = cv2.resize(bgr, (args.width, args.height))
                elif bgr.shape != shape:
                    regrouped.setdefault(bgr.shape, []).append(f)
                    continue
                raws.append(cv2.cvtColor(bgr, cv2.COLOR_BGR2RGB))
            if not raws:
                continue
            if len(raws) < args.batch_size and len(paths) > args.batch_size:
                # Tail of a multi-batch group: pad so it reuses the full
                # batch's compiled executable instead of compiling anew.
                raw, n_real = pad_to_multiple(np.stack(raws), args.batch_size)
            else:
                # Group fits in one batch: padding would only multiply
                # compute (its shape compiles exactly one program either
                # way, the common case for unique-resolution directories).
                raw, n_real = np.stack(raws), len(raws)
            out = engine.enhance(raw)
            for key, batch in (
                ("uciqe_raw", uciqe_batch(jnp.asarray(raw))),
                ("uiqm_raw", uiqm_batch(jnp.asarray(raw))),
                ("uciqe_enhanced", uciqe_batch(jnp.asarray(out))),
                ("uiqm_enhanced", uiqm_batch(jnp.asarray(out))),
            ):
                sums[key] += float(np.asarray(batch)[:n_real].sum())
            n_scored += n_real
        if not work and regrouped:
            work, regrouped = list(regrouped.items()), {}
    if n_scored == 0:
        raise FileNotFoundError(f"no readable images in {args.raw_dir}")
    return {k: v / n_scored for k, v in sums.items()} | {"images": n_scored}


def main(argv=None):
    args = parse_args(argv)
    t0 = time.perf_counter()

    from waternet_tpu.utils.platform import ensure_platform

    ensure_platform()
    from waternet_tpu.utils.platform import enable_compile_cache

    enable_compile_cache()

    if args.seed not in (None, 0):
        import warnings

        warnings.warn(
            f"--seed {args.seed} is accepted for reference CLI compatibility "
            "only: this scorer always evaluates the canonical seed-0 split "
            "(the reference would have moved images between train and val).",
            RuntimeWarning,
            stacklevel=1,
        )

    if args.raw_dir:
        metrics = score_no_reference(args)
        pprint(metrics)
        print(f"Scored {metrics['images']} raw images in {time.perf_counter() - t0:.1f}s")
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(metrics, f, indent=2)
        return

    from waternet_tpu.data.uieb import UIEBDataset, reference_split
    from waternet_tpu.hub import resolve_weights
    from waternet_tpu.models.vgg import resolve_vgg_params
    from waternet_tpu.training.trainer import TrainConfig, TrainingEngine

    data_root = Path(args.data_root)
    dataset = UIEBDataset(
        data_root / "raw-890",
        data_root / "reference-890",
        im_height=args.height,
        im_width=args.width,
    )
    # Scoring on a non-reference split silently produces wrong-but-plausible
    # numbers (train/val leakage for reference-trained checkpoints), so a
    # fallback-split warning here is a hard error unless explicitly allowed.
    import warnings

    from waternet_tpu.data.uieb import NonReferenceSplitWarning

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always", RuntimeWarning)
        train_idx, val_idx = reference_split(len(dataset), n_val=args.val_size)
    if any(issubclass(w.category, NonReferenceSplitWarning) for w in caught):
        if not args.allow_nonreference_split:
            raise SystemExit(
                "score.py: refusing to score on a non-reference split "
                "(torch unavailable and dataset is not the canonical 890 "
                "pairs). Re-run with --allow-nonreference-split to proceed "
                "anyway; the numbers will not be comparable to the reference."
            )
    for w in caught:  # replay everything recorded, fatal or not
        warnings.warn_explicit(w.message, w.category, w.filename, w.lineno)
    indices = {"val": val_idx, "train": train_idx,
               "all": np.arange(len(dataset))}[args.split]

    params = resolve_weights(args.weights)
    if params is None:
        raise FileNotFoundError(f"could not load weights from {args.weights}")

    config = TrainConfig(
        batch_size=args.batch_size,
        im_height=args.height,
        im_width=args.width,
        precision=args.precision,
        host_preprocess=not args.device_preprocess,
        augment=False,
    )
    engine = TrainingEngine(
        config, params=params, vgg_params=resolve_vgg_params(args.vgg_weights)
    )

    if args.bug_compat_perceptual:
        # Bug-compat accumulates per-batch on the host; stays synchronous.
        metrics = _eval_bug_compat(engine, dataset, indices, args.batch_size)
    elif args.workers > 0:
        metrics = engine.eval_epoch_pipelined(
            dataset, indices, workers=args.workers
        )
        # The scorer's contract output is the parity-grade metric dict;
        # keep the pipeline instrumentation out of it (train.py and
        # bench.py are where those numbers are reported).
        metrics = {
            k: v for k, v in metrics.items() if not k.startswith("pipeline_")
        }
    else:
        metrics = engine.eval_epoch(
            dataset.batches(indices, args.batch_size, shuffle=False)
        )

    pprint(metrics)
    print(f"Scored {len(indices)} images in {time.perf_counter() - t0:.1f}s")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(metrics, f, indent=2)


def _eval_bug_compat(engine, dataset, indices, batch_size):
    """Reference `train.py:71`: perceptual_loss is overwritten per batch, so
    the reported value is last_batch_perceptual / n_batches."""
    sums = {"mse": 0.0, "ssim": 0.0, "psnr": 0.0}
    last_perc = 0.0
    count = 0
    for raw, ref in dataset.batches(indices, batch_size, shuffle=False):
        raw, ref, n_real = engine._pad_batch(raw, ref)
        if engine.config.host_preprocess:
            tensors = engine._host_preprocess_batch(raw, ref, None)
            m = engine.eval_step_pre(engine.state, *tensors, n_real)
        else:
            import jax.numpy as jnp

            m = engine.eval_step(
                engine.state, jnp.asarray(raw), jnp.asarray(ref), n_real
            )
        for k in sums:
            sums[k] += float(m[k])
        last_perc = float(m["perceptual_loss"])
        count += 1
    out = {k: v / max(count, 1) for k, v in sums.items()}
    out["perceptual_loss"] = last_perc / max(count, 1)
    return out


if __name__ == "__main__":
    main()
